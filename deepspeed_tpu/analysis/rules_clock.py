"""DT002 — clock-injection.

Every latency decision in the serving tier — TTL cancellation, hard
deadlines, hedged dispatch, hung-replica strikes, TTFT/TPOT histograms —
runs off an injectable clock (`ServingEngine(clock=...)`,
`ServingRouter(clock=...)`), because the PR 9 chaos harness proves the
self-healing behavior by swapping that clock for a `ChaosClock`. A
direct `time.time()`/`time.monotonic()`/`time.perf_counter()` CALL in
`serving/` or `inference/` bypasses the injection point: the code under
it becomes untestable under chaos and silently exempt from the
deadline/hedging proofs.

The sanctioned default-binding idiom does not fire — it references the
function without calling it::

    self._clock = clock if clock is not None else time.monotonic

Out of scope by design: `telemetry/` (it IS the wall-clock layer),
checkpointing, launchers. Known evasion this heuristic cannot see:
aliasing (`t = time.time; t()`) — the fixture tests document it.
"""

from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import Rule, register
from deepspeed_tpu.analysis.jaxmodel import dotted

_WALL_CLOCKS = ("time", "monotonic", "perf_counter", "monotonic_ns",
                "perf_counter_ns", "time_ns")


@register
class ClockInjectionRule(Rule):
    id = "DT002"
    name = "clock-injection"
    description = (
        "direct wall-clock call in the serving tier — route through the "
        "injectable clock the chaos harness swaps")
    paths = ("deepspeed_tpu/serving/", "deepspeed_tpu/inference/")

    def check_module(self, ctx):
        findings = []
        # alias maps: `import time as t` and `from time import monotonic`
        module_aliases = set()
        fn_aliases = {}                      # local name -> time.<attr>
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        module_aliases.add(a.asname or a.name)
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name in _WALL_CLOCKS:
                        fn_aliases[a.asname or a.name] = f"time.{a.name}"
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name is None:
                continue
            hit = None
            parts = name.split(".")
            if (len(parts) == 2 and parts[0] in module_aliases
                    and parts[1] in _WALL_CLOCKS):
                hit = f"time.{parts[1]}"
            elif name in fn_aliases:
                hit = fn_aliases[name]
            if hit:
                findings.append(ctx.finding(
                    self.id, node, f"direct wall-clock call {hit}() — "
                    f"serving-tier code must read time through the "
                    f"injectable clock (`self._clock`), or the chaos "
                    f"harness cannot drive it"))
        return findings
