"""DT003 — donation-safety.

Every persistent jitted program in the stack donates its big buffer
(`donate_argnums`): the paged KV pool into the decode/prefill/verify
steps, the train state into the train step, the destination pool into
the handoff transplant. Donation lets XLA alias the update in place —
and makes the PYTHON-side argument a dead reference the moment the call
returns. Reading it afterwards is not an error on CPU (jax warns at
most); on TPU it can silently read clobbered memory: the classic
wrong-answer-no-crash bug.

The rule: a name (local or `self.attr`) passed at a donated argument
position of a known-donating callable (see jaxmodel.JitRegistry — direct
`jax.jit(..., donate_argnums=...)` bindings and factory returns) must
not be READ again in the same function scope unless it was rebound
first. The sanctioned idiom rebinds at the donation site itself::

    tok, self.pool = self._prefill_step(..., self.pool, ...)   # clean

Donating inside a loop without a same-statement rebind flags even when
the read is textually BEFORE the call — the back edge makes it a
read-after-donation on iteration two.

Blind spots (documented, not silent): donated subscripts
(`caches[i][0]`) and cross-module program handles are not tracked.
"""

from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import Rule, register
from deepspeed_tpu.analysis.jaxmodel import (
    JitRegistry, assign_target_names, dotted, iter_functions, loads_in,
    own_calls, statements_in_order)


def _reads_name(loaded: str, name: str) -> bool:
    return loaded == name or loaded.startswith(name + ".")


@register
class DonationSafetyRule(Rule):
    id = "DT003"
    name = "donation-safety"
    description = (
        "a buffer passed at a donated argument position of a jitted "
        "program is read again before being rebound — use-after-donation "
        "is silent wrong-answer territory on TPU")

    def check_module(self, ctx):
        registry = JitRegistry.collect(ctx.tree)
        if not any(p.donate for p in registry.programs.values()):
            return []
        findings = []
        for fn in iter_functions(ctx.tree):
            findings.extend(self._check_function(ctx, fn, registry))
        return findings

    def _check_function(self, ctx, fn, registry):
        findings = []
        stmts = statements_in_order(fn)
        # donated: name -> (donation stmt, loop depth at donation)
        donated = {}
        for stmt, depth in stmts:
            # 1) reads of still-donated names in this statement
            for loaded, node in loads_in(stmt):
                for name, (dsite, _dd) in list(donated.items()):
                    if dsite is stmt:
                        continue              # the donation call itself
                    if _reads_name(loaded, name):
                        findings.append(ctx.finding(
                            self.id, node,
                            f"'{name}' was donated to a jitted program "
                            f"at line {dsite.lineno} and is read again "
                            f"here without being rebound — the buffer "
                            f"is dead after donation"))
                        del donated[name]     # one report per donation
            # 2) donations made by this statement
            rebound = assign_target_names(stmt)
            new_donations = []
            for call in own_calls(stmt):
                prog = registry.lookup(call)
                if prog is None or not prog.donate:
                    continue
                for pos in prog.donate:
                    if pos < len(call.args):
                        name = dotted(call.args[pos])
                        if name is not None:
                            new_donations.append(name)
            # 3) rebinds clear old donations; a same-statement rebind of
            #    a new donation is the sanctioned `x, pool = f(pool)`
            for name in rebound:
                donated.pop(name, None)
            for name in new_donations:
                if name not in rebound:
                    donated[name] = (stmt, depth)
        # 4) loop back edges: a donation inside a loop, never rebound,
        #    where the SAME loop body also reads the name — iteration
        #    two reads a donated buffer even if the read is textually
        #    above the call
        for name, (dsite, ddepth) in donated.items():
            if ddepth == 0:
                continue
            loop = self._enclosing_loop(fn, dsite)
            if loop is None:
                continue
            # the donation statement itself counts: passing the name to
            # the program again next iteration IS the read-after-donation
            for stmt in ast.walk(loop):
                if not isinstance(stmt, ast.stmt):
                    continue
                for loaded, node in loads_in(stmt):
                    if _reads_name(loaded, name):
                        findings.append(ctx.finding(
                            self.id, node,
                            f"'{name}' is donated at line "
                            f"{dsite.lineno} inside this loop and never "
                            f"rebound — the next iteration reads a "
                            f"donated buffer"))
                        break
                else:
                    continue
                break
        return findings

    @staticmethod
    def _enclosing_loop(fn, target_stmt):
        """Innermost For/While in `fn` containing `target_stmt`."""
        best = None
        for node in ast.walk(fn):
            if isinstance(node, (ast.For, ast.While)):
                for ch in ast.walk(node):
                    if ch is target_stmt:
                        best = node       # ast.walk is outer-to-inner
                        break
        return best
