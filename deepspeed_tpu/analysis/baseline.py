"""Ratcheting baseline for `dstpu_lint`.

`lint_baseline.json` grandfathers findings that predate the linter (or a
new rule): a finding whose (rule, path, stripped-line-text) fingerprint
matches a baseline entry does not fail the build. The file is a RATCHET
— it may only shrink:

* `dstpu_lint` exits 1 on any NON-baselined finding; baselining it by
  hand means editing the checked-in JSON, which a reviewer sees.
* `dstpu_lint --baseline` rewrites the file as the INTERSECTION of the
  old baseline and the current findings — fixed findings fall out,
  new findings are refused (they stay failing).
* stale entries (baselined findings that no longer occur) also exit 1,
  with instructions to shrink — a rotting entry would silently
  grandfather the same finding if it were ever reintroduced.

Fingerprints use the source line TEXT, not the line NUMBER, so edits
elsewhere in a file do not churn the baseline; identical lines in one
file share an entry with a count.
"""

from __future__ import annotations

import json
import pathlib
from typing import Dict, List, Tuple

from deepspeed_tpu.analysis.core import Finding

BASELINE_NAME = "lint_baseline.json"
_HEADER = (
    "dstpu_lint ratcheting baseline — grandfathered findings that "
    "predate the rule that catches them. This file may only SHRINK: "
    "fix a finding and run `dstpu_lint --baseline` to drop its entry. "
    "New findings are never added here — fix them or suppress them "
    "with a reasoned `# dstpu: ignore[...]` pragma.")

Key = Tuple[str, str, str]            # (rule, path, snippet)


def default_path() -> pathlib.Path:
    return pathlib.Path(__file__).parent / BASELINE_NAME


def load(path=None) -> Dict[Key, int]:
    """Baseline entries as fingerprint -> grandfathered count. A missing
    file is an empty baseline."""
    p = pathlib.Path(path) if path else default_path()
    if not p.exists():
        return {}
    data = json.loads(p.read_text())
    out: Dict[Key, int] = {}
    for e in data.get("entries", []):
        out[(e["rule"], e["path"], e["snippet"])] = int(e.get("count", 1))
    return out


def split(findings: List[Finding], baseline: Dict[Key, int]
          ) -> Tuple[List[Finding], List[Finding], List[Key]]:
    """(new, grandfathered, stale-keys). Per fingerprint, up to the
    baselined COUNT of occurrences is grandfathered (sorted order keeps
    the choice deterministic); the surplus is new."""
    budget = dict(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in sorted(findings, key=Finding.sort_key):
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            old.append(f)
        else:
            new.append(f)
    # any unused allowance is stale: the ratchet wants exact counts, so
    # fixing ONE of three identical baselined findings already requires
    # (and permits only) a shrink
    stale = [k for k, n in sorted(budget.items()) if n > 0]
    return new, old, stale


def shrink(findings: List[Finding], old_baseline: Dict[Key, int]
           ) -> Dict[Key, int]:
    """The `--baseline` update: per key, min(old count, current count);
    keys with no current finding drop out; keys not already baselined
    never enter (the ratchet)."""
    current: Dict[Key, int] = {}
    for f in findings:
        current[f.key()] = current.get(f.key(), 0) + 1
    out: Dict[Key, int] = {}
    for k, n in old_baseline.items():
        have = current.get(k, 0)
        if have > 0:
            out[k] = min(n, have)
    return out


def write(baseline: Dict[Key, int], path=None) -> pathlib.Path:
    p = pathlib.Path(path) if path else default_path()
    entries = [{"rule": r, "path": pa, "snippet": s, "count": n}
               for (r, pa, s), n in sorted(baseline.items())]
    p.write_text(json.dumps({"_comment": _HEADER, "entries": entries},
                            indent=2, sort_keys=False) + "\n")
    return p
