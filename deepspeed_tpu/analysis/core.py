"""`dstpu_lint` framework core — findings, pragmas, rule registry, driver.

The serving/training stack runs on conventions that nothing enforced
mechanically until this package: injectable clocks in the serving tier
(the chaos harness swaps them), buffer donation on every persistent
jitted program (use-after-donation is a silent wrong-answer bug on TPU),
no host syncs or per-call `jax.jit` construction in hot paths, and a
docs-synced metric catalog. Each convention is a `Rule` here; the CLI
(`bin/dstpu_lint`) and the tier-1 self-check test share this driver.

Everything in this package is stdlib-only (`ast`, `re`, `json`) — the
linter must import in milliseconds and run anywhere, including
environments without jax. DT005 is the one exception: it resolves
dynamically composed metric names by importing the package, lazily,
inside its check.

Suppression grammar (one finding class, one reason, same line or the
line directly above)::

    x.item()   # dstpu: ignore[DT001]: completion fence, cold path
    # dstpu: ignore[DT001,DT003]: reason covering the next line
    y = donated_read(y)

A pragma without a reason string does NOT suppress — it becomes a DT000
finding itself, as does a pragma naming an unknown rule or suppressing
nothing (when the full rule set runs). The checked-in baseline
(`lint_baseline.json`, see baseline.py) grandfathers pre-existing
findings; it may only shrink.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import pathlib
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Tuple

# framework-reserved id: pragma hygiene + unparsable files
FRAMEWORK_RULE = "DT000"

_RULE_ID_RE = re.compile(r"^DT\d{3}$")

# the pragma grammar: a comment `dstpu: ignore[DT001]: reason text`
# (multiple ids comma-separated; the reason clause is mandatory)
PRAGMA_RE = re.compile(
    r"#\s*dstpu:\s*ignore\[([^\]]*)\]\s*(?::\s*(\S.*))?\s*$")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    `snippet` (the stripped source line) is the baseline fingerprint
    anchor: line numbers drift with every edit above a finding, the line
    text itself only changes when the finding's code changes."""
    rule: str
    path: str          # repo-relative, posix separators
    line: int          # 1-based
    col: int           # 0-based
    message: str
    snippet: str = ""

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule, self.message)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: " \
               f"{self.rule} {self.message}"


@dataclasses.dataclass
class Pragma:
    line: int                  # line the pragma comment sits on
    rules: Tuple[str, ...]
    reason: str
    standalone: bool           # comment-only line: covers the NEXT line
    used: bool = False

    def covers(self) -> Tuple[int, ...]:
        # a standalone pragma anchors the line below it; a trailing one
        # anchors its own line
        return (self.line + 1,) if self.standalone else (self.line,)


def _comment_tokens(source: str):
    """(line, col, text) of every real COMMENT token — pragma grammar in
    a docstring or f-string (this package documents itself!) must not
    parse as a pragma."""
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        return [(t.start[0], t.start[1], t.string) for t in toks
                if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError):   # pragma: no cover
        return []


def scan_pragmas(source: str, lines: List[str], path: str,
                 known_rules: Iterable[str]) -> Tuple[List[Pragma],
                                                      List[Finding]]:
    """Parse every suppression pragma in a file; malformed ones (no
    reason, empty/unknown rule list) come back as DT000 findings and
    suppress nothing."""
    known = set(known_rules)
    pragmas: List[Pragma] = []
    findings: List[Finding] = []
    for i, col, comment in _comment_tokens(source):
        m = PRAGMA_RE.search(comment)
        if not m:
            continue
        text = lines[i - 1] if i <= len(lines) else comment
        snippet = text.strip()
        ids = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = (m.group(2) or "").strip()
        bad = [r for r in ids if not _RULE_ID_RE.match(r)
               or (known and r not in known)]
        if not ids or bad:
            findings.append(Finding(
                FRAMEWORK_RULE, path, i, col, "malformed pragma: "
                f"unknown or empty rule list {list(ids) or '[]'} — use "
                f"`# dstpu: ignore[DTnnn]: reason`", snippet))
            continue
        if not reason:
            findings.append(Finding(
                FRAMEWORK_RULE, path, i, col,
                f"pragma for {','.join(ids)} has no reason string — a "
                f"suppression must say WHY the finding is intentional "
                f"(`# dstpu: ignore[{','.join(ids)}]: reason`); it "
                f"suppresses nothing until it does", snippet))
            continue
        standalone = text.strip().startswith("#")
        pragmas.append(Pragma(i, ids, reason, standalone))
    return pragmas, findings


@dataclasses.dataclass
class ModuleContext:
    """Everything a per-file rule sees: one parsed module."""
    path: str                  # repo-relative posix path
    source: str
    lines: List[str]
    tree: ast.Module

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule, self.path, node.lineno, node.col_offset,
                       message, self.snippet(node.lineno))


@dataclasses.dataclass
class ProjectContext:
    """What a project-level rule sees: the repo root plus every module
    the driver already parsed (path -> ModuleContext). `full_scan` says
    the default roots were scanned — a rule may then reuse `modules`
    instead of re-reading the tree."""
    repo_root: pathlib.Path
    modules: Dict[str, ModuleContext]
    full_scan: bool = True


class Rule:
    """Base class. Subclasses set `id`/`name`/`description`, optionally
    scope themselves with `paths`/`exclude` (repo-relative prefixes), and
    implement `check_module` (per-file) or `check_project` (once per run,
    `project_level = True`)."""

    id: str = ""
    name: str = ""
    description: str = ""
    paths: Tuple[str, ...] = ()
    exclude: Tuple[str, ...] = ()
    project_level: bool = False

    def applies(self, path: str) -> bool:
        if any(path.startswith(e) for e in self.exclude):
            return False
        return not self.paths or any(path.startswith(p)
                                     for p in self.paths)

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        return ()

    def check_project(self, ctx: ProjectContext) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, Rule] = {}
_LOADED = False


def register(cls):
    """Class decorator: instantiate and add to the global rule registry."""
    rule = cls()
    assert _RULE_ID_RE.match(rule.id), f"bad rule id {rule.id!r}"
    assert rule.id not in _REGISTRY, f"duplicate rule id {rule.id}"
    _REGISTRY[rule.id] = rule
    return cls


def all_rules() -> Dict[str, Rule]:
    """id -> rule, importing the rule modules on first use."""
    global _LOADED
    if not _LOADED:
        from deepspeed_tpu.analysis import (  # noqa: F401
            rules_hostsync, rules_clock, rules_donation,
            rules_recompile, rules_catalog)
        _LOADED = True
    return dict(sorted(_REGISTRY.items()))


@dataclasses.dataclass
class LintReport:
    findings: List[Finding]                      # active (not suppressed)
    suppressed: List[Tuple[Finding, Pragma]]
    rules_run: List[str]
    scanned: List[str] = dataclasses.field(default_factory=list)

    def sorted_findings(self) -> List[Finding]:
        return sorted(self.findings, key=Finding.sort_key)


# directories under the repo root the driver scans by default; tests and
# docs are rule inputs (DT005 reads docs/), not lint targets
DEFAULT_SCAN_ROOTS = ("deepspeed_tpu",)


def iter_source_files(repo_root: pathlib.Path,
                      targets: Optional[List[str]] = None):
    """Yield (repo-relative posix path, absolute path) for every python
    file in scope, sorted for deterministic output."""
    roots = [repo_root / t for t in (targets or DEFAULT_SCAN_ROOTS)]
    seen = set()
    for root in roots:
        if not root.exists():
            # a typo'd CI target must fail, not green-light zero files
            raise FileNotFoundError(f"lint target does not exist: {root}")
        if root.is_file():
            files = [root]
        else:
            files = sorted(root.rglob("*.py"))
        for f in files:
            if "__pycache__" in f.parts or f in seen:
                continue
            seen.add(f)
            yield f.relative_to(repo_root).as_posix(), f


def analyze_module(ctx: ModuleContext, rules: Iterable[Rule],
                   known_ids: Iterable[str],
                   check_unused: bool = True) -> Tuple[List[Finding],
                                                       List[Tuple[Finding,
                                                                  Pragma]]]:
    """Run per-file rules over one parsed module and apply its pragmas.
    Returns (active findings incl. DT000 hygiene, suppressed pairs)."""
    raw: List[Finding] = []
    for rule in rules:
        if not rule.project_level and rule.applies(ctx.path):
            raw.extend(rule.check_module(ctx))
    pragmas, hygiene = scan_pragmas(ctx.source, ctx.lines, ctx.path,
                                    known_ids)
    by_line: Dict[int, List[Pragma]] = {}
    for p in pragmas:
        for ln in p.covers():
            by_line.setdefault(ln, []).append(p)
    active: List[Finding] = []
    suppressed: List[Tuple[Finding, Pragma]] = []
    for f in raw:
        hit = next((p for p in by_line.get(f.line, ())
                    if f.rule in p.rules), None)
        if hit is not None:
            hit.used = True
            suppressed.append((f, hit))
        else:
            active.append(f)
    if check_unused:
        for p in pragmas:
            if not p.used:
                active.append(Finding(
                    FRAMEWORK_RULE, ctx.path, p.line, 0,
                    f"unused pragma: no {','.join(p.rules)} finding on "
                    f"the line it covers — delete it (dead suppressions "
                    f"hide future regressions)", ctx.snippet(p.line)))
    return active + hygiene, suppressed


def run_lint(repo_root, targets: Optional[List[str]] = None,
             rule_ids: Optional[List[str]] = None,
             check_unused: Optional[bool] = None) -> LintReport:
    """Parse every file in scope once, run the per-file rules, apply
    pragmas, then run the project-level rules. Pure function of the
    tree — no baseline logic here (see baseline.py / cli.py)."""
    repo_root = pathlib.Path(repo_root).resolve()
    registry = all_rules()
    if rule_ids is not None:
        unknown = [r for r in rule_ids if r not in registry]
        if unknown:
            raise KeyError(f"unknown rule id(s): {unknown}; "
                           f"known: {list(registry)}")
        rules = [registry[r] for r in rule_ids]
    else:
        rules = list(registry.values())
    known_ids = list(registry) + [FRAMEWORK_RULE]
    # unused-pragma hygiene only makes sense against the full rule set —
    # under --rules filtering, every other rule's pragmas look unused
    if check_unused is None:
        check_unused = rule_ids is None

    findings: List[Finding] = []
    suppressed: List[Tuple[Finding, Pragma]] = []
    modules: Dict[str, ModuleContext] = {}
    scanned: List[str] = []
    for rel, abspath in iter_source_files(repo_root, targets):
        scanned.append(rel)
        source = abspath.read_text()
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            findings.append(Finding(FRAMEWORK_RULE, rel, e.lineno or 1, 0,
                                    f"file does not parse: {e.msg}"))
            continue
        ctx = ModuleContext(rel, source, source.splitlines(), tree)
        modules[rel] = ctx
        active, supp = analyze_module(ctx, rules, known_ids, check_unused)
        findings.extend(active)
        suppressed.extend(supp)

    pctx = ProjectContext(repo_root, modules, full_scan=targets is None)
    for rule in rules:
        if rule.project_level:
            findings.extend(rule.check_project(pctx))

    return LintReport(sorted(findings, key=Finding.sort_key), suppressed,
                      [r.id for r in rules], scanned)
