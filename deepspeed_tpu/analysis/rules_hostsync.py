"""DT001 — host-sync-in-hot-path.

A host sync (`.item()`, `jax.device_get`, `block_until_ready`,
`np.asarray` on a device value) inside the serving/inference step path
stalls the dispatch pipeline: the host blocks until the device drains,
and the next program launch can't overlap the current one. The serving
tier's whole design budget is ONE host roundtrip per decode window /
verify step (see `ServingEngine._step_impl`); an accidental extra sync
is invisible in tests on CPU and a throughput cliff on a real TPU.

Scope: the serving tier, the inference tier, and the training engines'
dispatch files — plus the modules whose *deliberate* syncs (host-offload
tiers, timing fences) carry `# dstpu: ignore[DT001]: reason` pragmas so
the review-time question "is this sync on purpose?" is answered in the
source, once.

Device-value detection for `np.asarray`/`np.array` is a local taint:
names assigned from a call to a known persistent jitted program (see
jaxmodel.JitRegistry) are device values until rebound. `np.asarray`
applied to the result of `jax.device_get(...)` does NOT double-report —
the device_get is the sync and the only finding.
"""

from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import Rule, register
from deepspeed_tpu.analysis.jaxmodel import (
    JitRegistry, assign_target_names, dotted, iter_functions, own_calls,
    statements_in_order)

_SYNC_CALLS = {
    "jax.device_get": "jax.device_get() blocks until the device value "
                      "is materialized on the host",
    "jax.block_until_ready": "jax.block_until_ready() is a full device "
                             "fence",
}
_NP_CONVERT = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


@register
class HostSyncRule(Rule):
    id = "DT001"
    name = "host-sync-in-hot-path"
    description = (
        "host synchronization (.item(), jax.device_get, "
        "block_until_ready, np.asarray on a device value) in a "
        "dispatch-latency-sensitive path; intentional syncs carry a "
        "reasoned pragma")
    paths = (
        "deepspeed_tpu/serving/",
        "deepspeed_tpu/inference/",
        "deepspeed_tpu/runtime/engine.py",
        "deepspeed_tpu/runtime/hybrid_engine.py",
        "deepspeed_tpu/runtime/cpu_optimizer.py",
        "deepspeed_tpu/runtime/infinity.py",
        "deepspeed_tpu/launcher/comm_bench.py",
        "deepspeed_tpu/comm/comm.py",
        "deepspeed_tpu/comm/collectives.py",
        "deepspeed_tpu/parallel/moe.py",
    )

    def check_module(self, ctx):
        findings = []
        registry = JitRegistry.collect(ctx.tree)

        def check_call(call: ast.Call, tainted):
            name = dotted(call.func)
            if name in _SYNC_CALLS:
                findings.append(ctx.finding(
                    self.id, call, f"host sync: {_SYNC_CALLS[name]}"))
                return
            if (isinstance(call.func, ast.Attribute)
                    and call.func.attr == "item" and not call.args):
                findings.append(ctx.finding(
                    self.id, call, "host sync: .item() forces a "
                    "device->host transfer and drains the pipeline"))
                return
            if (isinstance(call.func, ast.Attribute)
                    and call.func.attr == "block_until_ready"):
                findings.append(ctx.finding(
                    self.id, call, "host sync: .block_until_ready() is "
                    "a device fence"))
                return
            if name in _NP_CONVERT and call.args:
                arg = call.args[0]
                argname = dotted(arg)
                if argname is not None and argname in tainted:
                    findings.append(ctx.finding(
                        self.id, call, f"host sync: {name}() on "
                        f"'{argname}', a device value produced by the "
                        f"jitted program at line {tainted[argname]} — "
                        f"this transfers and blocks"))
                elif isinstance(arg, ast.Call):
                    prog = registry.lookup(arg)
                    if prog is not None:
                        findings.append(ctx.finding(
                            self.id, call, f"host sync: {name}() "
                            f"directly on the result of jitted program "
                            f"'{prog.name}'"))

        # module-level statements: no taint, but direct syncs still count
        class TopVisitor(ast.NodeVisitor):
            def visit_FunctionDef(self, node):
                pass                      # handled per-function below
            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Call(self, node):
                check_call(node, {})
                self.generic_visit(node)

        TopVisitor().visit(ctx.tree)

        for fn in iter_functions(ctx.tree):
            tainted = {}                 # dotted name -> taint line
            for stmt, _depth in statements_in_order(fn):
                for node in own_calls(stmt):
                    check_call(node, tainted)
                # taint update from this statement's assignment
                if isinstance(stmt, ast.Assign):
                    value = stmt.value
                    is_device = (isinstance(value, ast.Call)
                                 and registry.lookup(value) is not None)
                    for name in assign_target_names(stmt):
                        if is_device:
                            tainted[name] = stmt.lineno
                        else:
                            tainted.pop(name, None)
        return findings
