"""DT005 — metric-catalog drift.

The docs/profiling.md "Metric catalog" section and the source tree must
agree: every literal metric name recorded through the telemetry facade
(or a registry handle) appears in the catalog, and every catalog row
names a metric that still exists (no dead rows). Dynamically composed
names — f-string router counters, per-replica TTFT, `record_events`
routing, the memscope `LEDGER_GAUGES` loop — cannot be seen by a static
scan, so they are enumerated explicitly below: growing one means growing
its doc row, and the enumeration is the escape hatch a new dynamic
emitter must join.

This is the ONE implementation of the check (migrated from the former
inline body of `tests/test_telemetry.py::test_metric_catalog_lint`,
which now calls `catalog_findings`). The CLI runs it as rule DT005; the
tier-1 test asserts it returns nothing.

Resolving the dynamic names imports `deepspeed_tpu` (the only rule that
does); the import is lazy so `dstpu_lint --rules DT001..DT004` stays
jax-free.
"""

from __future__ import annotations

import pathlib
import re
from typing import List, Optional

from deepspeed_tpu.analysis.core import Finding, Rule, register

# literal names recorded through the facade (inc / observe / set_gauge)
# or a registry handle (histogram / gauge / counter) with a quoted
# "<subsystem>/<metric>" first argument
_RECORD_RE = re.compile(
    r'\.(?:inc|observe|set_gauge|histogram|gauge|counter)'
    r'\(\s*"([^"\s]+/[^"\s]+)"')

# names composed at runtime that no static scan can see; each entry is
# documented in the catalog like a literal one. ServingRouter counters
# and the memscope LEDGER_GAUGES list are pulled from the live package
# so this module cannot drift from them.
_STATIC_DYNAMIC_NAMES = (
    "router/replica/<rid>/ttft_ms",   # per-replica, rid interpolated
    "train/hbm_bytes_in_use",         # gauge set via a (src, dst) table
    "train/hbm_peak_bytes",
    "Checkpoint/save_ms",             # routed through record_events
    # MoE grad-path extras: slash-keyed scalars the loss aux dict exports
    # through the engine's generic gauge loop (`_after_step` publishes
    # every "<sub>/<name>" metric) — no literal recording site
    "moe/aux_loss",
    "moe/overflow_tokens",
    "moe/dropped_frac",
)


def _dynamic_names() -> set:
    """Runtime-composed metric names (imports the package, lazily)."""
    from deepspeed_tpu.autotuning.session import TUNE_COUNTERS
    from deepspeed_tpu.comm import collectives as coll_mod
    from deepspeed_tpu.serving import Autoscaler, ServingRouter
    from deepspeed_tpu.telemetry import memscope as memscope_mod
    dynamic = {f"router/{k}"
               for k in ServingRouter(replicas=[]).counters}
    # tune-session counters ride one f-string (`tune/{name}`); the live
    # tuple is the enumeration, so growing it grows this check
    dynamic |= {f"tune/{k}" for k in TUNE_COUNTERS}
    # autoscaler decisions ride one f-string (`fabric/{name}`); enumerate
    # the live counter set so the catalog cannot drift from it
    dynamic |= {f"fabric/{k}"
                for k in Autoscaler(ServingRouter(replicas=[]),
                                    spawn=lambda i: None).counters}
    dynamic |= set(_STATIC_DYNAMIC_NAMES)
    dynamic |= {f"mem/{k}" for k in memscope_mod.LEDGER_GAUGES}
    # comm facade per-op stats (CommStats.bind_telemetry f-strings);
    # the catalog documents the placeholder form once per suffix, like
    # router/replica/<rid>/ttft_ms — accept both spellings
    dynamic |= {f"comm/{op}_{suffix}"
                for op in (*coll_mod.OP_NAMES, "<op>")
                for suffix in ("bytes", "calls", "ms")}
    return dynamic


def catalog_findings(repo_root,
                     docs_path: Optional[pathlib.Path] = None,
                     package_root: Optional[pathlib.Path] = None,
                     sources: Optional[dict] = None) -> List[Finding]:
    """The metric-catalog check. Returns [] when docs and code agree.

    `docs_path`/`package_root` exist for the fixture tests (point the
    doc side at a synthetic catalog); `sources` ({rel path: text}) lets
    the lint driver hand over the files it already read instead of a
    second tree walk. Production test callers pass only `repo_root`."""
    repo_root = pathlib.Path(repo_root)
    pkg = package_root or repo_root / "deepspeed_tpu"
    docs = docs_path or repo_root / "docs" / "profiling.md"

    if sources is None:
        sources = {}
        for p in sorted(pkg.rglob("*.py")):
            if "__pycache__" not in p.parts:
                sources[p.relative_to(repo_root).as_posix()] = \
                    p.read_text()
    code_names = {}                       # name -> (rel path, line)
    for rel in sorted(sources):
        text = sources[rel]
        for m in _RECORD_RE.finditer(text):
            line = text.count("\n", 0, m.start()) + 1
            code_names.setdefault(m.group(1), (rel, line))
    if not code_names:
        return [Finding("DT005", pkg.name, 1, 0,
                        "metric scan found no recording sites — did the "
                        "telemetry facade move? (the scan regex no "
                        "longer matches anything)")]

    try:
        dynamic = _dynamic_names()
    except Exception as e:                # pragma: no cover - env-specific
        return [Finding("DT005", "deepspeed_tpu", 1, 0,
                        f"dynamic metric-name resolution failed "
                        f"({type(e).__name__}: {e}) — the catalog check "
                        f"needs an importable package")]

    doc_rel = docs.relative_to(repo_root).as_posix() \
        if docs.is_relative_to(repo_root) else str(docs)
    if not docs.exists():
        return [Finding("DT005", doc_rel, 1, 0,
                        "metric catalog document is missing")]
    doc_text = docs.read_text()
    if "### Metric catalog" not in doc_text:
        return [Finding("DT005", doc_rel, 1, 0,
                        'no "### Metric catalog" section in the metric '
                        'catalog document')]
    section = doc_text.split("### Metric catalog")[1].split("###")[0]
    sec_start = doc_text[:doc_text.index("### Metric catalog")] \
        .count("\n") + 1
    doc_names = {}                        # name -> doc line
    # backticked repo paths in the section's prose are cross-links, not
    # catalog rows
    link_prefixes = ("docs/", "bin/", "tests/", "deepspeed_tpu/",
                     "examples/")
    for i, line in enumerate(section.splitlines(), start=sec_start):
        for m in re.finditer(r"`([^`\s]+/[^`\s]+)`", line):
            if not m.group(1).startswith(link_prefixes):
                doc_names.setdefault(m.group(1), i)

    findings = []
    for name in sorted(set(code_names) - set(doc_names)):
        path, line = code_names[name]
        findings.append(Finding(
            "DT005", path, line, 0,
            f"metric '{name}' is recorded here but missing from the "
            f"{doc_rel} catalog — add a row (name, unit, meaning)"))
    for name in sorted(set(doc_names) - set(code_names) - dynamic):
        findings.append(Finding(
            "DT005", doc_rel, doc_names[name], 0,
            f"catalog row '{name}' has no recording site left in the "
            f"tree — delete the dead row (dynamic names belong in "
            f"analysis/rules_catalog.py's enumeration)"))
    return findings


@register
class MetricCatalogRule(Rule):
    id = "DT005"
    name = "metric-catalog"
    description = (
        "docs/profiling.md metric catalog and the recording sites in "
        "the tree must agree — no undocumented metrics, no dead rows "
        "(dynamic names are enumerated in rules_catalog.py)")
    project_level = True

    def check_project(self, ctx):
        # a full default scan already read every package file — reuse it;
        # a scoped run (explicit targets) must still scan the WHOLE tree,
        # or unscanned recording sites would read as dead catalog rows
        sources = ({p: m.source for p, m in ctx.modules.items()}
                   if ctx.full_scan else None)
        return catalog_findings(ctx.repo_root, sources=sources)
