"""deepspeed_tpu — a TPU-native distributed training & inference framework.

Capability-parity rebuild of DeepSpeed (reference: /root/reference, v0.11.2) designed
TPU-first: compiled SPMD over a `jax.sharding.Mesh` instead of a hook-driven eager
runtime. The public surface mirrors the reference's top-level API
(`deepspeed/__init__.py:64` initialize, `:269` init_inference, `:246`
add_config_arguments) so users of the reference can switch with minimal friction.
"""

__version__ = "0.1.0"
version = __version__

from deepspeed_tpu.config.core import TpuTrainConfig
from deepspeed_tpu.runtime.engine import Engine, initialize
from deepspeed_tpu.inference.engine import InferenceEngine, init_inference
from deepspeed_tpu.inference.scheduler import Request, ServingEngine
from deepspeed_tpu.serving import ServingRouter
from deepspeed_tpu import comm
from deepspeed_tpu import zero
from deepspeed_tpu.utils.logging import logger, log_dist
from deepspeed_tpu.platform import get_accelerator

from deepspeed_tpu.runtime.arguments import add_config_arguments

# reference-name aliases + parity surface (deepspeed/__init__.py:21-45)
from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                           DeepSpeedTransformerLayer)
from deepspeed_tpu.runtime.hybrid_engine import HybridEngine
from deepspeed_tpu.runtime.lr_schedules import add_tuning_arguments
from deepspeed_tpu.runtime import activation_checkpointing as checkpointing
from deepspeed_tpu.inference.config import TpuInferenceConfig
from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.utils.init_on_device import OnDevice

DeepSpeedEngine = Engine
DeepSpeedHybridEngine = HybridEngine
DeepSpeedConfig = TpuTrainConfig
DeepSpeedInferenceConfig = TpuInferenceConfig


def default_inference_config():
    """Reference `default_inference_config` (`deepspeed/__init__.py:262`):
    the inference config schema with default values, as a dict."""
    import dataclasses
    return dataclasses.asdict(TpuInferenceConfig())


def _get_monitor():  # lazy to keep import light
    from deepspeed_tpu.monitor.monitor import MonitorMaster
    return MonitorMaster


__all__ = [
    "initialize",
    "init_inference",
    "default_inference_config",
    "add_config_arguments",
    "add_tuning_arguments",
    "init_distributed",
    "Engine",
    "DeepSpeedEngine",
    "HybridEngine",
    "DeepSpeedHybridEngine",
    "InferenceEngine",
    "ServingEngine",
    "Request",
    "TpuTrainConfig",
    "DeepSpeedConfig",
    "TpuInferenceConfig",
    "DeepSpeedInferenceConfig",
    "checkpointing",
    "DeepSpeedTransformerLayer",
    "DeepSpeedTransformerConfig",
    "OnDevice",
    "comm",
    "zero",
    "logger",
    "log_dist",
    "get_accelerator",
    "__version__",
]
