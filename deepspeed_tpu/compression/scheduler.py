"""Compression scheduling (reference `compression/scheduler.py`): feature gates by
global step (offset / frequency)."""


class CompressionScheduler:
    def __init__(self, schedule_offset=0, schedule_offset_end=None, frequency=1):
        self.offset = schedule_offset
        self.offset_end = schedule_offset_end
        self.frequency = max(frequency, 1)

    def is_active(self, step):
        if step < self.offset:
            return False
        if self.offset_end is not None and step > self.offset_end:
            return False
        return (step - self.offset) % self.frequency == 0

    def ratio(self, step, start_ratio=0.0, target_ratio=0.5, total_steps=1000):
        """Cubic sparsity ramp (snip_momentum style)."""
        if step <= self.offset:
            return start_ratio
        progress = min((step - self.offset) / max(total_steps - self.offset, 1), 1.0)
        return target_ratio + (start_ratio - target_ratio) * (1 - progress)**3
