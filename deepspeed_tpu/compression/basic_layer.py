"""Compression primitives: fake quantization (QAT) and pruning masks.

Reference: `deepspeed/compression/basic_layer.py` (LinearLayer_Compress with
weight/activation quantization and sparse/row/head/channel pruning) +
`compression/utils.py`. Functional form: transforms applied to params inside the
loss (straight-through estimator keeps them differentiable).
"""

import jax
import jax.numpy as jnp


def fake_quantize(w, bits=8, symmetric=True, per_channel=True, axis=-1):
    """QAT fake-quant with straight-through estimator (reference
    `Quantizer`/`fake_quantizer.cu` semantics).

    `bits` may be a scalar or a length-`w.shape[0]` sequence (per-layer bit
    widths for stacked-block leaves — the MoQ schedule's mixed precision)."""
    if per_channel and w.ndim >= 2:
        reduce_axes = tuple(i for i in range(w.ndim) if i != (axis % w.ndim))
    else:
        reduce_axes = tuple(range(w.ndim))
    if not jnp.isscalar(bits) and getattr(jnp.asarray(bits), "ndim", 0) > 0:
        barr = jnp.asarray(bits, jnp.float32)
        qmax = (2.0**(barr - 1) - 1).reshape((w.shape[0],) + (1,) * (w.ndim - 1))
    else:
        qmax = 2.0**(bits - 1) - 1
    amax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / qmax
    q = jnp.round(w / scale)
    q = jnp.clip(q, -qmax - 1, qmax)
    dequant = q * scale
    # STE: forward quantized, backward identity
    return w + jax.lax.stop_gradient(dequant - w)


def prune_magnitude(w, sparsity_ratio, method="l1", dim=None):
    """Magnitude pruning mask (reference sparse/row pruning): zero the smallest
    `sparsity_ratio` fraction — unstructured (dim=None) or whole rows/cols."""
    if sparsity_ratio <= 0:
        return w
    if dim is None:
        score = jnp.abs(w)
        k = int(score.size * sparsity_ratio)
        if k == 0:
            return w
        threshold = jnp.sort(score.reshape(-1))[k - 1]
        mask = (score > threshold).astype(w.dtype)
    else:
        score = jnp.sum(jnp.abs(w), axis=tuple(i for i in range(w.ndim) if i != dim))
        k = int(score.size * sparsity_ratio)
        if k == 0:
            return w
        threshold = jnp.sort(score)[k - 1]
        keep = (score > threshold).astype(w.dtype)
        shape = [1] * w.ndim
        shape[dim] = w.shape[dim]
        mask = keep.reshape(shape)
    return w * mask


def head_prune(w_qkv, num_heads, ratio):
    """Head pruning for fused qkv weights [.., D, 3D]: zero lowest-norm heads."""
    if ratio <= 0:
        return w_qkv
    D = w_qkv.shape[-2]
    hd = D // num_heads
    parts = jnp.split(w_qkv, 3, axis=-1)          # q,k,v each [..., D, D]
    q = parts[0].reshape(*parts[0].shape[:-1], num_heads, hd)
    score = jnp.sqrt(jnp.sum(jnp.square(q), axis=tuple(range(q.ndim - 2)) + (q.ndim - 1,)))
    k = int(num_heads * ratio)
    if k == 0:
        return w_qkv
    threshold = jnp.sort(score)[k - 1]
    keep = (score > threshold).astype(w_qkv.dtype)     # [H]
    mask = jnp.repeat(keep, hd)                         # [D]
    return w_qkv * jnp.concatenate([mask, mask, mask])[None, :] \
        if w_qkv.ndim == 2 else w_qkv * jnp.concatenate([mask, mask, mask])
