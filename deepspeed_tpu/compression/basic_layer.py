"""Compression primitives: fake quantization (QAT) and pruning masks.

Reference: `deepspeed/compression/basic_layer.py` (LinearLayer_Compress with
weight/activation quantization and sparse/row/head/channel pruning) +
`compression/utils.py`. Functional form: transforms applied to params inside the
loss (straight-through estimator keeps them differentiable).
"""

import jax
import jax.numpy as jnp


def fake_quantize(w, bits=8, symmetric=True, per_channel=True, axis=-1):
    """QAT fake-quant with straight-through estimator (reference
    `Quantizer`/`fake_quantizer.cu` semantics).

    `bits` may be a scalar or a length-`w.shape[0]` sequence (per-layer bit
    widths for stacked-block leaves — the MoQ schedule's mixed precision)."""
    if per_channel and w.ndim >= 2:
        reduce_axes = tuple(i for i in range(w.ndim) if i != (axis % w.ndim))
    else:
        reduce_axes = tuple(range(w.ndim))
    if not jnp.isscalar(bits) and getattr(jnp.asarray(bits), "ndim", 0) > 0:
        barr = jnp.asarray(bits, jnp.float32)
        qmax = (2.0**(barr - 1) - 1).reshape((w.shape[0],) + (1,) * (w.ndim - 1))
    else:
        qmax = 2.0**(bits - 1) - 1
    amax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / qmax
    q = jnp.round(w / scale)
    q = jnp.clip(q, -qmax - 1, qmax)
    dequant = q * scale
    # STE: forward quantized, backward identity
    return w + jax.lax.stop_gradient(dequant - w)


def prune_magnitude(w, sparsity_ratio, method="l1", dim=None):
    """Magnitude pruning mask (reference sparse/row pruning): zero the smallest
    `sparsity_ratio` fraction — unstructured (dim=None) or whole rows/cols."""
    if sparsity_ratio <= 0:
        return w
    if dim is None:
        score = jnp.abs(w)
        k = int(score.size * sparsity_ratio)
        if k == 0:
            return w
        threshold = jnp.sort(score.reshape(-1))[k - 1]
        mask = (score > threshold).astype(w.dtype)
    else:
        score = jnp.sum(jnp.abs(w), axis=tuple(i for i in range(w.ndim) if i != dim))
        k = int(score.size * sparsity_ratio)
        if k == 0:
            return w
        threshold = jnp.sort(score)[k - 1]
        keep = (score > threshold).astype(w.dtype)
        shape = [1] * w.ndim
        shape[dim] = w.shape[dim]
        mask = keep.reshape(shape)
    return w * mask


def quantize_activation(x, bits=8, symmetric=True):
    """Activation fake-quant with STE (reference `basic_layer.py` QuantAct
    role: per-tensor dynamic range calibration on each forward).

    symmetric: scale by max|x|; asymmetric: affine [min, max] with a zero
    point (better for post-gelu activations, which are skewed positive)."""
    if not bits or bits <= 0:
        return x
    xf = x.astype(jnp.float32)
    if symmetric:
        qmax = 2.0 ** (bits - 1) - 1
        amax = jnp.max(jnp.abs(xf))
        scale = jnp.maximum(amax, 1e-8) / qmax
        q = jnp.clip(jnp.round(xf / scale), -qmax - 1, qmax) * scale
    else:
        levels = 2.0 ** bits - 1
        lo = jnp.min(xf)
        hi = jnp.max(xf)
        scale = jnp.maximum(hi - lo, 1e-8) / levels
        q = jnp.round((xf - lo) / scale) * scale + lo
    q = q.astype(x.dtype)
    return x + jax.lax.stop_gradient(q - x)


def snip_momentum_mask(w, m, sparsity_ratio, block=(4, 1)):
    """Structured SNIP-momentum pruning mask (reference sparse_pruning method
    "snip_momentum" + `helper.py` block granularity): importance = |w * m|
    (m = the optimizer's momentum-averaged gradient — Adam's exp_avg plays
    the accumulated-|w*grad| role), scored at `block` granularity over the
    LAST TWO dims, lowest `sparsity_ratio` fraction of blocks zeroed."""
    if sparsity_ratio <= 0:
        return jnp.ones_like(w)
    br, bc = block
    R, C = w.shape[-2], w.shape[-1]
    assert R % br == 0 and C % bc == 0, (
        f"snip_momentum block {block} must divide the weight dims {(R, C)}")
    imp = jnp.abs(w.astype(jnp.float32) * m.astype(jnp.float32))
    blocked = imp.reshape(*w.shape[:-2], R // br, br, C // bc, bc)
    score = blocked.sum(axis=(-3, -1))                       # [..., R/br, C/bc]
    k = int(score.size * sparsity_ratio)
    if k == 0:
        return jnp.ones_like(w)
    # rank-based EXACT-k pruning: a threshold compare would zero every block
    # tied at the threshold (e.g. all zero-importance blocks at small ratios,
    # overshooting the scheduled ramp by an arbitrary amount)
    order = jnp.argsort(score.reshape(-1))
    keep_flat = jnp.ones((score.size,), w.dtype).at[order[:k]].set(0)
    keep = keep_flat.reshape(score.shape)
    mask = jnp.repeat(jnp.repeat(keep, br, axis=-2), bc, axis=-1)
    return mask.reshape(w.shape)


def head_prune(w_qkv, num_heads, ratio):
    """Head pruning for fused qkv weights [.., D, 3D]: zero lowest-norm heads."""
    if ratio <= 0:
        return w_qkv
    D = w_qkv.shape[-2]
    hd = D // num_heads
    parts = jnp.split(w_qkv, 3, axis=-1)          # q,k,v each [..., D, D]
    q = parts[0].reshape(*parts[0].shape[:-1], num_heads, hd)
    score = jnp.sqrt(jnp.sum(jnp.square(q), axis=tuple(range(q.ndim - 2)) + (q.ndim - 1,)))
    k = int(num_heads * ratio)
    if k == 0:
        return w_qkv
    threshold = jnp.sort(score)[k - 1]
    keep = (score > threshold).astype(w_qkv.dtype)     # [H]
    mask = jnp.repeat(keep, hd)                         # [D]
    return w_qkv * jnp.concatenate([mask, mask, mask])[None, :] \
        if w_qkv.ndim == 2 else w_qkv * jnp.concatenate([mask, mask, mask])
