"""Stateful compression: SNIP-momentum structured pruning + the activation
fake-quant schedule gate.

Reference: `compression/compress.py:100` routes sparse_pruning method
"snip_momentum" to an importance-accumulating structured pruner whose
sparsity follows a cubic ramp (`compression/helper.py`), and activation
quantization turns on at its `schedule_offset`. Both are TRACE-TIME state
here: the engine calls `.step(engine)` once per optimizer step; a True
return means the compiled step must be rebuilt (same retrace contract as
the MoQ scheduler, `runtime/quantize.py`).
"""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.compression.basic_layer import snip_momentum_mask
from deepspeed_tpu.compression.scheduler import CompressionScheduler
from deepspeed_tpu.utils.logging import logger


def _shared():
    # compress.py owns the path/pattern helpers (mask keys and transform
    # lookups must stringify identically); imported lazily to avoid a cycle
    from deepspeed_tpu.compression.compress import _match, _path_str
    return _match, _path_str


class SnipMomentumPruner:
    """Block-structured pruning with |w * exp_avg| importance.

    The reference accumulates |w*grad| with momentum; Adam's exp_avg IS the
    momentum-averaged gradient, so importance reads the optimizer state the
    engine already holds — no extra per-step compute. Masks refresh at the
    scheduler's frequency along a cubic sparsity ramp and are baked into the
    retraced step as constants (one retrace per refresh)."""

    def __init__(self, params, modules=("*",), dense_ratio=0.1,
                 block_pattern="4x1", schedule_offset=0,
                 schedule_offset_end=None, frequency=100):
        self.patterns = list(modules)
        self.target_sparsity = 1.0 - float(dense_ratio)
        r, c = (int(v) for v in str(block_pattern).lower().split("x"))
        self.block = (r, c)
        self.sched = CompressionScheduler(schedule_offset, schedule_offset_end,
                                          frequency)
        self.total_steps = (schedule_offset_end
                            if schedule_offset_end is not None
                            else schedule_offset + 10 * frequency)
        self.masks = {}          # path str -> jnp mask (trace-time constants)
        _match, _path_str = _shared()
        self._matching = [
            _path_str(path) for path, leaf in
            jax.tree_util.tree_flatten_with_path(params)[0]
            if leaf.ndim >= 2 and _match(_path_str(path), self.patterns)
            and leaf.shape[-2] % self.block[0] == 0
            and leaf.shape[-1] % self.block[1] == 0]

    def current_ratio(self, step):
        return self.sched.ratio(step, start_ratio=0.0,
                                target_ratio=self.target_sparsity,
                                total_steps=self.total_steps)

    def step(self, engine):
        step = engine.global_steps
        if not self.sched.is_active(step):
            return False
        if self.current_ratio(step) <= 0:
            return False
        return self._refresh(engine, step)

    def _refresh(self, engine, step):
        ratio = self.current_ratio(step)
        _, _path_str = _shared()
        params = engine.state.params
        mu = _find_momentum(engine.state.opt_state)
        flat_p = {_path_str(path): leaf for path, leaf in
                  jax.tree_util.tree_flatten_with_path(params)[0]}
        flat_m = ({_path_str(path): leaf for path, leaf in
                   jax.tree_util.tree_flatten_with_path(mu)[0]}
                  if mu is not None else {})
        for pstr in self._matching:
            w = flat_p.get(pstr)
            m = flat_m.get(pstr, w)  # no momentum (e.g. SGD): |w*w| magnitude
            if w is None:
                continue
            self.masks[pstr] = snip_momentum_mask(w, m, ratio, self.block)
        logger.info(f"snip_momentum: masks refreshed at step {step} "
                    f"(sparsity {ratio:.3f}, {len(self.masks)} leaves)")
        return True

    def apply(self, pstr, leaf):
        mask = self.masks.get(pstr)
        return leaf if mask is None else leaf * mask.astype(leaf.dtype)

    def on_resume(self, engine):
        """Checkpoint load: masks are DERIVED state (params + optimizer
        momentum + restored step counter) — rebuild them immediately instead
        of waiting up to frequency-1 steps (during which weights would regrow
        into pruned slots)."""
        step = engine.global_steps
        if step < self.sched.offset or self.current_ratio(step) <= 0:
            return False
        return self._refresh(engine, step)


def _find_momentum(opt_state):
    """Locate the Adam/momentum first-moment tree inside an optax state."""
    found = []

    def walk(s):
        if hasattr(s, "mu"):
            found.append(s.mu)
        elif hasattr(s, "trace"):
            found.append(s.trace)
        elif isinstance(s, (tuple, list)):
            for c in s:
                walk(c)

    walk(opt_state)
    return found[0] if found else None


class ActQuantGate:
    """Activation fake-quant schedule gate (reference activation_quantization
    shared_parameters.schedule_offset): `active`/`bits` are read at TRACE
    time by the model (GPTConfig.act_quant); the engine retraces when the
    gate flips on/off."""

    def __init__(self, bits=8, symmetric=True, schedule_offset=0,
                 schedule_offset_end=None):
        self.bits = int(bits)
        self.symmetric = bool(symmetric)
        self.offset = schedule_offset
        self.offset_end = schedule_offset_end
        self.active = schedule_offset <= 0

    def step(self, engine):
        want = engine.global_steps >= self.offset and (
            self.offset_end is None or engine.global_steps <= self.offset_end)
        if want != self.active:
            self.active = want
            logger.info(f"activation quantization {'ON' if want else 'OFF'} "
                        f"at step {engine.global_steps} ({self.bits} bits)")
            return True
        return False

    # gate state is a pure function of the restored step counter
    on_resume = step
