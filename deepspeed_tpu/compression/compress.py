"""Config-driven model compression.

Reference: `deepspeed/compression/compress.py:100` (`init_compression`: walks
modules replacing layers per the config's group patterns) and `:148`
(`redundancy_clean`: makes pruning permanent).

Functional form: `init_compression(model_spec, ds_config)` returns a new
ModelSpec whose loss applies the configured transforms (fake-quant weights,
pruning masks) to matching param leaves before the forward — the QAT/pruning
effect without module surgery. `redundancy_clean` applies the transforms to the
stored params permanently.
"""

import re

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.basic_layer import fake_quantize, prune_magnitude
from deepspeed_tpu.utils.logging import logger


def _extract_groups(comp_config):
    """Normalize the reference's nested config blocks into
    [(kind, params_dict, [module_patterns])]."""
    groups = []
    if hasattr(comp_config, "to_dict"):
        comp_config = comp_config.to_dict()
    for kind in ("weight_quantization", "sparse_pruning", "row_pruning",
                 "head_pruning", "channel_pruning", "activation_quantization"):
        block = comp_config.get(kind) or {}
        shared = block.get("shared_parameters", {})
        if not shared.get("enabled", bool(block.get("enabled", False))):
            continue
        diff = block.get("different_groups", {})
        if diff:
            for _, g in diff.items():
                params = g.get("params", {})
                modules = g.get("modules", ["*"])
                groups.append((kind, {**shared, **params}, modules))
        else:
            groups.append((kind, dict(shared), ["*"]))
    return groups


def _match(path, patterns):
    return any(p == "*" or re.search(p.replace("*", ".*"), path) for p in patterns)


def _path_str(path):
    """Keypath → string; ONE definition shared with pruners.py — mask keys
    and transform lookups must stringify identically."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def _transform_leaf(kind, params, leaf, scheduler=None):
    if leaf.ndim < 2:
        return leaf
    if kind == "weight_quantization":
        if scheduler is not None:
            # MoQ: live per-layer bits, read at trace time — the engine
            # retraces the step when the schedule advances (runtime/quantize.py)
            return fake_quantize(leaf, bits=scheduler.bits_vector(leaf.shape[0]))
        bits = params.get("start_bits", params.get("target_bits", 8))
        return fake_quantize(leaf, bits=int(bits))
    if kind == "sparse_pruning":
        if params.get("method") == "snip_momentum":
            return leaf  # stateful: masks applied via SnipMomentumPruner
        return prune_magnitude(leaf, 1 - params.get("dense_ratio", 0.5))
    if kind == "row_pruning":
        return prune_magnitude(leaf, 1 - params.get("dense_ratio", 0.5), dim=leaf.ndim - 2)
    if kind == "channel_pruning":
        # output-channel pruning (reference channel_pruning on conv/linear
        # out dims): whole columns of the 2D weight
        return prune_magnitude(leaf, 1 - params.get("dense_ratio", 0.5), dim=leaf.ndim - 1)
    if kind == "head_pruning":
        return leaf  # needs head count; applied via model-specific hook
    if kind == "activation_quantization":
        return leaf  # applies to activations, wired through the model cfg
    return leaf


def _build_param_transform(groups, scheduler=None, pruners=()):
    def transform(params):
        def leaf_fn(path, leaf):
            pstr = _path_str(path)
            out = leaf
            for kind, gparams, patterns in groups:
                if _match(pstr, patterns):
                    sched = scheduler if kind == "weight_quantization" else None
                    out = _transform_leaf(kind, gparams, out, scheduler=sched)
            for pruner in pruners or ():
                # snip_momentum masks (trace-time constants; the engine
                # retraces on each scheduled refresh)
                out = pruner.apply(pstr, out)
            return out

        return jax.tree_util.tree_map_with_path(leaf_fn, params)

    return transform


def _build_moq_scheduler(groups, n_layers):
    """A MoQScheduler when any weight_quantization group schedules a bit
    reduction (start_bits > target_bits); None for static-bits QAT."""
    for kind, gparams, _ in groups:
        if kind != "weight_quantization":
            continue
        start = int(gparams.get("start_bits", gparams.get("target_bits", 8)))
        target = int(gparams.get("target_bits", start))
        if start > target:
            from deepspeed_tpu.runtime.quantize import MoQScheduler
            return MoQScheduler(
                start_bits=start, target_bits=target,
                period=int(gparams.get("quantization_period",
                                       gparams.get("quantize_period", 100))),
                layer_num=n_layers)
    return None


def apply_layer_reduction(params, lr_cfg):
    """Student initialization from teacher layers (reference
    `compression/compress.py` layer_reduction + `student_initialization`:
    copy the listed teacher layers into the shallower student). The model
    zoo stacks blocks on a leading layer axis and scans over it, so the
    student is a pure slice — forward/loss work unchanged at the new depth."""
    keep = lr_cfg.get("teacher_layer")
    if keep is None:
        keep = list(range(int(lr_cfg.get("keep_number_layer", 0))))
    assert keep, "layer_reduction: set teacher_layer or keep_number_layer"
    assert "blocks" in params, (
        "layer_reduction needs the stacked-blocks param layout "
        "(params['blocks'] leaves with a leading layer axis, as the model zoo "
        f"produces); got keys {sorted(params)}")
    depth = jax.tree_util.tree_leaves(params["blocks"])[0].shape[0]
    assert max(keep) < depth and min(keep) >= 0, (
        f"layer_reduction: teacher_layer {keep} out of range for a "
        f"{depth}-layer teacher (jnp indexing would silently clamp)")
    idx = jnp.asarray(keep, jnp.int32)
    out = dict(params)
    out["blocks"] = jax.tree_util.tree_map(lambda a: a[idx], params["blocks"])
    logger.info(f"layer_reduction: student keeps teacher layers {keep}")
    return out


def init_compression(model, deepspeed_config, teacher_model=None, mpu=None):
    """Returns a ModelSpec with the compression transforms woven into the loss.
    `model` is a ModelSpec (reference takes an nn.Module)."""
    from deepspeed_tpu.config.core import TpuTrainConfig
    from deepspeed_tpu.runtime.engine import ModelSpec
    cfg = TpuTrainConfig.load(deepspeed_config)
    lr_cfg = cfg.compression_training.layer_reduction
    groups = _extract_groups(cfg.compression_training)
    if not groups and not lr_cfg.get("enabled"):
        logger.warning("init_compression: no enabled compression blocks")
        return model

    params = model.params
    if lr_cfg.get("enabled"):
        src = teacher_model.params if teacher_model is not None else params
        params = apply_layer_reduction(src, lr_cfg)

    inner_loss = model.loss_fn

    # activation quantization: wired through the zoo config (the reference
    # quantizes each compressed linear's INPUT inside LinearLayer_Compress;
    # here models/gpt reads cfg.act_quant at trace time). Models without an
    # arch_cfg cannot consume it -> fail loudly, not silently.
    act_gate = None
    aq = [g for g in groups if g[0] == "activation_quantization"]
    if aq:
        from deepspeed_tpu.compression.pruners import ActQuantGate
        assert len(aq) == 1 and aq[0][2] == ["*"], (
            "activation_quantization applies model-wide here (the gate rides "
            "the model config, not per-leaf transforms) — per-module groups "
            f"are not supported yet: {[(g[2]) for g in aq]}")
        gp = aq[0][1]
        act_gate = ActQuantGate(
            bits=int(gp.get("bits", gp.get("start_bits", 8))),
            symmetric=gp.get("quantization_type", "symmetric") == "symmetric",
            schedule_offset=int(gp.get("schedule_offset", 0)),
            schedule_offset_end=gp.get("schedule_offset_end"))
        arch = getattr(model, "arch_cfg", None)
        assert arch is not None and hasattr(arch, "act_quant"), (
            "activation_quantization needs a model whose config consumes "
            "cfg.act_quant (the GPT zoo); this model has no arch_cfg")
        import dataclasses as _dc
        new_arch = _dc.replace(arch, act_quant=act_gate)
        import functools as _ft
        assert isinstance(inner_loss, _ft.partial) and "cfg" in inner_loss.keywords, (
            "activation_quantization: cannot rebind the model config on a "
            "non-zoo loss function")
        inner_loss = _ft.partial(inner_loss.func, *inner_loss.args,
                                 **{**inner_loss.keywords, "cfg": new_arch})

    pruners = []
    for _, gp, mods in (g for g in groups if g[0] == "sparse_pruning"
                        and g[1].get("method") == "snip_momentum"):
        from deepspeed_tpu.compression.pruners import SnipMomentumPruner
        pruners.append(SnipMomentumPruner(
            params, modules=mods,
            dense_ratio=float(gp.get("dense_ratio", 0.1)),
            block_pattern=gp.get("block_pattern", "4x1"),
            schedule_offset=int(gp.get("schedule_offset", 0)),
            schedule_offset_end=gp.get("schedule_offset_end"),
            frequency=int(gp.get("frequency", 100))))

    scheduler = None
    if groups:
        n_layers = 1
        if isinstance(params, dict) and "blocks" in params:
            n_layers = jax.tree_util.tree_leaves(params["blocks"])[0].shape[0]
        scheduler = _build_moq_scheduler(groups, n_layers)
        transform = _build_param_transform(groups, scheduler=scheduler,
                                           pruners=pruners)

        def compressed_loss(params, batch, rng=None):
            return inner_loss(transform(params), batch, rng)
    else:
        compressed_loss = inner_loss

    steppers = ([act_gate] if act_gate is not None else []) + pruners

    logger.info(f"compression enabled: {[g[0] for g in groups]}"
                + (" + layer_reduction" if lr_cfg.get("enabled") else "")
                + (" + MoQ schedule" if scheduler is not None else ""))
    return ModelSpec(loss_fn=compressed_loss, params=params,
                     param_specs=model.param_specs, apply_fn=model.apply_fn,
                     has_aux=model.has_aux, name=model.name + "+compress",
                     arch_cfg=getattr(model, "arch_cfg", None),
                     quantize_scheduler=scheduler,
                     compression_steppers=steppers or None)


def redundancy_clean(model_or_params, deepspeed_config, mpu=None):
    """Make compression permanent (reference `redundancy_clean`): applies the
    transforms to the actual parameter values."""
    from deepspeed_tpu.config.core import TpuTrainConfig
    cfg = TpuTrainConfig.load(deepspeed_config)
    groups = _extract_groups(cfg.compression_training)
    transform = _build_param_transform(groups)
    params = getattr(model_or_params, "params", model_or_params)
    cleaned = transform(params)
    if hasattr(model_or_params, "params"):
        model_or_params.params = cleaned
        return model_or_params
    return cleaned
