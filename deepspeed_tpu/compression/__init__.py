from deepspeed_tpu.compression.compress import (apply_layer_reduction,
                                                init_compression,
                                                redundancy_clean)
from deepspeed_tpu.compression.basic_layer import fake_quantize, prune_magnitude
from deepspeed_tpu.compression.scheduler import CompressionScheduler
