"""Unified attention dispatch — ONE decision layer for every attention call.

The model zoo has five attention entry points (training flash/chunked/dense,
chunked paged prefill, paged single-token decode, paged spec-decode verify,
contiguous-cache decode) and until PR 14 each call site carried its own copy
of the engage predicate: the training `use_flash_attention` check lived at
`models/gpt.py::_attention` while the decode-kernel check lived 400 lines
away in `_decode_kernel_wanted`, and every new variant (the PR 12 quantized
kernels, ring context parallelism) had to be special-cased at each site.

This module is the single home for those decisions. A call site builds an
`AttnSite` — the dispatch KEY: (phase, q/kv length, mesh axes, kv dtype)
plus the masking flags that disqualify kernels — and `select()` walks the
PROGRAM REGISTRY (highest priority first) to name the program that runs.
Variants register once here instead of branching at five call sites:

  * the ring / ring∘Ulysses context-parallel programs (`parallel/ring.py`)
    register with a `runner` — the training forward invokes them through
    the registry without knowing their internals;
  * the PR 12 quantized paged kernels register as ordinary programs keyed
    on `kv_dtype`, not as an if/else inside the paged attention half.

Every predicate reads only TRACE-TIME-STATIC inputs (shapes, config
fields, the installed mesh spec), so dispatch can never cause a recompile:
the serving tier's ≤1-compile-per-program invariant is untouched, and
`dstpu_lint` DT004 treats `register_program` as a once-per-lifetime
construction context (programs built at registration time are persistent,
exactly like the scheduler's `_build_*` programs).

`dispatch_table()` renders the live registry — the reference table in
docs/kernels.md is generated from the same data the dispatcher walks.
"""

import dataclasses
from typing import Callable, Dict, Optional, Tuple

# ----------------------------------------------------------------------
# engage predicates — the ONE home of the measured crossovers
# ----------------------------------------------------------------------

# Training auto-dispatch crossover (measured r4, bf16 dots + 512-blocks:
# XLA materialized attention wins <= 512, flash wins 1.6x/2.3x/3.4x at
# 1k/2k/4k fwd+bwd) — see GPTConfig.use_flash_attention.
FLASH_MIN_SEQ = 1024
# Decode auto-dispatch: the blocked streaming kernel reads only the live
# cache prefix while the XLA einsum reads the whole allocated M every step;
# below this the einsum already sits at the bandwidth floor (r5: 174-204us
# vs kernel 189us vs floor 164us at ctx 8k) — see docs/kernels.md.
DECODE_KERNEL_MIN_CTX = 8192


def flash_wanted(force_flash: Optional[bool], T: int) -> bool:
    """THE training-attention flash predicate (single definition — the two
    historical copies at models/gpt.py:436 and :855 both resolve here).
    `force_flash` is `GPTConfig.use_flash_attention`: True forces, False
    forbids, None auto-engages from FLASH_MIN_SEQ."""
    return force_flash is True or (force_flash is None and T >= FLASH_MIN_SEQ)


def decode_kernel_wanted(force_flash: Optional[bool], M: int) -> bool:
    """THE decode-kernel predicate: explicit True forces, auto engages from
    DECODE_KERNEL_MIN_CTX with a block-tileable length (contiguous path:
    M = allocated cache length; paged path: M = table_width * block = the
    effective context)."""
    return (force_flash is True
            or (force_flash is None
                and M >= DECODE_KERNEL_MIN_CTX and M % 128 == 0))


def active_mesh_axes() -> Tuple[str, ...]:
    """Mesh axes with size > 1 on the installed global mesh (() when no
    mesh) — the `mesh_axes` component of the dispatch key."""
    from deepspeed_tpu.comm import mesh as mesh_mod
    if not mesh_mod.has_mesh():
        return ()
    sizes = mesh_mod.get_spec().axis_sizes()
    return tuple(name for name, n in sizes.items() if n > 1)


# ----------------------------------------------------------------------
# the dispatch key
# ----------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AttnSite:
    """One attention call site's dispatch key. Everything here is known at
    trace time; nothing data-dependent may enter (that would make program
    selection a recompile hazard)."""
    phase: str                    # "train" | "decode" | "paged_decode" |
                                  # "prefill_chunk" | "verify"
    q_len: int                    # query length (T; chunk C; 1 for decode)
    kv_len: int                   # key/context length (T, M, or nb*block)
    causal: bool = True
    has_bias: bool = False        # additive bias (alibi) present
    has_window: bool = False      # sliding-window / per-layer local mask
    scale_attn: bool = True       # False = unscaled scores (GPT-Neo)
    kv_dtype: str = "bfloat16"    # KV storage dtype ("int8" = quantized pool)
    block_size: int = 0           # paged pool physical block (paged phases)
    mesh_axes: Tuple[str, ...] = ()  # active (size>1) mesh axes
    force_flash: Optional[bool] = None  # GPTConfig.use_flash_attention
    chunk_min: Optional[int] = None     # GPTConfig.chunked_attn_min_seq
    backend: Optional[str] = None       # GPTConfig.attention_backend request
    external_fn: bool = False     # caller supplied its own attn_fn — only
                                  # the "external" pseudo-program may match

    @property
    def square(self) -> bool:
        return self.q_len == self.kv_len


# ----------------------------------------------------------------------
# the program registry
# ----------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AttentionProgram:
    """One registered attention implementation.

    `matches` decides eligibility from the AttnSite alone; `runner`, when
    set, is the zoo-layout callable ([B, T, H, hd] q/k/v, matched heads)
    the training forward invokes — phases whose call signatures carry pool
    state (decode/paged) dispatch by NAME and invoke at the call site.
    `when` is the human-readable engage condition for `dispatch_table()`
    and docs/kernels.md."""
    name: str
    phases: Tuple[str, ...]
    priority: int                 # higher wins among eligible programs
    matches: Callable[[AttnSite], bool]
    when: str = ""
    runner: Optional[Callable] = None


_REGISTRY: Dict[str, AttentionProgram] = {}


def register_program(program: AttentionProgram) -> AttentionProgram:
    """Add (or replace) a program in the dispatch registry. Registration is
    a once-per-lifetime construction context: a program whose runner closes
    over jitted callables builds them HERE, not per call."""
    _REGISTRY[program.name] = program
    return program


def get_program(name: str) -> AttentionProgram:
    return _REGISTRY[name]


def registered_programs(phase: Optional[str] = None):
    """Programs (highest priority first, name-tiebroken) — the order
    `select` walks."""
    progs = [p for p in _REGISTRY.values()
             if phase is None or phase in p.phases]
    return sorted(progs, key=lambda p: (-p.priority, p.name))


def select(site: AttnSite) -> str:
    """Name the program this site runs: the highest-priority registered
    program whose `matches(site)` holds. Every phase registers a priority-0
    fallback that always matches, so selection is total.

    An explicit ring-family `backend` request on a live `sequence` mesh
    that resolves to a NON-ring program (the site carries alibi/window
    bias or non-square attention — outside the kernel contract) raises
    instead of silently materializing dense attention: at the 128k+
    contexts context parallelism exists for, the dense fallback is an
    HBM OOM far from its cause. (A request with NO `sequence` axis still
    falls through to auto — that degenerate case is exact and documented
    on `GPTConfig.attention_backend`.) A backend string naming NO
    registered program is a config typo and raises immediately — silently
    ignoring "ring-ulysses" would hand a 128k run to single-chip dense."""
    if site.phase == "train" and site.backend is not None \
            and site.backend not in _REGISTRY:
        raise ValueError(
            f"unknown attention_backend {site.backend!r}: no program of "
            f"that name is registered (registered: {sorted(_REGISTRY)})")
    for prog in registered_programs(site.phase):
        if prog.matches(site):
            if (site.phase == "train"
                    and site.backend in ("ring", "ring_ulysses")
                    and "sequence" in site.mesh_axes
                    and prog.name not in ("ring", "ring_ulysses",
                                          "external")):
                raise ValueError(
                    f"attention_backend={site.backend!r} was requested on "
                    f"a `sequence` mesh but this site is ineligible for "
                    f"the ring programs (alibi/sliding-window bias or "
                    f"non-square attention — the plain-causal kernel "
                    f"contract) — resolved program would be "
                    f"{prog.name!r}. Drop the backend request or the "
                    f"arch flag")
            return prog.name
    raise LookupError(
        f"no attention program registered for phase {site.phase!r} "
        f"(registry: {sorted(_REGISTRY)})")


def dispatch_table() -> Dict[str, list]:
    """phase -> [(program, when)] in selection order — the reference table
    (docs/kernels.md renders this)."""
    phases = ("train", "prefill_chunk", "decode", "paged_decode", "verify")
    return {ph: [(p.name, p.when) for p in registered_programs(ph)]
            for ph in phases}


# ----------------------------------------------------------------------
# built-in programs
# ----------------------------------------------------------------------
# Priorities: 100s = explicit backend requests (ring family), 50s =
# kernel/escape-hatch engagement, 0 = the always-eligible dense fallback.


def _kernel_shape_ok(site: AttnSite) -> bool:
    """Kernel-path disqualifiers shared by flash/chunked/ring: the Pallas
    contract is plain (un-biased, un-windowed, scaled) square causal-or-not
    attention on 128-multiple sequences."""
    return (not site.has_bias and not site.has_window and site.square
            and site.q_len % 128 == 0)


def _train_external(site):
    return site.external_fn


def _train_ring(site):
    return (site.backend in ("ring", "ring_ulysses")
            and "sequence" in site.mesh_axes
            and not site.has_bias and not site.has_window and site.square)


def _train_chunked(site):
    return (site.phase == "train" and _kernel_shape_ok(site)
            and site.scale_attn and site.causal
            and flash_wanted(site.force_flash, site.q_len)
            and site.chunk_min is not None and site.q_len >= site.chunk_min)


def _train_flash(site):
    return (site.phase == "train" and _kernel_shape_ok(site)
            and site.scale_attn and site.causal
            and flash_wanted(site.force_flash, site.q_len))


def _run_ring(q, k, v, *, causal=True, sm_scale=None):
    from deepspeed_tpu.parallel.ring import ring_attention
    return ring_attention(q, k, v, causal=causal, sm_scale=sm_scale)


def _run_ring_ulysses(q, k, v, *, causal=True, sm_scale=None):
    from deepspeed_tpu.parallel.ring import ring_ulysses_attention
    return ring_ulysses_attention(q, k, v, causal=causal, sm_scale=sm_scale)


def _run_flash(q, k, v, *, causal=True, sm_scale=None):
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
    return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)


def _run_chunked(q, k, v, *, causal=True, sm_scale=None):
    import jax.numpy as jnp
    from deepspeed_tpu.ops.chunked_attention import chunked_attention
    out = chunked_attention(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                            jnp.swapaxes(v, 1, 2), causal=causal,
                            sm_scale=sm_scale)
    return jnp.swapaxes(out, 1, 2)


register_program(AttentionProgram(
    name="external", phases=("train",), priority=1000,
    matches=_train_external,
    when="caller passed an explicit attn_fn (sparse/Ulysses wrappers)"))

register_program(AttentionProgram(
    name="ring_ulysses", phases=("train",), priority=110,
    matches=lambda s: _train_ring(s) and s.backend == "ring_ulysses",
    when="attention_backend='ring_ulysses', `sequence` mesh axis active; "
         "sp = ulysses_degree x ring_degree (head all-to-all around the "
         "K/V ring)",
    runner=_run_ring_ulysses))

register_program(AttentionProgram(
    name="ring", phases=("train",), priority=100,
    matches=lambda s: _train_ring(s) and s.backend == "ring",
    when="attention_backend='ring', `sequence` mesh axis active; K/V "
         "shards rotate via ppermute, flash kernel per ring step",
    runner=_run_ring))

register_program(AttentionProgram(
    name="chunked", phases=("train",), priority=60,
    matches=_train_chunked,
    when="chunked_attn_min_seq set and T >= it (remat/memory escape "
         "hatch; ~2.8x slower than flash)",
    runner=_run_chunked))

register_program(AttentionProgram(
    name="flash", phases=("train",), priority=50,
    matches=_train_flash,
    when=f"T >= {FLASH_MIN_SEQ} (auto) or use_flash_attention=True; "
         "plain scaled causal, T % 128 == 0",
    runner=_run_flash))

register_program(AttentionProgram(
    name="dense", phases=("train",), priority=0,
    matches=lambda s: True,
    when="fallback: XLA materialized attention (GQA grouped einsum, "
         "alibi/window masks, short T)"))


# -- contiguous-cache decode ------------------------------------------------

register_program(AttentionProgram(
    name="decode_kernel", phases=("decode",), priority=50,
    matches=lambda s: (not s.has_bias and not s.has_window
                       and decode_kernel_wanted(s.force_flash, s.kv_len)),
    when=f"M >= {DECODE_KERNEL_MIN_CTX} and M % 128 == 0 (auto) or "
         "use_flash_attention=True; no alibi/window"))

register_program(AttentionProgram(
    name="decode_dense", phases=("decode",), priority=0,
    matches=lambda s: True,
    when="fallback: XLA einsum over the whole allocated cache"))


# -- paged pool (serving) ---------------------------------------------------


def _paged_kernel_ok(site):
    return (site.phase == "paged_decode" and site.q_len == 1
            and not site.has_bias and not site.has_window
            and site.block_size % 128 == 0
            and decode_kernel_wanted(site.force_flash, site.kv_len))


register_program(AttentionProgram(
    name="paged_kernel_quant", phases=("paged_decode",), priority=60,
    matches=lambda s: _paged_kernel_ok(s) and s.kv_dtype == "int8",
    when="int8 pool + kernel conditions: streamed tiles dequantize "
         "in-kernel (paged_decode_attention_quant)"))

register_program(AttentionProgram(
    name="paged_kernel", phases=("paged_decode",), priority=50,
    matches=_paged_kernel_ok,
    when="C == 1, block % 128 == 0, effective context nb*block past the "
         "decode crossover; no alibi/window"))

register_program(AttentionProgram(
    name="paged_gather_quant",
    phases=("paged_decode", "prefill_chunk", "verify"), priority=10,
    matches=lambda s: s.kv_dtype == "int8",
    when="int8 pool on the gather path: dequantizing gather oracle "
         "(chunked prefill, verify, CPU/arch-flag fallbacks)"))

register_program(AttentionProgram(
    name="paged_gather",
    phases=("paged_decode", "prefill_chunk", "verify"), priority=0,
    matches=lambda s: True,
    when="fallback: table gather + dense attend (matmul-bound chunked "
         "prefill and spec-decode verify always take this)"))
