"""Adam variants with the reference's class names.

`FusedAdam` (reference `deepspeed/ops/adam/fused_adam.py:18`) and
`DeepSpeedCPUAdam` (`deepspeed/ops/adam/cpu_adam.py:13`) exposed as optax
transformations. On TPU, "fused" means the whole multi-tensor update compiles into
the jitted step (XLA does what `multi_tensor_adam.cu` does by hand); the CPU
variant pins its state to host memory for ZeRO-Offload
(analog of `csrc/adam/cpu_adam_impl.cpp` — the step runs on host while the TPU
computes the next microbatch; see runtime/offload.py for the C++-accelerated path).
"""

import optax


def FusedAdam(params=None,
              lr=1e-3,
              bias_correction=True,
              betas=(0.9, 0.999),
              eps=1e-8,
              adam_w_mode=True,
              weight_decay=0.0,
              amsgrad=False,
              set_grad_none=True):
    """Returns an optax GradientTransformation. `params` accepted for signature parity."""
    assert not amsgrad, "amsgrad not supported (matches reference FusedAdam)"
    if adam_w_mode:
        return optax.adamw(lr, b1=betas[0], b2=betas[1], eps=eps, weight_decay=weight_decay)
    tx = optax.adam(lr, b1=betas[0], b2=betas[1], eps=eps)
    if weight_decay:
        tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    return tx


def DeepSpeedCPUAdam(model_params=None,
                     lr=1e-3,
                     bias_correction=True,
                     betas=(0.9, 0.999),
                     eps=1e-8,
                     weight_decay=0.0,
                     amsgrad=False,
                     adamw_mode=True,
                     fp32_optimizer_states=True):
    """Host-offloaded Adam: identical math, state placed on host (wired by the engine
    when zero_optimization.offload_optimizer.device == 'cpu')."""
    from deepspeed_tpu.ops.optim import mark_host_offload
    tx = FusedAdam(model_params, lr=lr, bias_correction=bias_correction, betas=betas,
                   eps=eps, adam_w_mode=adamw_mode, weight_decay=weight_decay, amsgrad=amsgrad)
    return mark_host_offload(tx)
