from deepspeed_tpu.ops.adam import FusedAdam, DeepSpeedCPUAdam
from deepspeed_tpu.ops.lamb import FusedLamb
from deepspeed_tpu.ops.lion import FusedLion, DeepSpeedCPULion
from deepspeed_tpu.ops.adagrad import DeepSpeedCPUAdagrad
from deepspeed_tpu.ops.optim import build_optimizer, OPTIMIZER_REGISTRY
from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                           DeepSpeedTransformerLayer)
