"""Optimizer registry + config-driven construction.

Analog of the reference's `_configure_basic_optimizer` (`runtime/engine.py:1239`)
which maps config `optimizer.type` strings (Adam/AdamW/Lamb/OneBitAdam/Lion/...) to
implementations. Here every optimizer is an `optax.GradientTransformation`; "fused"
is the default on TPU because XLA fuses the whole update into the step program
(reference needs `csrc/adam/multi_tensor_adam.cu` for that).
"""

from typing import Any, Callable, Dict, NamedTuple, Optional, Union

import optax

from deepspeed_tpu.utils.logging import logger

ScalarOrSchedule = Union[float, Callable[[int], float]]


class OffloadedTransformation(NamedTuple):
    """A GradientTransformation tagged for host (CPU) state placement — the engine
    places its optimizer state in pinned host memory (ZeRO-Offload analog)."""
    init: Callable
    update: Callable
    offload_to_host: bool = True


def mark_host_offload(tx: optax.GradientTransformation) -> OffloadedTransformation:
    return OffloadedTransformation(init=tx.init, update=tx.update)

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
LION_OPTIMIZER = "lion"
MUON_OPTIMIZER = "muon"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"


def _adam(lr: ScalarOrSchedule, params: Dict[str, Any]):
    betas = params.get("betas", (0.9, 0.999))
    return optax.adam(lr, b1=betas[0], b2=betas[1], eps=params.get("eps", 1e-8))


def _adamw(lr: ScalarOrSchedule, params: Dict[str, Any]):
    betas = params.get("betas", (0.9, 0.999))
    return optax.adamw(lr,
                       b1=betas[0],
                       b2=betas[1],
                       eps=params.get("eps", 1e-8),
                       weight_decay=params.get("weight_decay", 0.01))


def _lamb(lr: ScalarOrSchedule, params: Dict[str, Any]):
    betas = params.get("betas", (0.9, 0.999))
    return optax.lamb(lr,
                      b1=betas[0],
                      b2=betas[1],
                      eps=params.get("eps", 1e-6),
                      weight_decay=params.get("weight_decay", 0.0))


def _lion(lr: ScalarOrSchedule, params: Dict[str, Any]):
    betas = params.get("betas", (0.9, 0.99))
    return optax.lion(lr, b1=betas[0], b2=betas[1], weight_decay=params.get("weight_decay", 0.0))


def _sgd(lr: ScalarOrSchedule, params: Dict[str, Any]):
    return optax.sgd(lr, momentum=params.get("momentum", 0.0), nesterov=params.get("nesterov", False))


def _adagrad(lr: ScalarOrSchedule, params: Dict[str, Any]):
    return optax.adagrad(lr, eps=params.get("eps", 1e-10))


def _onebit_adam(lr: ScalarOrSchedule, params: Dict[str, Any]):
    # Compressed-communication family (reference `runtime/fp16/onebit/`):
    # warmup phase = exact base optimizer, then frozen variance + sign-compressed
    # momentum with error feedback (see runtime/compressed_grads.py).
    from deepspeed_tpu.runtime.compressed_grads import onebit_adam
    return onebit_adam(lr, params)


def _onebit_lamb(lr: ScalarOrSchedule, params: Dict[str, Any]):
    from deepspeed_tpu.runtime.compressed_grads import onebit_lamb
    return onebit_lamb(lr, params)


def _zero_one_adam(lr: ScalarOrSchedule, params: Dict[str, Any]):
    from deepspeed_tpu.runtime.compressed_grads import zero_one_adam
    return zero_one_adam(lr, params)


OPTIMIZER_REGISTRY = {
    ADAM_OPTIMIZER: _adam,
    ADAMW_OPTIMIZER: _adamw,
    LAMB_OPTIMIZER: _lamb,
    LION_OPTIMIZER: _lion,
    SGD_OPTIMIZER: _sgd,
    ADAGRAD_OPTIMIZER: _adagrad,
    ONEBIT_ADAM_OPTIMIZER: _onebit_adam,
    ZERO_ONE_ADAM_OPTIMIZER: _zero_one_adam,
    ONEBIT_LAMB_OPTIMIZER: _onebit_lamb,
}


# Reference config type strings that name implementation variants of the same
# optimizer (fused CUDA kernels / AVX host step) — on TPU there is one XLA-fused
# implementation each, so they alias (reference: ops/adam/fused_adam.py:18,
# ops/adam/cpu_adam.py:13, ops/lamb/fused_lamb.py:14, ops/lion/*).
OPTIMIZER_ALIASES = {
    "fusedadam": ADAM_OPTIMIZER,
    "fusedadamw": ADAMW_OPTIMIZER,
    "fusedlamb": LAMB_OPTIMIZER,
    "fusedlion": LION_OPTIMIZER,
    "deepspeedcpuadam": ADAM_OPTIMIZER,
    "deepspeedcpulion": LION_OPTIMIZER,
    "deepspeedcpuadagrad": ADAGRAD_OPTIMIZER,
    "onebitadam": ONEBIT_ADAM_OPTIMIZER,
    "zerooneadam": ZERO_ONE_ADAM_OPTIMIZER,
    "onebitlamb": ONEBIT_LAMB_OPTIMIZER,
}


def build_optimizer(opt_config, lr_schedule: Optional[Callable[[int], float]] = None):
    """Build an optax optimizer from an OptimizerConfig block.

    `lr_schedule` (from the scheduler block) overrides the static `lr` param.
    """
    name = opt_config.type.lower()
    name = OPTIMIZER_ALIASES.get(name, name)
    if name not in OPTIMIZER_REGISTRY:
        raise ValueError(f"Unknown optimizer '{opt_config.type}'. "
                         f"Known: {sorted(OPTIMIZER_REGISTRY)}")
    params = dict(opt_config.params)
    lr = lr_schedule if lr_schedule is not None else params.get("lr", 1e-3)
    logger.info(f"Building optimizer: {name} (lr={'<schedule>' if callable(lr) else lr})")
    return OPTIMIZER_REGISTRY[name](lr, params)
