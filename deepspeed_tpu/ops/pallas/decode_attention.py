"""Single-token KV-cache attention (Pallas) — the decode hot op.

Analog of the reference's `softmax_context` CUDA kernel
(`csrc/transformer/inference/csrc/pt_binding.cpp`, softmax.cu — fused
KV-cache attention with alibi/rope handled upstream). Decode attention is
HBM-bandwidth bound: each step streams the live K/V prefix once.

The cache is BLOCKED: [B, Hkv, M, hd] with M a multiple of `block_m` (the
inference engine rounds `max_len` up — `TpuInferenceConfig.kv_block_size`),
addressed by the kernel in [num_blocks, block_m, hd] units. The grid walks
the block axis; Pallas's pipeline DMAs one double-buffered [block_m, hd]
K/V tile from HBM per step while the online-softmax accumulator lives in
VMEM scratch — the VMEM working set is O(block_m), so context length is
bounded by HBM, not the old whole-[M, hd]-slab VMEM cap (~14k tokens at
head_dim 128 bf16). Blocks past each row's live prefix are neither fetched
(the scalar-prefetched `pos` clamps the block index map, and Pallas elides
the DMA when consecutive block indices repeat) nor computed (`pl.when`),
so a step's HBM traffic is ceil((pos+1)/block_m) tiles — the valid prefix
only, PagedAttention-style, regardless of the cache's allocated M. GQA is
supported by attending one kv head's group of query heads per grid cell.

Layout: q [B, H, hd]; k/v cache [B, Hkv, M, hd]; pos [B] (current position,
inclusive — the new token's k/v must already be scattered at pos).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128


def _use_interpret():
    return jax.default_backend() not in ("tpu", "axon")


def _online_softmax_tile(q, k, v, pos, j, acc_ref, m_ref, l_ref, *,
                         sm_scale, block_m):
    """One streamed KV tile's online-softmax update — the SINGLE definition
    of the decode-attention math, shared by the contiguous, paged, and
    quantized-paged kernels (the dequantizing kernel hands in already-
    dequantized tiles; everything after the load is identical, so the
    variants cannot drift numerically).

    q: [G, hd]; k/v: [block_m, hd] in the compute dtype; scratch acc
    [G, hd] fp32, m/l [G, _LANES] fp32 carried across the (sequential,
    innermost) block axis.

    native-dtype dots (fp32 accumulate via preferred_element_type):
    pre-casting K/V blocks to fp32 doubles the VMEM working set and VPU
    traffic (same fix as flash_attention.py)."""
    G = q.shape[0]
    in_dtype = q.dtype
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
    k_pos = j * block_m + jax.lax.broadcasted_iota(jnp.int32, (G, block_m), 1)
    s = jnp.where(k_pos <= pos, s, NEG_INF)
    m_prev = m_ref[:, 0:1]
    l_prev = l_ref[:, 0:1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p.astype(in_dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                   *, sm_scale, block_m):
    # q_ref: [1, 1, G, hd]; k_ref/v_ref: [1, 1, block_m, hd] (one streamed
    # cache tile); pos_ref: SMEM [B]; scratch acc [G, hd] fp32, m/l
    # [G, _LANES] fp32. Grid (B, Hkv, num_blocks): the block axis is
    # innermost and sequential, scratch carries the online softmax across it.
    b = pl.program_id(0)
    j = pl.program_id(2)
    nm = pl.num_programs(2)
    pos = pos_ref[b]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # only blocks intersecting [0, pos]; beyond them the clamped index map
    # re-serves the frontier tile and this predicate keeps it out of the math
    @pl.when(j * block_m <= pos)
    def _step():
        _online_softmax_tile(q_ref[0, 0], k_ref[0, 0], v_ref[0, 0], pos, j,
                             acc_ref, m_ref, l_ref,
                             sm_scale=sm_scale, block_m=block_m)

    @pl.when(j == nm - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)


def decode_attention(q, k, v, pos, sm_scale=None, block_m=None, interpret=None):
    """q: [B, H, hd]; k,v: [B, Hkv, M, hd]; pos: [B] int32 → [B, H, hd].

    Attends each query head to cache positions 0..pos inclusive. GQA-aware:
    H must be a multiple of Hkv; the group of G=H//Hkv query heads rides one
    grid cell with its kv head. Streams the cache one [block_m, hd] tile at
    a time and touches only the live prefix — M is bounded by HBM, and a
    mostly-empty long cache costs what its prefix costs, not what its
    allocation costs (the XLA einsum path always reads all M).

    `block_m=None` auto-selects: decode is HBM-bandwidth-bound (each step
    must read the whole live KV prefix), and the inner-loop fixed overhead
    dominates at small blocks — measured on v5e at ctx 8192 / GQA 4 kv heads
    (median-of-6 interleaved marginal timings): 644 us/step at block 128 vs
    189 us at block 512, against a 164 us bandwidth floor and XLA's 174-204
    us. Large blocks put the kernel AT the floor; nothing can go below it.
    """
    if interpret is None:
        interpret = _use_interpret()
    B, H, hd = q.shape
    _, Hkv, M, _ = k.shape
    assert H % Hkv == 0
    G = H // Hkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)
    if block_m is None:
        # largest measured-good block that tiles M exactly — a non-divisor
        # would force the whole-cache pad below
        block_m = 512 if M >= 1024 else 128
        while block_m > 128 and M % block_m != 0:
            block_m //= 2
    block_m = min(block_m, M)
    if M % block_m != 0:  # pad cache length to block multiple (masked anyway;
        # the engine's kv_block_size rounding keeps serving caches
        # block-tileable, so only direct odd-M callers pay this copy)
        pad = block_m - M % block_m
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        M += pad

    pos = pos.astype(jnp.int32)
    qg = q.reshape(B, Hkv, G, hd)

    def kv_index(b, h, j, pos_ref):
        # clamp past-prefix steps to the frontier block: consecutive equal
        # indices elide the DMA, so dead blocks cost no HBM traffic
        return (b, h, jnp.minimum(j, pos_ref[b] // block_m), 0)

    out = pl.pallas_call(
        functools.partial(_decode_kernel, sm_scale=sm_scale, block_m=block_m),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, Hkv, M // block_m),
            in_specs=[
                pl.BlockSpec((1, 1, G, hd), lambda b, h, j, pos_ref: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, block_m, hd), kv_index),
                pl.BlockSpec((1, 1, block_m, hd), kv_index),
            ],
            out_specs=pl.BlockSpec((1, 1, G, hd),
                                   lambda b, h, j, pos_ref: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((G, hd), jnp.float32),
                pltpu.VMEM((G, _LANES), jnp.float32),
                pltpu.VMEM((G, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, hd), q.dtype),
        interpret=interpret,
    )(pos, qg, k, v)
    return out.reshape(B, H, hd)


def _paged_decode_kernel(pos_ref, bt_ref, q_ref, k_ref, v_ref, o_ref, acc_ref,
                         m_ref, l_ref, *, sm_scale, block_m):
    # Same math as _decode_kernel — only the ADDRESSING differs: the grid's
    # block axis walks LOGICAL blocks 0..nb-1 of each row, and the index map
    # (not this body) resolves each one to a physical pool block through the
    # scalar-prefetched block table. bt_ref is therefore unused here; the
    # online-softmax state, the live-prefix predicate (j*block_m <= pos) and
    # the in-block position mask are identical because logical positions are
    # what `pos` counts.
    del bt_ref
    _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                   sm_scale=sm_scale, block_m=block_m)


def paged_decode_attention(q, k_pool, v_pool, block_tables, pos, sm_scale=None,
                           interpret=None):
    """Decode attention over a PAGED KV pool (vLLM's PagedAttention layout).

    q: [B, H, hd]; k_pool/v_pool: [N, Hkv, block, hd] physical blocks shared
    by every sequence; block_tables: [B, nb] int32 mapping each row's logical
    block j to a physical pool block; pos: [B] int32 (current position,
    inclusive — the new token's k/v must already be scattered at pos).
    Returns [B, H, hd].

    The grid walks each row's logical blocks; the kv index map resolves
    logical → physical through the scalar-prefetched table, so the kernel
    DMAs exactly the pool tiles covering the live prefix — no [B, M] gather
    is ever materialized in HBM (the XLA fallback path pays that gather
    every step). Past-prefix steps clamp to the frontier logical block:
    consecutive equal physical indices elide the DMA, same trick as the
    contiguous kernel. Rows whose table entries all point at the reserved
    trash block (inactive slots) produce garbage output that callers ignore.
    """
    if interpret is None:
        interpret = _use_interpret()
    B, H, hd = q.shape
    N, Hkv, block_m, _ = k_pool.shape
    nb = block_tables.shape[1]
    assert H % Hkv == 0
    G = H // Hkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)

    pos = pos.astype(jnp.int32)
    block_tables = block_tables.astype(jnp.int32)
    qg = q.reshape(B, Hkv, G, hd)

    def kv_index(b, h, j, pos_ref, bt_ref):
        # clamp to the frontier LOGICAL block, then translate to physical:
        # dead logical blocks re-serve the frontier's physical tile and the
        # repeated index elides the DMA
        jj = jnp.minimum(j, pos_ref[b] // block_m)
        return (bt_ref[b, jj], h, 0, 0)

    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, sm_scale=sm_scale,
                          block_m=block_m),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, Hkv, nb),
            in_specs=[
                pl.BlockSpec((1, 1, G, hd),
                             lambda b, h, j, pos_ref, bt_ref: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, block_m, hd), kv_index),
                pl.BlockSpec((1, 1, block_m, hd), kv_index),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, G, hd), lambda b, h, j, pos_ref, bt_ref: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((G, hd), jnp.float32),
                pltpu.VMEM((G, _LANES), jnp.float32),
                pltpu.VMEM((G, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, hd), q.dtype),
        interpret=interpret,
    )(pos, block_tables, qg, k_pool, v_pool)
    return out.reshape(B, H, hd)


def _paged_decode_quant_kernel(pos_ref, bt_ref, q_ref, k_ref, v_ref, ks_ref,
                               vs_ref, o_ref, acc_ref, m_ref, l_ref, *,
                               sm_scale, block_m):
    # The int8-pool variant: k/v tiles arrive QUANTIZED (int8 payload +
    # [block_m, g] f32 group scales, both resolved through the same
    # logical->physical index map), are dequantized here in VMEM — fp K/V
    # never exists in HBM — and then run the shared online-softmax tile
    # update. Dequant ordering (int8 -> f32 x scale -> narrow to the
    # compute dtype) is pinned to `quantization.dequantize_kv`, so this
    # kernel and the dequantizing gather oracle see bit-identical tiles.
    del bt_ref
    b = pl.program_id(0)
    j = pl.program_id(2)
    nm = pl.num_programs(2)
    pos = pos_ref[b]
    in_dtype = q_ref.dtype

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(j * block_m <= pos)
    def _step():
        # THE dequant definition, not a copy: `dequantize_kv` is pure jnp
        # (reshape-to-groups x scale, narrow last) and traces fine inside
        # the kernel body — the write path, the gather oracle, and this
        # tile load literally share one function, so they cannot drift
        from deepspeed_tpu.inference.quantization import dequantize_kv
        _online_softmax_tile(q_ref[0, 0],
                             dequantize_kv(k_ref[0, 0], ks_ref[0, 0],
                                           in_dtype),
                             dequantize_kv(v_ref[0, 0], vs_ref[0, 0],
                                           in_dtype), pos, j,
                             acc_ref, m_ref, l_ref,
                             sm_scale=sm_scale, block_m=block_m)

    @pl.when(j == nm - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)


def paged_decode_attention_quant(q, k_pool, v_pool, k_scale, v_scale,
                                 block_tables, pos, sm_scale=None,
                                 interpret=None):
    """Decode attention over the INT8 paged pool: dequantize-inside-the-
    kernel PagedAttention.

    q: [B, H, hd]; k_pool/v_pool: [N, Hkv, block, hd] int8; k_scale/v_scale:
    [N, Hkv, block, hd//g] f32 (the `init_paged_kv_pool` quantized layout);
    block_tables: [B, nb]; pos: [B]. Returns [B, H, hd] in q's dtype.

    Identical grid walk to `paged_decode_attention` — the scale tiles ride
    the SAME scalar-prefetched logical->physical index map as the payload,
    so a step's HBM traffic is the live prefix's int8 bytes plus its scales
    (~half the bf16 pool's traffic at group >= 8): decode is HBM-bandwidth-
    bound, so the quantized pool buys tokens/s, not just capacity. fp K/V
    exists only tile-by-tile in VMEM."""
    if interpret is None:
        interpret = _use_interpret()
    B, H, hd = q.shape
    N, Hkv, block_m, _ = k_pool.shape
    g = k_scale.shape[-1]
    nb = block_tables.shape[1]
    assert H % Hkv == 0
    G = H // Hkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)

    pos = pos.astype(jnp.int32)
    block_tables = block_tables.astype(jnp.int32)
    qg = q.reshape(B, Hkv, G, hd)

    def kv_index(b, h, j, pos_ref, bt_ref):
        jj = jnp.minimum(j, pos_ref[b] // block_m)
        return (bt_ref[b, jj], h, 0, 0)

    out = pl.pallas_call(
        functools.partial(_paged_decode_quant_kernel, sm_scale=sm_scale,
                          block_m=block_m),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, Hkv, nb),
            in_specs=[
                pl.BlockSpec((1, 1, G, hd),
                             lambda b, h, j, pos_ref, bt_ref: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, block_m, hd), kv_index),
                pl.BlockSpec((1, 1, block_m, hd), kv_index),
                pl.BlockSpec((1, 1, block_m, g), kv_index),
                pl.BlockSpec((1, 1, block_m, g), kv_index),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, G, hd), lambda b, h, j, pos_ref, bt_ref: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((G, hd), jnp.float32),
                pltpu.VMEM((G, _LANES), jnp.float32),
                pltpu.VMEM((G, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, hd), q.dtype),
        interpret=interpret,
    )(pos, block_tables, qg, k_pool, v_pool, k_scale, v_scale)
    return out.reshape(B, H, hd)


def paged_decode_attention_quant_reference(q, pool_l, block_tables, pos,
                                           sm_scale=None):
    """jnp oracle for the quantized kernel: the dequantizing gather
    (`kv_cache.gather_block_kv_dequant` — the SAME definition the XLA
    fallback path runs, so the oracle cannot silently diverge from
    production) followed by the contiguous fp reference. `pool_l` is one
    layer's quantized pool slice (k/v int8 + k_scale/v_scale)."""
    from deepspeed_tpu.inference.kv_cache import gather_block_kv_dequant
    k, v = gather_block_kv_dequant(pool_l, block_tables, q.dtype)
    return decode_attention_reference(q, k, v, pos, sm_scale=sm_scale)


def paged_decode_attention_reference(q, k_pool, v_pool, block_tables, pos,
                                     sm_scale=None):
    """jnp oracle: gather each row's blocks into a contiguous cache (the
    SAME gather the XLA fallback path uses — one definition, so the oracle
    cannot silently diverge from production), then run the contiguous
    reference."""
    from deepspeed_tpu.inference.kv_cache import gather_block_kv
    k, v = gather_block_kv(k_pool, v_pool, block_tables)
    return decode_attention_reference(q, k, v, pos, sm_scale=sm_scale)


def decode_attention_reference(q, k, v, pos, sm_scale=None):
    """jnp reference (numerics oracle for tests)."""
    B, H, hd = q.shape
    _, Hkv, M, _ = k.shape
    G = H // Hkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)
    qg = q.reshape(B, Hkv, G, hd)
    s = jnp.einsum("bkgd,bkmd->bkgm", qg.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    valid = (jnp.arange(M)[None, :] <= pos[:, None])[:, None, None, :]
    s = jnp.where(valid, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgm,bkmd->bkgd", p, v.astype(jnp.float32))
    return out.reshape(B, H, hd).astype(q.dtype)
