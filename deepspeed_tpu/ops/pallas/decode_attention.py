"""Single-token KV-cache attention (Pallas) — the decode hot op.

Analog of the reference's `softmax_context` CUDA kernel
(`csrc/transformer/inference/csrc/pt_binding.cpp`, softmax.cu — fused
KV-cache attention with alibi/rope handled upstream). Decode attention is
HBM-bandwidth bound: each step streams the whole K/V cache once. This kernel
keeps the online-softmax accumulator in VMEM, reads K/V in blocks, masks by the
current sequence position, and supports GQA by attending one kv head's group of
query heads per grid cell.

Layout: q [B, H, hd]; k/v cache [B, Hkv, M, hd]; pos [B] (current position,
inclusive — the new token's k/v must already be scattered at pos).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _use_interpret():
    return jax.default_backend() not in ("tpu", "axon")


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, *, sm_scale, block_m):
    # q_ref: [1, 1, G, hd]; k_ref/v_ref: [1, 1, M, hd]; pos_ref: SMEM [B]
    b = pl.program_id(0)
    pos = pos_ref[b]
    G, hd = q_ref.shape[2:]
    M = k_ref.shape[2]
    # native-dtype loads + dots (fp32 accumulate via preferred_element_type):
    # pre-casting K/V blocks to fp32 doubles the VMEM working set and VPU
    # traffic (same fix as flash_attention.py)
    in_dtype = q_ref.dtype
    q = q_ref[0, 0]

    nblocks = pl.cdiv(pos + 1, block_m)  # only blocks intersecting [0, pos]

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, 0, pl.ds(j * block_m, block_m), :]
        v = v_ref[0, 0, pl.ds(j * block_m, block_m), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        k_pos = j * block_m + jax.lax.broadcasted_iota(jnp.int32, (G, block_m), 1)
        s = jnp.where(k_pos <= pos, s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(in_dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((G, hd), jnp.float32)
    m0 = jnp.full((G,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((G,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nblocks, body, (acc0, m0, l0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def decode_attention(q, k, v, pos, sm_scale=None, block_m=None, interpret=None):
    """q: [B, H, hd]; k,v: [B, Hkv, M, hd]; pos: [B] int32 → [B, H, hd].

    Attends each query head to cache positions 0..pos inclusive. GQA-aware:
    H must be a multiple of Hkv; the group of G=H//Hkv query heads rides one
    grid cell with its kv head.

    `block_m=None` auto-selects: decode is HBM-bandwidth-bound (each step
    must read the whole live KV cache), and the inner-loop fixed overhead
    dominates at small blocks — measured on v5e at ctx 8192 / GQA 4 kv heads
    (median-of-6 interleaved marginal timings): 644 us/step at block 128 vs
    189 us at block 512, against a 164 us bandwidth floor and XLA's 174-204
    us. Large blocks put the kernel AT the floor; nothing can go below it.
    """
    if interpret is None:
        interpret = _use_interpret()
    B, H, hd = q.shape
    _, Hkv, M, _ = k.shape
    assert H % Hkv == 0
    G = H // Hkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)
    if block_m is None:
        block_m = 512 if M >= 1024 else 128
    block_m = min(block_m, M)
    if M % block_m != 0:  # pad cache length to block multiple (masked anyway)
        pad = block_m - M % block_m
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        M += pad

    qg = q.reshape(B, Hkv, G, hd)
    out = pl.pallas_call(
        functools.partial(_decode_kernel, sm_scale=sm_scale, block_m=block_m),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, Hkv),
            in_specs=[
                pl.BlockSpec((1, 1, G, hd), lambda b, h, pos_ref: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, M, hd), lambda b, h, pos_ref: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, M, hd), lambda b, h, pos_ref: (b, h, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, G, hd), lambda b, h, pos_ref: (b, h, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, hd), q.dtype),
        interpret=interpret,
    )(pos.astype(jnp.int32), qg, k, v)
    return out.reshape(B, H, hd)


def decode_attention_reference(q, k, v, pos, sm_scale=None):
    """jnp reference (numerics oracle for tests)."""
    B, H, hd = q.shape
    _, Hkv, M, _ = k.shape
    G = H // Hkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)
    qg = q.reshape(B, Hkv, G, hd)
    s = jnp.einsum("bkgd,bkmd->bkgm", qg.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    valid = (jnp.arange(M)[None, :] <= pos[:, None])[:, None, None, :]
    s = jnp.where(valid, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgm,bkmd->bkgd", p, v.astype(jnp.float32))
    return out.reshape(B, H, hd).astype(q.dtype)
