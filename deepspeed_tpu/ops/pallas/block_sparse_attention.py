"""Block-sparse flash attention (Pallas, TPU).

Real-kernel analog of the reference's Triton SDD/DSD block-sparse matmuls
(`ops/sparse_attention/matmul.py:17`): the `[H, n, n]` block layout from the
sparsity configs (`ops/sparse_attention.py`) folds into the flash kernel's KV
loop as a **visit list** — for every (head, q-tile) row the kernel iterates
ONLY the k-blocks with any live fine-granular cell, so compute and HBM
traffic scale with layout density, not T^2.

Mechanics:
  * host side: the fine layout (granularity `config.block`, normalized to 16)
    is coarsened to (block_q x 128) kernel granularity; per (h, qi) rows of
    visited k-block indices + counts are precomputed (static per layout+T)
    and passed as scalar-prefetch operands (SMEM — the splash-attention
    pattern; the TPU lowering requires SMEM for scalar/loop-bound data);
  * kernel side: `fori_loop` over the visit count with `pl.multiple_of`-
    aligned dynamic loads of the listed k-blocks; the fine 16-granular mask
    tile is picked out with a one-hot selection matmul and expanded to
    [block_q, 128] with two 0/1 expansion matmuls (all MXU-friendly — Mosaic
    cannot prove alignment of dynamic lane/sublane slices, so no slicing);
  * block_q defaults to 512 at long T: grid-step fixed overhead measured
    ~20us/step on v5e dominates at 128 (5.3ms of a 5.6ms pass at T=8k/5%),
    so fewer, fatter q tiles buy ~4x;
  * backward: same structure — dq iterates the q-row visit lists, dk/dv
    iterate the TRANSPOSED lists, matching the forward's visited set
    exactly, with the standard recomputation flash backward.

Numerics match the dense masked fp32 path (`SparseSelfAttention`'s fallback)
to fp32 tolerance on CPU (interpret) and to the MXU default-precision band on
hardware. Fully-dead query rows are rejected at build time (softmax over an
empty visit set is undefined; no shipped config produces them).
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
BLOCK_K = 128
FINE = 16                      # internal mask granularity
FPK_K = BLOCK_K // FINE        # fine cells per k block (8 — tiling-legal)


class BiasVmemBudgetError(ValueError):
    """The bias-streaming path cannot fit its VMEM slabs at this shape.

    A dedicated type so callers (SparseSelfAttention) can fall back to the
    dense path on exactly this condition without swallowing unrelated
    ValueErrors from inside the kernel."""


def _use_interpret():
    return jax.default_backend() not in ("tpu", "axon")


def _visit_lists(coarse):
    """coarse: [H, nq, nk] bool -> (counts [H,nq], idx [H,nq,max_visits]).
    idx rows are the visited k-block indices, padded with 0 (never read past
    counts)."""
    H, nq, nk = coarse.shape
    counts = coarse.sum(-1).astype(np.int32)
    maxv = max(1, int(counts.max()))
    idx = np.zeros((H, nq, maxv), np.int32)
    for h in range(H):
        for i in range(nq):
            cols = np.nonzero(coarse[h, i])[0]
            idx[h, i, :len(cols)] = cols
    return counts, idx


def _expander(fine_rows, width):
    """[fine_rows, width] 0/1 matrix E with E[a, i] = (i // FINE == a);
    fine_tile -> (E_q.T @ tile) @ E_k expands a 16-granular mask tile to
    kernel granularity using two small matmuls."""
    a = jax.lax.broadcasted_iota(jnp.int32, (fine_rows, width), 0)
    i = jax.lax.broadcasted_iota(jnp.int32, (fine_rows, width), 1)
    return (i // FINE == a).astype(jnp.float32)


def _expand_mask(tile, width_q, width_k):
    """tile: [fq, fk] f32 -> [width_q, width_k] f32 (0/1)."""
    Eq = _expander(tile.shape[0], width_q)
    Ek = _expander(tile.shape[1], width_k)
    return jax.lax.dot_general(
        jax.lax.dot_general(Eq, tile, (((0,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32),
        Ek, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)


def _select_cols(layout_row, j, width):
    """layout_row: [fq, n16]; select columns j*width..+width via a one-hot
    selection matmul (Mosaic cannot prove alignment of dynamic lane slices;
    a matmul against an iota-built selector is always legal)."""
    n16 = layout_row.shape[1]
    c = jax.lax.broadcasted_iota(jnp.int32, (n16, width), 0)
    b = jax.lax.broadcasted_iota(jnp.int32, (n16, width), 1)
    S = (c == j * width + b).astype(jnp.float32)
    return jax.lax.dot_general(layout_row, S, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _select_row(mat, i):
    """mat: [n_rows, W]; pick row i as [W] via one-hot matmul (dynamic
    sublane slicing has the same Mosaic alignment restriction)."""
    n_rows = mat.shape[0]
    r = jax.lax.broadcasted_iota(jnp.int32, (1, n_rows), 1)
    onehot = (r == i).astype(jnp.float32)
    row = jax.lax.dot_general(onehot, mat, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return row.reshape((mat.shape[1],))


def _causal_tile(qi, block_q, j, transpose=False):
    """[block_q, BLOCK_K] bool (or its transpose): token-granular q >= k for
    q-tile qi vs k-block j — the layout's unidirectional tril is only
    block-granular, so diagonal blocks need this intra-block mask."""
    shape = (BLOCK_K, block_q) if transpose else (block_q, BLOCK_K)
    qdim, kdim = (1, 0) if transpose else (0, 1)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, shape, qdim)
    k_pos = j * BLOCK_K + jax.lax.broadcasted_iota(jnp.int32, shape, kdim)
    return q_pos >= k_pos


def _fwd_kernel(counts_ref, idx_ref, layout_ref, *rest, causal, has_bias,
                has_kpm):
    # counts_ref: [H, nbq] SMEM; idx_ref: [H, nbq, maxv] SMEM;
    # layout_ref: [fq, n16] f32 (this q-tile's fine mask rows);
    # optional bias_ref: [nbk, block_q, BLOCK_K] (this (h, qi)'s additive-bias
    # tiles — dynamic leading-index load per visited k-block);
    # optional kvb_ref: [nbk, BLOCK_K] (this batch's key-padding additive row);
    # q_ref: [block_q, D]; k/v_ref: [T, D]; lse_ref: [nbq, block_q] whole
    rest = list(rest)
    bias_ref = rest.pop(0) if has_bias else None
    kvb_ref = rest.pop(0) if has_kpm else None
    q_ref, k_ref, v_ref, o_ref, lse_ref = rest
    h, qi = pl.program_id(1), pl.program_id(2)
    block_q, D = q_ref.shape
    # dots run on native-dtype operands (bf16 in, fp32 accumulate) — casting
    # inputs to fp32 first forces the MXU's ~4x-slower fp32 path (same fix as
    # flash_attention.py); p/ds narrow back to the input dtype for the second
    # dot of each pair, softmax stats stay fp32
    in_dtype = q_ref.dtype
    q = q_ref[:, :]
    n_visit = counts_ref[h, qi]

    def body(t, carry):
        acc, m_prev, l_prev = carry
        j = idx_ref[h, qi, t]
        start = pl.multiple_of(j * BLOCK_K, BLOCK_K)
        k = k_ref[pl.ds(start, BLOCK_K), :]
        v = v_ref[pl.ds(start, BLOCK_K), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if has_bias:
            s = s + bias_ref[j]
        if has_kpm:
            s = s + _select_row(kvb_ref[:, :], j)[None, :]
        tile = _select_cols(layout_ref[:, :], j, FPK_K)
        s = jnp.where(_expand_mask(tile, block_q, BLOCK_K) > 0, s, NEG_INF)
        if causal:
            s = jnp.where(_causal_tile(qi, block_q, j), s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(in_dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_visit, body, (acc0, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[:, :] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[qi, :] = (m + jnp.log(l_safe)).astype(jnp.float32)


def _bwd_dq_kernel(counts_ref, idx_ref, layout_ref, *rest, causal, has_bias,
                   has_kpm, want_dbias, swapped_grid):
    # swapped_grid (learned bias with a single shared-head slab): grid is
    # (b, qi, h) so the head-broadcast dbias block's revisits across h are
    # CONSECUTIVE — Pallas only guarantees output-block accumulation across
    # back-to-back grid steps (a revisit after the block was swapped out
    # loses the writes). want_dbias is False for non-learned masks: the bias
    # still masks s, but no dense [T, T] gradient output is materialized.
    rest = list(rest)
    bias_ref = rest.pop(0) if has_bias else None
    kvb_ref = rest.pop(0) if has_kpm else None
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref = rest[:7]
    dbias_ref = rest[7] if want_dbias else None
    if swapped_grid:
        qi, h = pl.program_id(1), pl.program_id(2)
    else:
        h, qi = pl.program_id(1), pl.program_id(2)
    block_q, D = q_ref.shape
    in_dtype = q_ref.dtype
    q = q_ref[:, :]
    do = do_ref[:, :]
    lse = lse_ref[qi, :]
    delta = delta_ref[qi, :]
    n_visit = counts_ref[h, qi]

    if want_dbias:
        # zero the dbias block on first visit: every program owns its block
        # when the bias is per-head; the shared-slab case revisits across h
        # (consecutive under swapped_grid) and zeroes only at h == 0
        @pl.when(pl.program_id(2) == 0 if swapped_grid else True)
        def _zero():
            dbias_ref[...] = jnp.zeros(dbias_ref.shape, dbias_ref.dtype)

    def body(t, dq):
        j = idx_ref[h, qi, t]
        start = pl.multiple_of(j * BLOCK_K, BLOCK_K)
        k = k_ref[pl.ds(start, BLOCK_K), :]
        v = v_ref[pl.ds(start, BLOCK_K), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if has_bias:
            s = s + bias_ref[j]
        if has_kpm:
            s = s + _select_row(kvb_ref[:, :], j)[None, :]
        tile = _select_cols(layout_ref[:, :], j, FPK_K)
        s = jnp.where(_expand_mask(tile, block_q, BLOCK_K) > 0, s, NEG_INF)
        if causal:
            s = jnp.where(_causal_tile(qi, block_q, j), s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds_f32 = p * (dp - delta[:, None])
        if want_dbias:
            # dL/dbias for this tile: the bias enters s additively AFTER the
            # q-side sm_scale folding, so dbias == ds (accumulated over batch
            # outside, and over heads here when the slab is head-shared)
            dbias_ref[j] = dbias_ref[j] + ds_f32
        ds = ds_f32.astype(in_dtype)
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n_visit, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[:, :] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(counts_ref, idx_ref, layout_ref, *rest, block_q, causal,
                    has_bias, has_kpm):
    # transposed visit lists: for THIS k-block, which q-tiles touch it.
    # layout_ref is this k-row of layout^T: [FPK_K, n16].
    # optional bias_ref: [nbq, block_q, BLOCK_K] (this (h, ki)'s column of
    # the blocked bias in the S orientation — each picked tile is transposed
    # in-register, saving a dense-T^2 HBM copy); optional kvbT_ref:
    # [BLOCK_K, 1] (this (b, ki)'s key-padding additive column).
    rest = list(rest)
    bias_ref = rest.pop(0) if has_bias else None
    kvbT_ref = rest.pop(0) if has_kpm else None
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref = rest
    h, ki = pl.program_id(1), pl.program_id(2)
    block_k, D = dk_ref.shape
    in_dtype = k_ref.dtype
    k = k_ref[:, :]
    v = v_ref[:, :]
    n_visit = counts_ref[h, ki]
    fq = block_q // FINE

    def body(t, carry):
        dk, dv = carry
        i = idx_ref[h, ki, t]
        start = pl.multiple_of(i * block_q, block_q)
        q = q_ref[pl.ds(start, block_q), :]
        do = do_ref[pl.ds(start, block_q), :]
        lse = _select_row(lse_ref[:, :], i)
        delta = _select_row(delta_ref[:, :], i)
        sT = jax.lax.dot_general(k, q, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)  # [bk, bq]
        if has_bias:
            sT = sT + bias_ref[i].T                                   # -> [bk, bq]
        if has_kpm:
            sT = sT + kvbT_ref[:, :]                                  # [bk, 1]
        tileT = _select_cols(layout_ref[:, :], i, fq)                 # [FPK_K, fq]
        sT = jnp.where(_expand_mask(tileT, BLOCK_K, block_q) > 0, sT, NEG_INF)
        if causal:
            sT = jnp.where(_causal_tile(i, block_q, ki, transpose=True),
                           sT, NEG_INF)
        pT = jnp.exp(sT - lse[None, :])
        dv = dv + jax.lax.dot_general(pT.astype(in_dtype), do, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dpT = jax.lax.dot_general(v, do, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)  # [bk, bq]
        dsT = (pT * (dpT - delta[None, :])).astype(in_dtype)
        dk = dk + jax.lax.dot_general(dsT, q, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((block_k, D), jnp.float32)
    dv0 = jnp.zeros((block_k, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, n_visit, body, (dk0, dv0))
    dk_ref[:, :] = dk.astype(dk_ref.dtype)
    dv_ref[:, :] = dv.astype(dv_ref.dtype)


def _normalize_16(layout, block):
    """Re-express a [H, T/block, T/block] layout at the internal 16
    granularity (expand coarse blocks; group finer ones by any())."""
    layout = np.asarray(layout, bool)
    if block == FINE:
        return layout
    H, n, _ = layout.shape
    if block > FINE:
        assert block % FINE == 0, f"layout block {block} must be a multiple of {FINE}"
        r = block // FINE
        return np.kron(layout, np.ones((r, r), bool))
    r = FINE // block
    assert r * block == FINE, f"layout block {block} must divide {FINE}"
    n16 = n // r
    return layout.reshape(H, n16, r, n16, r).any((2, 4))


def _build(layout, T, block, block_q, causal=False):
    """Host-side static prep: 16-granular fine masks (f32, both orientations)
    + visit lists at (block_q x BLOCK_K) granularity, all numpy."""
    fine = _normalize_16(layout, block)                # [H, n16, n16]
    H, n16, _ = fine.shape
    assert n16 * FINE == T, (n16, T)
    assert T % block_q == 0 and T % BLOCK_K == 0, (T, block_q)
    nbq, nbk = T // block_q, T // BLOCK_K
    fq = block_q // FINE
    coarse = fine.reshape(H, nbq, fq, nbk, FPK_K).any((2, 4))
    assert coarse.any(-1).all(), \
        "sparsity layout has a fully-masked query row (undefined softmax)"
    if causal:
        # the intersection with the token-granular causal mask must also keep
        # >=1 key per query row (else m stays -inf and the kernel emits a
        # spurious mean-of-V with bogus grads): a fine row survives iff some
        # visited fine tile lies on or below the diagonal — a strictly-upper
        # layout row dies even though the layout-only check above passes
        assert np.tril(np.ones((n16, n16), bool))[None].__and__(fine).any(-1).all(), \
            "causal=True: some query row's visited blocks are entirely in " \
            "the future (fully masked after the causal intersection)"
    counts, idx = _visit_lists(coarse)
    countsT, idxT = _visit_lists(coarse.transpose(0, 2, 1))
    fineT = fine.transpose(0, 2, 1)
    return (counts, idx, fine.astype(np.float32), countsT, idxT,
            fineT.astype(np.float32), nbq, nbk)


def block_sparse_attention(q, k, v, layout, block=16, sm_scale=None,
                           block_q=None, causal=False, interpret=None,
                           bias=None, key_padding_mask=None,
                           bias_needs_grad=None):
    """q,k,v: [B, H, T, D]; layout: [H, T//block, T//block] bool (numpy,
    static). Differentiable; compute scales with layout density. The softmax
    scale is folded into q once up front (not per-block).

    `causal=True` adds TOKEN-granular q>=k masking inside visited blocks —
    the unidirectional layouts' tril is block-granular only (a diagonal
    block is fully open, leaking up to block-1 future tokens), so causal
    LMs must set this.

    `bias`: optional additive score bias [T, T] or [Hb, T, T] with Hb in
    {1, H} — the reference's rpe / additive attn_mask, streamed IN-KERNEL
    (reference `ops/sparse_attention/softmax.py` streams these through its
    Triton kernel the same way). Differentiable (rpe may be learned): the
    backward accumulates dbias inside the dq kernel over the visited blocks
    only. `bias_needs_grad` (default: True when bias is given): pass False
    for NON-learned masks — the dbias accumulation materializes a dense
    [B, Hb, T, T] fp32 output, which is pure waste when the gradient is
    discarded (256 MB x B at T=8k). `key_padding_mask`: optional [B, T]
    bool, True = attend — masked keys get -1e30 added before the online
    softmax, matching the dense path's where(). Batched [B, T, T] masks
    don't fit the per-head slab streaming; `SparseSelfAttention` falls back
    to dense (with a warning) for those."""
    if interpret is None:
        interpret = _use_interpret()
    B, H, T, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    if block_q is None:
        block_q = 512 if T >= 2048 else 128
        while block_q > 128 and T % block_q != 0:
            block_q //= 2
        if bias is not None:
            # the bias slab a q-tile program holds in VMEM is [nbk, block_q,
            # BLOCK_K] f32 = T*block_q*4 bytes; cap it at ~2 MiB (next to
            # k/v/q tiles) by shrinking the AUTO-chosen q tile (an explicitly
            # passed block_q is respected)
            while block_q > 128 and T * block_q * 4 > 2 * 2**20:
                block_q //= 2
    if bias_needs_grad is None:
        bias_needs_grad = bias is not None
    if bias is not None:
        # fail loudly where the bias streaming cannot fit VMEM: per-program
        # resident slabs are the bias tile stack (T*block_q*4), the dbias
        # output block (same size, learned bias only), and the [T, D] k/v/q
        # slabs — Mosaic's allocation failure at compile time is far less
        # actionable than this message
        itemsize = jnp.dtype(q.dtype).itemsize
        est = (T * block_q * 4 * (2 if bias_needs_grad else 1)
               + 4 * T * D * itemsize)
        if est > 12 * 2**20:
            raise BiasVmemBudgetError(
                f"block-sparse bias streaming at T={T}, block_q={block_q}, "
                f"D={D} needs ~{est / 2**20:.0f} MiB of VMEM-resident slabs "
                "(>12 MiB budget): pass a smaller block_q, drop the bias "
                "(mask via the layout), or use bias_needs_grad=False for "
                "non-learned masks")
    layout = np.asarray(layout, bool)
    if layout.shape[0] == 1 and H > 1:
        # head-broadcast layout (the configs allow num_heads=1 shared layouts)
        layout = np.broadcast_to(layout, (H,) + layout.shape[1:])
    assert layout.shape[0] == H, (layout.shape, H)
    args = _build_cached(layout, T, block, block_q, bool(causal))
    nbq, nbk = T // block_q, T // BLOCK_K
    bias_q = None
    if bias is not None:
        bias = jnp.asarray(bias, jnp.float32)
        if bias.ndim == 2:
            bias = bias[None]
        assert bias.shape in ((1, T, T), (H, T, T)), (bias.shape, H, T)
        # blocked per (q-tile, k-block): [Hb, nbq, nbk, block_q, BLOCK_K]
        bias_q = bias.reshape(bias.shape[0], nbq, block_q, nbk, BLOCK_K) \
                     .transpose(0, 1, 3, 2, 4)
    kvb = None
    if key_padding_mask is not None:
        kpm = jnp.asarray(key_padding_mask)
        assert kpm.shape == (B, T), (kpm.shape, B, T)
        kvb = jnp.where(kpm, 0.0, NEG_INF).astype(jnp.float32) \
                 .reshape(B, nbk, BLOCK_K)
    return _sparse(q, k, v, *args, bias_q, kvb, float(sm_scale), int(block_q),
                   bool(causal), bool(interpret), bool(bias_needs_grad))


_BUILD_CACHE = {}


def _build_cached(layout, T, block, block_q, causal=False):
    """Memoize _build's host-side visit-list loops — eager per-token callers
    would otherwise redo O(H*nq*nk) Python work every call. Cached values are
    HOST numpy, converted per call site: caching jnp arrays would capture
    tracers when the first call happens under a jit trace and leak them into
    later traces (observed UnexpectedTracerError)."""
    # key on the bytes themselves, not hash(): a 64-bit collision between two
    # same-shape layouts would silently serve the wrong sparsity pattern
    key = (layout.tobytes(), layout.shape, T, block, block_q, causal)
    if key not in _BUILD_CACHE:
        (counts, idx, fine, countsT, idxT, fineT, _, _) = \
            _build(layout, T, block, block_q, causal)
        _BUILD_CACHE[key] = (counts, idx, fine, countsT, idxT, fineT)
        if len(_BUILD_CACHE) > 32:  # bound resident mask tables
            _BUILD_CACHE.pop(next(iter(_BUILD_CACHE)))
    return tuple(jnp.asarray(a) for a in _BUILD_CACHE[key])


@functools.partial(jax.custom_vjp, nondiff_argnums=(11, 12, 13, 14, 15))
def _sparse(q, k, v, counts, idx, fine, countsT, idxT, fineT, bias_q, kvb,
            sm_scale, block_q, causal, interpret, need_dbias):
    out, _ = _sparse_fwd_impl(q, k, v, counts, idx, fine, bias_q, kvb,
                              sm_scale, block_q, causal, interpret)
    return out


def _bias_specs(bias_q, kvb, index_b, index_hqi):
    """BlockSpecs for the optional bias/key-padding inputs of the fwd and dq
    kernels. index_b/index_hqi: pick (b,) / (h, qi) out of the grid args."""
    specs = []
    if bias_q is not None:
        Hb, nbq, nbk, bq, bk = bias_q.shape
        specs.append(pl.BlockSpec(
            (None, None, nbk, bq, bk),
            lambda *g, Hb=Hb: (index_hqi(*g)[0] if Hb > 1 else 0,
                               index_hqi(*g)[1], 0, 0, 0)))
    if kvb is not None:
        _, nbk, bk = kvb.shape
        specs.append(pl.BlockSpec((None, nbk, bk),
                                  lambda *g: (index_b(*g), 0, 0)))
    return specs


def _sparse_fwd_impl(q, k, v, counts, idx, fine, bias_q, kvb, sm_scale,
                     block_q, causal, interpret):
    B, H, T, D = q.shape
    nbq = T // block_q
    n16 = fine.shape[-1]
    fq = block_q // FINE
    qs = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)
    extra_specs = _bias_specs(bias_q, kvb, lambda b, h, qi, *_: b,
                              lambda b, h, qi, *_: (h, qi))
    extra_args = [a for a in (bias_q, kvb) if a is not None]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, nbq),
        in_specs=[
            pl.BlockSpec((None, None, fq, n16),
                         lambda b, h, qi, *_: (h, qi, 0, 0)),
            *extra_specs,
            pl.BlockSpec((None, None, block_q, D),
                         lambda b, h, qi, *_: (b, h, qi, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h, qi, *_: (b, h, 0, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h, qi, *_: (b, h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, D),
                         lambda b, h, qi, *_: (b, h, qi, 0)),
            pl.BlockSpec((None, None, nbq, block_q),
                         lambda b, h, qi, *_: (b, h, 0, 0)),
        ],
    )
    # fine mask rows regrouped per q-tile: [H, nbq, fq, n16] -> block (fq, n16)
    fine_q = fine.reshape(H, nbq, fq, n16)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal,
                          has_bias=bias_q is not None, has_kpm=kvb is not None),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, nbq, block_q), jnp.float32),
        ],
        interpret=interpret,
    )(counts, idx, fine_q, *extra_args, qs, k, v)
    return out, lse


def _sparse_vjp_fwd(q, k, v, counts, idx, fine, countsT, idxT, fineT, bias_q,
                    kvb, sm_scale, block_q, causal, interpret, need_dbias):
    out, lse = _sparse_fwd_impl(q, k, v, counts, idx, fine, bias_q, kvb,
                                sm_scale, block_q, causal, interpret)
    return out, (q, k, v, out, lse, counts, idx, fine, countsT, idxT, fineT,
                 bias_q, kvb)


def _sparse_vjp_bwd(sm_scale, block_q, causal, interpret, need_dbias, res, g):
    (q, k, v, out, lse, counts, idx, fine, countsT, idxT, fineT,
     bias_q, kvb) = res
    B, H, T, D = q.shape
    nbq, nbk = T // block_q, T // BLOCK_K
    n16 = fine.shape[-1]
    fq = block_q // FINE
    do = g
    has_bias, has_kpm = bias_q is not None, kvb is not None
    want_dbias = has_bias and need_dbias
    Hb = bias_q.shape[0] if has_bias else 0
    qs = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = delta.reshape(B, H, nbq, block_q)
    fine_q = fine.reshape(H, nbq, fq, n16)

    # head-shared LEARNED bias slab: dbias accumulates across h IN-kernel,
    # which needs the revisits consecutive -> grid (b, qi, h); per-head slabs
    # (and non-learned masks, which emit no dbias) keep the cache-friendly
    # (b, h, qi) order
    swapped = want_dbias and Hb == 1
    if swapped:
        grid = (B, nbq, H)
        gb, gh, gqi = (lambda b, qi, h, *_: b), (lambda b, qi, h, *_: h), \
                      (lambda b, qi, h, *_: qi)
    else:
        grid = (B, H, nbq)
        gb, gh, gqi = (lambda b, h, qi, *_: b), (lambda b, h, qi, *_: h), \
                      (lambda b, h, qi, *_: qi)
    extra_specs = _bias_specs(bias_q, kvb, gb,
                              lambda *a: (gh(*a), gqi(*a)))
    extra_args = [a for a in (bias_q, kvb) if a is not None]
    dq_out_specs = pl.BlockSpec((None, None, block_q, D),
                                lambda *a: (gb(*a), gh(*a), gqi(*a), 0))
    dq_out_shape = jax.ShapeDtypeStruct((B, H, T, D), q.dtype)
    if want_dbias:
        # dbias is per-batch (summed after): cross-b accumulation would need
        # b-innermost revisits, which would refetch the [T, D] k/v slabs every
        # program. [B, Hb, nbq, nbk, bq, bk] f32 — dense T^2; only emitted
        # for a LEARNED bias (need_dbias), never for plain masks.
        dq_out_specs = [dq_out_specs, pl.BlockSpec(
            (None, None, None, nbk, block_q, BLOCK_K),
            lambda *a: (gb(*a), gh(*a) if Hb > 1 else 0, gqi(*a), 0, 0, 0))]
        dq_out_shape = [dq_out_shape, jax.ShapeDtypeStruct(
            (B, Hb, nbq, nbk, block_q, BLOCK_K), jnp.float32)]
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, fq, n16),
                         lambda *a: (gh(*a), gqi(*a), 0, 0)),
            *extra_specs,
            pl.BlockSpec((None, None, block_q, D),
                         lambda *a: (gb(*a), gh(*a), gqi(*a), 0)),
            pl.BlockSpec((None, None, T, D), lambda *a: (gb(*a), gh(*a), 0, 0)),
            pl.BlockSpec((None, None, T, D), lambda *a: (gb(*a), gh(*a), 0, 0)),
            pl.BlockSpec((None, None, block_q, D),
                         lambda *a: (gb(*a), gh(*a), gqi(*a), 0)),
            pl.BlockSpec((None, None, nbq, block_q),
                         lambda *a: (gb(*a), gh(*a), 0, 0)),
            pl.BlockSpec((None, None, nbq, block_q),
                         lambda *a: (gb(*a), gh(*a), 0, 0)),
        ],
        out_specs=dq_out_specs,
    )
    dq_res = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, has_bias=has_bias,
                          has_kpm=has_kpm, want_dbias=want_dbias,
                          swapped_grid=swapped),
        grid_spec=dq_spec, out_shape=dq_out_shape,
        interpret=interpret,
    )(counts, idx, fine_q, *extra_args, qs, k, v, do, lse, delta)
    dbias_q = None
    if want_dbias:
        dq, dbias_raw = dq_res
        dbias_q = dbias_raw.sum(axis=0)
    else:
        dq = dq_res
    dq = (dq.astype(jnp.float32) * sm_scale).astype(q.dtype)

    # fineT rows regrouped per k-block: [H, nbk, FPK_K, n16]
    fineT_k = fineT.reshape(H, nbk, FPK_K, n16)
    dkv_extra_specs = []
    dkv_extra_args = []
    if has_bias:
        # stream the SAME blocked bias_q — no transposed HBM copy (an extra
        # dense-T^2 tensor + full transpose per step): per (h, ki) the slab
        # is bias_q[h?, :, ki] = [nbq, block_q, BLOCK_K] and the kernel
        # transposes each picked tile to the sT orientation in-register
        dkv_extra_specs.append(pl.BlockSpec(
            (None, nbq, None, block_q, BLOCK_K),
            lambda b, h, ki, *_, Hb=Hb: (h if Hb > 1 else 0, 0, ki, 0, 0)))
        dkv_extra_args.append(bias_q)
    if has_kpm:
        kvbT = kvb[..., None]                       # [B, nbk, BLOCK_K, 1]
        dkv_extra_specs.append(pl.BlockSpec(
            (None, None, BLOCK_K, 1), lambda b, h, ki, *_: (b, ki, 0, 0)))
        dkv_extra_args.append(kvbT)
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, nbk),
        in_specs=[
            pl.BlockSpec((None, None, FPK_K, n16),
                         lambda b, h, ki, *_: (h, ki, 0, 0)),
            *dkv_extra_specs,
            pl.BlockSpec((None, None, T, D), lambda b, h, ki, *_: (b, h, 0, 0)),
            pl.BlockSpec((None, None, BLOCK_K, D),
                         lambda b, h, ki, *_: (b, h, ki, 0)),
            pl.BlockSpec((None, None, BLOCK_K, D),
                         lambda b, h, ki, *_: (b, h, ki, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h, ki, *_: (b, h, 0, 0)),
            pl.BlockSpec((None, None, nbq, block_q),
                         lambda b, h, ki, *_: (b, h, 0, 0)),
            pl.BlockSpec((None, None, nbq, block_q),
                         lambda b, h, ki, *_: (b, h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, BLOCK_K, D),
                         lambda b, h, ki, *_: (b, h, ki, 0)),
            pl.BlockSpec((None, None, BLOCK_K, D),
                         lambda b, h, ki, *_: (b, h, ki, 0)),
        ],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, causal=causal,
                          has_bias=has_bias, has_kpm=has_kpm),
        grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        ],
        interpret=interpret,
    )(countsT, idxT, fineT_k, *dkv_extra_args, qs, k, v, do, lse, delta)
    # dk needs no extra sm_scale: the kernel contracts ds^T against the
    # PRE-SCALED q, which already carries the factor (dq does need it — its
    # contraction is against the unscaled k)

    return (dq, dk, dv, None, None, None, None, None, None, dbias_q, None)


_sparse.defvjp(_sparse_vjp_fwd, _sparse_vjp_bwd)
