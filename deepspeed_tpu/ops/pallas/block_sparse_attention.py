"""Block-sparse flash attention (Pallas, TPU).

Real-kernel analog of the reference's Triton SDD/DSD block-sparse matmuls
(`ops/sparse_attention/matmul.py:17`): the `[H, n, n]` block layout from the
sparsity configs (`ops/sparse_attention.py`) folds into the flash kernel's KV
loop as a **visit list** — for every (head, q-tile) row the kernel iterates
ONLY the k-blocks with any live fine-granular cell, so compute and HBM
traffic scale with layout density, not T^2.

Mechanics:
  * host side: the fine layout (granularity `config.block`, normalized to 16)
    is coarsened to (block_q x 128) kernel granularity; per (h, qi) rows of
    visited k-block indices + counts are precomputed (static per layout+T)
    and passed as scalar-prefetch operands (SMEM — the splash-attention
    pattern; the TPU lowering requires SMEM for scalar/loop-bound data);
  * kernel side: `fori_loop` over the visit count with `pl.multiple_of`-
    aligned dynamic loads of the listed k-blocks; the fine 16-granular mask
    tile is picked out with a one-hot selection matmul and expanded to
    [block_q, 128] with two 0/1 expansion matmuls (all MXU-friendly — Mosaic
    cannot prove alignment of dynamic lane/sublane slices, so no slicing);
  * block_q defaults to 512 at long T: grid-step fixed overhead measured
    ~20us/step on v5e dominates at 128 (5.3ms of a 5.6ms pass at T=8k/5%),
    so fewer, fatter q tiles buy ~4x;
  * backward: same structure — dq iterates the q-row visit lists, dk/dv
    iterate the TRANSPOSED lists, matching the forward's visited set
    exactly, with the standard recomputation flash backward.

Numerics match the dense masked fp32 path (`SparseSelfAttention`'s fallback)
to fp32 tolerance on CPU (interpret) and to the MXU default-precision band on
hardware. Fully-dead query rows are rejected at build time (softmax over an
empty visit set is undefined; no shipped config produces them).
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
BLOCK_K = 128
FINE = 16                      # internal mask granularity
FPK_K = BLOCK_K // FINE        # fine cells per k block (8 — tiling-legal)


def _use_interpret():
    return jax.default_backend() not in ("tpu", "axon")


def _visit_lists(coarse):
    """coarse: [H, nq, nk] bool -> (counts [H,nq], idx [H,nq,max_visits]).
    idx rows are the visited k-block indices, padded with 0 (never read past
    counts)."""
    H, nq, nk = coarse.shape
    counts = coarse.sum(-1).astype(np.int32)
    maxv = max(1, int(counts.max()))
    idx = np.zeros((H, nq, maxv), np.int32)
    for h in range(H):
        for i in range(nq):
            cols = np.nonzero(coarse[h, i])[0]
            idx[h, i, :len(cols)] = cols
    return counts, idx


def _expander(fine_rows, width):
    """[fine_rows, width] 0/1 matrix E with E[a, i] = (i // FINE == a);
    fine_tile -> (E_q.T @ tile) @ E_k expands a 16-granular mask tile to
    kernel granularity using two small matmuls."""
    a = jax.lax.broadcasted_iota(jnp.int32, (fine_rows, width), 0)
    i = jax.lax.broadcasted_iota(jnp.int32, (fine_rows, width), 1)
    return (i // FINE == a).astype(jnp.float32)


def _expand_mask(tile, width_q, width_k):
    """tile: [fq, fk] f32 -> [width_q, width_k] f32 (0/1)."""
    Eq = _expander(tile.shape[0], width_q)
    Ek = _expander(tile.shape[1], width_k)
    return jax.lax.dot_general(
        jax.lax.dot_general(Eq, tile, (((0,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32),
        Ek, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)


def _select_cols(layout_row, j, width):
    """layout_row: [fq, n16]; select columns j*width..+width via a one-hot
    selection matmul (Mosaic cannot prove alignment of dynamic lane slices;
    a matmul against an iota-built selector is always legal)."""
    n16 = layout_row.shape[1]
    c = jax.lax.broadcasted_iota(jnp.int32, (n16, width), 0)
    b = jax.lax.broadcasted_iota(jnp.int32, (n16, width), 1)
    S = (c == j * width + b).astype(jnp.float32)
    return jax.lax.dot_general(layout_row, S, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _select_row(mat, i):
    """mat: [n_rows, W]; pick row i as [W] via one-hot matmul (dynamic
    sublane slicing has the same Mosaic alignment restriction)."""
    n_rows = mat.shape[0]
    r = jax.lax.broadcasted_iota(jnp.int32, (1, n_rows), 1)
    onehot = (r == i).astype(jnp.float32)
    row = jax.lax.dot_general(onehot, mat, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return row.reshape((mat.shape[1],))


def _causal_tile(qi, block_q, j, transpose=False):
    """[block_q, BLOCK_K] bool (or its transpose): token-granular q >= k for
    q-tile qi vs k-block j — the layout's unidirectional tril is only
    block-granular, so diagonal blocks need this intra-block mask."""
    shape = (BLOCK_K, block_q) if transpose else (block_q, BLOCK_K)
    qdim, kdim = (1, 0) if transpose else (0, 1)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, shape, qdim)
    k_pos = j * BLOCK_K + jax.lax.broadcasted_iota(jnp.int32, shape, kdim)
    return q_pos >= k_pos


def _fwd_kernel(counts_ref, idx_ref, layout_ref, q_ref, k_ref, v_ref,
                o_ref, lse_ref, *, causal):
    # counts_ref: [H, nbq] SMEM; idx_ref: [H, nbq, maxv] SMEM;
    # layout_ref: [fq, n16] f32 (this q-tile's fine mask rows);
    # q_ref: [block_q, D]; k/v_ref: [T, D]; lse_ref: [nbq, block_q] whole
    h, qi = pl.program_id(1), pl.program_id(2)
    block_q, D = q_ref.shape
    # dots run on native-dtype operands (bf16 in, fp32 accumulate) — casting
    # inputs to fp32 first forces the MXU's ~4x-slower fp32 path (same fix as
    # flash_attention.py); p/ds narrow back to the input dtype for the second
    # dot of each pair, softmax stats stay fp32
    in_dtype = q_ref.dtype
    q = q_ref[:, :]
    n_visit = counts_ref[h, qi]

    def body(t, carry):
        acc, m_prev, l_prev = carry
        j = idx_ref[h, qi, t]
        start = pl.multiple_of(j * BLOCK_K, BLOCK_K)
        k = k_ref[pl.ds(start, BLOCK_K), :]
        v = v_ref[pl.ds(start, BLOCK_K), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        tile = _select_cols(layout_ref[:, :], j, FPK_K)
        s = jnp.where(_expand_mask(tile, block_q, BLOCK_K) > 0, s, NEG_INF)
        if causal:
            s = jnp.where(_causal_tile(qi, block_q, j), s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(in_dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_visit, body, (acc0, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[:, :] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[qi, :] = (m + jnp.log(l_safe)).astype(jnp.float32)


def _bwd_dq_kernel(counts_ref, idx_ref, layout_ref, q_ref, k_ref, v_ref,
                   do_ref, lse_ref, delta_ref, dq_ref, *, causal):
    h, qi = pl.program_id(1), pl.program_id(2)
    block_q, D = q_ref.shape
    in_dtype = q_ref.dtype
    q = q_ref[:, :]
    do = do_ref[:, :]
    lse = lse_ref[qi, :]
    delta = delta_ref[qi, :]
    n_visit = counts_ref[h, qi]

    def body(t, dq):
        j = idx_ref[h, qi, t]
        start = pl.multiple_of(j * BLOCK_K, BLOCK_K)
        k = k_ref[pl.ds(start, BLOCK_K), :]
        v = v_ref[pl.ds(start, BLOCK_K), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        tile = _select_cols(layout_ref[:, :], j, FPK_K)
        s = jnp.where(_expand_mask(tile, block_q, BLOCK_K) > 0, s, NEG_INF)
        if causal:
            s = jnp.where(_causal_tile(qi, block_q, j), s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(in_dtype)
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n_visit, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[:, :] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(counts_ref, idx_ref, layout_ref, q_ref, k_ref, v_ref,
                    do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, block_q,
                    causal):
    # transposed visit lists: for THIS k-block, which q-tiles touch it.
    # layout_ref is this k-row of layout^T: [FPK_K, n16].
    h, ki = pl.program_id(1), pl.program_id(2)
    block_k, D = dk_ref.shape
    in_dtype = k_ref.dtype
    k = k_ref[:, :]
    v = v_ref[:, :]
    n_visit = counts_ref[h, ki]
    fq = block_q // FINE

    def body(t, carry):
        dk, dv = carry
        i = idx_ref[h, ki, t]
        start = pl.multiple_of(i * block_q, block_q)
        q = q_ref[pl.ds(start, block_q), :]
        do = do_ref[pl.ds(start, block_q), :]
        lse = _select_row(lse_ref[:, :], i)
        delta = _select_row(delta_ref[:, :], i)
        sT = jax.lax.dot_general(k, q, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)  # [bk, bq]
        tileT = _select_cols(layout_ref[:, :], i, fq)                 # [FPK_K, fq]
        sT = jnp.where(_expand_mask(tileT, BLOCK_K, block_q) > 0, sT, NEG_INF)
        if causal:
            sT = jnp.where(_causal_tile(i, block_q, ki, transpose=True),
                           sT, NEG_INF)
        pT = jnp.exp(sT - lse[None, :])
        dv = dv + jax.lax.dot_general(pT.astype(in_dtype), do, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dpT = jax.lax.dot_general(v, do, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)  # [bk, bq]
        dsT = (pT * (dpT - delta[None, :])).astype(in_dtype)
        dk = dk + jax.lax.dot_general(dsT, q, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((block_k, D), jnp.float32)
    dv0 = jnp.zeros((block_k, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, n_visit, body, (dk0, dv0))
    dk_ref[:, :] = dk.astype(dk_ref.dtype)
    dv_ref[:, :] = dv.astype(dv_ref.dtype)


def _normalize_16(layout, block):
    """Re-express a [H, T/block, T/block] layout at the internal 16
    granularity (expand coarse blocks; group finer ones by any())."""
    layout = np.asarray(layout, bool)
    if block == FINE:
        return layout
    H, n, _ = layout.shape
    if block > FINE:
        assert block % FINE == 0, f"layout block {block} must be a multiple of {FINE}"
        r = block // FINE
        return np.kron(layout, np.ones((r, r), bool))
    r = FINE // block
    assert r * block == FINE, f"layout block {block} must divide {FINE}"
    n16 = n // r
    return layout.reshape(H, n16, r, n16, r).any((2, 4))


def _build(layout, T, block, block_q, causal=False):
    """Host-side static prep: 16-granular fine masks (f32, both orientations)
    + visit lists at (block_q x BLOCK_K) granularity, all numpy."""
    fine = _normalize_16(layout, block)                # [H, n16, n16]
    H, n16, _ = fine.shape
    assert n16 * FINE == T, (n16, T)
    assert T % block_q == 0 and T % BLOCK_K == 0, (T, block_q)
    nbq, nbk = T // block_q, T // BLOCK_K
    fq = block_q // FINE
    coarse = fine.reshape(H, nbq, fq, nbk, FPK_K).any((2, 4))
    assert coarse.any(-1).all(), \
        "sparsity layout has a fully-masked query row (undefined softmax)"
    if causal:
        # the intersection with the token-granular causal mask must also keep
        # >=1 key per query row (else m stays -inf and the kernel emits a
        # spurious mean-of-V with bogus grads): a fine row survives iff some
        # visited fine tile lies on or below the diagonal — a strictly-upper
        # layout row dies even though the layout-only check above passes
        assert np.tril(np.ones((n16, n16), bool))[None].__and__(fine).any(-1).all(), \
            "causal=True: some query row's visited blocks are entirely in " \
            "the future (fully masked after the causal intersection)"
    counts, idx = _visit_lists(coarse)
    countsT, idxT = _visit_lists(coarse.transpose(0, 2, 1))
    fineT = fine.transpose(0, 2, 1)
    return (counts, idx, fine.astype(np.float32), countsT, idxT,
            fineT.astype(np.float32), nbq, nbk)


def block_sparse_attention(q, k, v, layout, block=16, sm_scale=None,
                           block_q=None, causal=False, interpret=None):
    """q,k,v: [B, H, T, D]; layout: [H, T//block, T//block] bool (numpy,
    static). Differentiable; compute scales with layout density. The softmax
    scale is folded into q once up front (not per-block).

    `causal=True` adds TOKEN-granular q>=k masking inside visited blocks —
    the unidirectional layouts' tril is block-granular only (a diagonal
    block is fully open, leaking up to block-1 future tokens), so causal
    LMs must set this."""
    if interpret is None:
        interpret = _use_interpret()
    B, H, T, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    if block_q is None:
        block_q = 512 if T >= 2048 else 128
        while block_q > 128 and T % block_q != 0:
            block_q //= 2
    layout = np.asarray(layout, bool)
    if layout.shape[0] == 1 and H > 1:
        # head-broadcast layout (the configs allow num_heads=1 shared layouts)
        layout = np.broadcast_to(layout, (H,) + layout.shape[1:])
    assert layout.shape[0] == H, (layout.shape, H)
    args = _build_cached(layout, T, block, block_q, bool(causal))
    return _sparse(q, k, v, *args, float(sm_scale), int(block_q),
                   bool(causal), bool(interpret))


_BUILD_CACHE = {}


def _build_cached(layout, T, block, block_q, causal=False):
    """Memoize _build's host-side visit-list loops — eager per-token callers
    would otherwise redo O(H*nq*nk) Python work every call. Cached values are
    HOST numpy, converted per call site: caching jnp arrays would capture
    tracers when the first call happens under a jit trace and leak them into
    later traces (observed UnexpectedTracerError)."""
    # key on the bytes themselves, not hash(): a 64-bit collision between two
    # same-shape layouts would silently serve the wrong sparsity pattern
    key = (layout.tobytes(), layout.shape, T, block, block_q, causal)
    if key not in _BUILD_CACHE:
        (counts, idx, fine, countsT, idxT, fineT, _, _) = \
            _build(layout, T, block, block_q, causal)
        _BUILD_CACHE[key] = (counts, idx, fine, countsT, idxT, fineT)
        if len(_BUILD_CACHE) > 32:  # bound resident mask tables
            _BUILD_CACHE.pop(next(iter(_BUILD_CACHE)))
    return tuple(jnp.asarray(a) for a in _BUILD_CACHE[key])


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11, 12))
def _sparse(q, k, v, counts, idx, fine, countsT, idxT, fineT,
            sm_scale, block_q, causal, interpret):
    out, _ = _sparse_fwd_impl(q, k, v, counts, idx, fine, sm_scale, block_q,
                              causal, interpret)
    return out


def _sparse_fwd_impl(q, k, v, counts, idx, fine, sm_scale, block_q, causal,
                     interpret):
    B, H, T, D = q.shape
    nbq = T // block_q
    n16 = fine.shape[-1]
    fq = block_q // FINE
    qs = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, nbq),
        in_specs=[
            pl.BlockSpec((None, None, fq, n16),
                         lambda b, h, qi, *_: (h, qi, 0, 0)),
            pl.BlockSpec((None, None, block_q, D),
                         lambda b, h, qi, *_: (b, h, qi, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h, qi, *_: (b, h, 0, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h, qi, *_: (b, h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, D),
                         lambda b, h, qi, *_: (b, h, qi, 0)),
            pl.BlockSpec((None, None, nbq, block_q),
                         lambda b, h, qi, *_: (b, h, 0, 0)),
        ],
    )
    # fine mask rows regrouped per q-tile: [H, nbq, fq, n16] -> block (fq, n16)
    fine_q = fine.reshape(H, nbq, fq, n16)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, nbq, block_q), jnp.float32),
        ],
        interpret=interpret,
    )(counts, idx, fine_q, qs, k, v)
    return out, lse


def _sparse_vjp_fwd(q, k, v, counts, idx, fine, countsT, idxT, fineT,
                    sm_scale, block_q, causal, interpret):
    out, lse = _sparse_fwd_impl(q, k, v, counts, idx, fine, sm_scale, block_q,
                                causal, interpret)
    return out, (q, k, v, out, lse, counts, idx, fine, countsT, idxT, fineT)


def _sparse_vjp_bwd(sm_scale, block_q, causal, interpret, res, g):
    q, k, v, out, lse, counts, idx, fine, countsT, idxT, fineT = res
    B, H, T, D = q.shape
    nbq, nbk = T // block_q, T // BLOCK_K
    n16 = fine.shape[-1]
    fq = block_q // FINE
    do = g
    qs = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = delta.reshape(B, H, nbq, block_q)
    fine_q = fine.reshape(H, nbq, fq, n16)

    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, nbq),
        in_specs=[
            pl.BlockSpec((None, None, fq, n16),
                         lambda b, h, qi, *_: (h, qi, 0, 0)),
            pl.BlockSpec((None, None, block_q, D),
                         lambda b, h, qi, *_: (b, h, qi, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h, qi, *_: (b, h, 0, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h, qi, *_: (b, h, 0, 0)),
            pl.BlockSpec((None, None, block_q, D),
                         lambda b, h, qi, *_: (b, h, qi, 0)),
            pl.BlockSpec((None, None, nbq, block_q),
                         lambda b, h, qi, *_: (b, h, 0, 0)),
            pl.BlockSpec((None, None, nbq, block_q),
                         lambda b, h, qi, *_: (b, h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, block_q, D),
                               lambda b, h, qi, *_: (b, h, qi, 0)),
    )
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal), grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        interpret=interpret,
    )(counts, idx, fine_q, qs, k, v, do, lse, delta)
    dq = (dq.astype(jnp.float32) * sm_scale).astype(q.dtype)

    # fineT rows regrouped per k-block: [H, nbk, FPK_K, n16]
    fineT_k = fineT.reshape(H, nbk, FPK_K, n16)
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, nbk),
        in_specs=[
            pl.BlockSpec((None, None, FPK_K, n16),
                         lambda b, h, ki, *_: (h, ki, 0, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h, ki, *_: (b, h, 0, 0)),
            pl.BlockSpec((None, None, BLOCK_K, D),
                         lambda b, h, ki, *_: (b, h, ki, 0)),
            pl.BlockSpec((None, None, BLOCK_K, D),
                         lambda b, h, ki, *_: (b, h, ki, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h, ki, *_: (b, h, 0, 0)),
            pl.BlockSpec((None, None, nbq, block_q),
                         lambda b, h, ki, *_: (b, h, 0, 0)),
            pl.BlockSpec((None, None, nbq, block_q),
                         lambda b, h, ki, *_: (b, h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, BLOCK_K, D),
                         lambda b, h, ki, *_: (b, h, ki, 0)),
            pl.BlockSpec((None, None, BLOCK_K, D),
                         lambda b, h, ki, *_: (b, h, ki, 0)),
        ],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, causal=causal),
        grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        ],
        interpret=interpret,
    )(countsT, idxT, fineT_k, qs, k, v, do, lse, delta)
    # dk needs no extra sm_scale: the kernel contracts ds^T against the
    # PRE-SCALED q, which already carries the factor (dq does need it — its
    # contraction is against the unscaled k)

    return (dq, dk, dv, None, None, None, None, None, None)


_sparse.defvjp(_sparse_vjp_fwd, _sparse_vjp_bwd)
