"""Stable token sort by expert (Pallas) — the MoE dropless-dispatch primitive.

Analog of the reference's `csrc/random_ltd/token_sort.cu`: rank every token
within its expert's queue (a stable counting sort over expert ids) so tokens
can scatter into per-expert buffers without capacity drops. `parallel/moe.py`'s
`dropless_moe` scatters with `buf.at[expert_idx, pos].set(x)` — `pos` from this
kernel, capacity = N, so no assignment can ever overflow.

Kernel shape: tokens along sublanes in `bn`-row blocks, experts along lanes.
The grid walks token blocks sequentially (TPU grids are sequential by
default); running per-expert counts accumulate in the revisited `counts`
output block — the standard Pallas accumulator pattern — so each block's
local cumsum offsets by everything already seen. All math is int32, which is
why the gather-oracle parity tests can demand bit-equality.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _use_interpret():
    return jax.default_backend() not in ("tpu", "axon")


def _block_rows(n):
    for b in (128, 64, 32, 16, 8, 4, 2, 1):
        if n % b == 0:
            return b
    return 1


def _token_sort_kernel(idx_ref, pos_ref, counts_ref, *, num_experts):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        counts_ref[:, :] = jnp.zeros_like(counts_ref)

    idx = idx_ref[:, :]                                        # [bn, 1] int32
    bn = idx.shape[0]
    e_iota = jax.lax.broadcasted_iota(jnp.int32, (bn, num_experts), 1)
    onehot = (idx == e_iota).astype(jnp.int32)                 # [bn, E]
    base = counts_ref[:, :]                                    # [1, E] seen so far
    csum = jnp.cumsum(onehot, axis=0)                          # 1-based in-block
    rank = csum - 1 + base                                     # 0-based global
    pos_ref[:, :] = jnp.sum(rank * onehot, axis=1, keepdims=True)
    counts_ref[:, :] = base + csum[-1:, :]


def token_sort(expert_idx, num_experts, interpret=None):
    """expert_idx: [N] int → (pos [N] int32, counts [E] int32).

    `pos[i]` is token i's 0-based stable rank within expert `expert_idx[i]`'s
    queue; `counts[e]` the number of tokens routed to expert e (callers route
    only valid ids — an out-of-range id matches no expert lane, so it counts
    nowhere and its rank degenerates to 0).
    """
    if interpret is None:
        interpret = _use_interpret()
    N = expert_idx.shape[0]
    idx2 = expert_idx.astype(jnp.int32).reshape(N, 1)
    bn = _block_rows(N)
    pos, counts = pl.pallas_call(
        functools.partial(_token_sort_kernel, num_experts=num_experts),
        grid=(N // bn,),
        in_specs=[pl.BlockSpec((bn, 1), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, num_experts), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, num_experts), jnp.int32),
        ],
        interpret=interpret,
    )(idx2)
    return pos.reshape(N), counts.reshape(num_experts)


def token_sort_oracle(expert_idx, num_experts):
    """Pure-jnp gather oracle for `token_sort` (bit-parity pinned by tests)."""
    idx = expert_idx.astype(jnp.int32)
    onehot = (idx[:, None]
              == jnp.arange(num_experts, dtype=jnp.int32)[None, :]).astype(jnp.int32)
    csum = jnp.cumsum(onehot, axis=0)
    pos = jnp.sum((csum - 1) * onehot, axis=1)
    return pos.astype(jnp.int32), csum[-1].astype(jnp.int32)
