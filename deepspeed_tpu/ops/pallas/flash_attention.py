"""Flash attention (Pallas, TPU) — HBM-streaming K/V.

The training-attention hot op — replaces the reference's fused softmax CUDA
kernels (`csrc/transformer/softmax_kernels.cu`, sparse/triton attention
`ops/sparse_attention/matmul.py`) with the memory-optimal streaming formulation:
online softmax over KV blocks, O(T) memory, fp32 accumulation, causal masking,
custom VJP with the standard recomputation backward.

Layout: [B, H, T, D] (wrapper transposes from the zoo's [B, T, H, D]).

K/V STREAM from HBM: the grid carries a KV-block dimension and Pallas's
pipeline DMAs one double-buffered [block_k, D] (resp. [block_q, D] in the
dk/dv pass) tile into VMEM per grid step while the previous tile computes.
The online-softmax state (acc/m/l) lives in VMEM scratch that persists
across the sequential KV grid steps, so the kernel's VMEM working set is
O(block), not O(T) — sequence length is bounded by HBM capacity
(`flash_max_seq`), not the old ~14k-token whole-slab VMEM cap. Causal
grids skip fully-masked tiles entirely: compute and output writes are
predicated off (`pl.when`), and the block index maps clamp to the diagonal
frontier so the dead steps' DMAs are elided too (repeated consecutive
block indices fetch nothing — same trick as the decode kernel's prefix
clamp).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30
# VPU lane width: m/l scratch rows are replicated across one lane tile so the
# scratch stays 2D and tile-aligned regardless of block_q
_LANES = 128


def _use_interpret():
    return jax.default_backend() not in ("tpu", "axon")


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, sm_scale, causal, block_k):
    # q_ref/o_ref: [block_q, D]; k_ref/v_ref: [block_k, D] (one streamed KV
    # tile); lse_ref: [1, block_q]; scratch acc [block_q, D] fp32, m/l
    # [block_q, _LANES] fp32 (row stats replicated across lanes — TPU scratch
    # wants a 128-lane trailing dim). Grid: (BH, nq, nk), nk innermost and
    # sequential, so scratch carries the online-softmax state across KV tiles.
    #
    # Dots run on NATIVE-dtype operands (bf16 in, fp32 out via
    # preferred_element_type): casting inputs to fp32 first forces the MXU's
    # fp32 path (~4x slower) and was measured to make the whole kernel lose
    # to XLA attention at seq 512. `p` narrows back to the input dtype for
    # the p@v dot — standard TPU flash practice; softmax stats stay fp32.
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    block_q, D = q_ref.shape
    in_dtype = q_ref.dtype

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    if causal:
        # any (q_pos >= k_pos) pair in this tile? max q_pos = (qi+1)*bq - 1
        run = ki * block_k < (qi + 1) * block_q
        last_ki = jnp.minimum(nk - 1, ((qi + 1) * block_q - 1) // block_k)
    else:
        run = ki >= 0          # traced always-true (Mosaic-friendly pl.when)
        last_ki = nk - 1

    @pl.when(run)
    def _step():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(in_dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == last_ki)
    def _finish():
        m = m_ref[:, 0]
        l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[...] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, :] = (m + jnp.log(l_safe)).astype(jnp.float32)


def _kv_index_map(causal, block_q, block_k):
    """KV-tile index for the (BH, nq, nk) grids. Causal grids clamp ki to the
    q row's diagonal frontier: fully-masked tiles re-serve the frontier block,
    and Pallas elides the DMA when consecutive block indices repeat — dead
    grid steps cost neither MXU (pl.when) nor HBM traffic (same trick as the
    decode kernel's prefix clamp)."""
    if not causal:
        return lambda bh, qi, ki: (bh, ki, 0)

    def index(bh, qi, ki):
        frontier = ((qi + 1) * block_q - 1) // block_k
        return (bh, jnp.minimum(ki, frontier), 0)

    return index


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    B, H, T, D = q.shape
    BH = B * H
    q2 = q.reshape(BH, T, D)
    k2 = k.reshape(BH, T, D)
    v2 = v.reshape(BH, T, D)
    Tb = T // block_q
    grid = (BH, Tb, T // block_k)
    kv_index = _kv_index_map(causal, block_q, block_k)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          block_k=block_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((None, block_k, D), kv_index),
            pl.BlockSpec((None, block_k, D), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            # blocked [Tb, bq] lse layout (rows per q block; lane-dim = bq)
            pl.BlockSpec((None, 1, block_q), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Tb, block_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q2, k2, v2)
    return out.reshape(B, H, T, D), lse


# ----------------------------------------------------------------------
# backward
# ----------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc_ref, *, sm_scale, causal, block_k):
    # streamed tiles: k/v [block_k, D] walk the KV grid dim; q/do/lse/delta
    # ride the q block; dq accumulates in scratch across the KV walk
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    block_q, D = q_ref.shape
    in_dtype = q_ref.dtype

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    if causal:
        run = ki * block_k < (qi + 1) * block_q
        last_ki = jnp.minimum(nk - 1, ((qi + 1) * block_q - 1) // block_k)
    else:
        run = ki >= 0          # traced always-true (Mosaic-friendly pl.when)
        last_ki = nk - 1

    @pl.when(run)
    def _step():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[0, :]
        delta = delta_ref[0, :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(in_dtype)
        dq_acc_ref[...] = dq_acc_ref[...] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == last_ki)
    def _finish():
        dq_ref[...] = (dq_acc_ref[...] * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                    *, sm_scale, causal, block_q):
    # grid (BH, nk, nq), nq innermost: q/do/lse/delta tiles stream past a
    # resident [block_k, D] k/v tile; dk/dv accumulate in scratch
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    block_k, D = k_ref.shape
    in_dtype = k_ref.dtype

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    # causal: q blocks strictly before the diagonal see no (q_pos >= k_pos)
    run = (qi + 1) * block_q > ki * block_k if causal else qi >= 0

    @pl.when(run)
    def _step():
        k = k_ref[...]
        v = v_ref[...]
        q = q_ref[...]
        do = do_ref[...]
        lse = lse_ref[0, :]
        delta = delta_ref[0, :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                                 # [bq, bk]
        dv_acc_ref[...] = dv_acc_ref[...] + jax.lax.dot_general(
            p.astype(in_dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(in_dtype)
        dk_acc_ref[...] = dk_acc_ref[...] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[...] = (dk_acc_ref[...] * sm_scale).astype(dk_ref.dtype)
        dv_ref[...] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd(res, g, sm_scale, causal, block_q, block_k, interpret,
               delta_adjust=None):
    q, k, v, o, lse = res
    do = g
    B, H, T, D = q.shape
    BH = B * H
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [B,H,T]
    if delta_adjust is not None:
        # lse cotangent: d lse/d s = p, so ds = p*(dp - delta + dlse) — i.e.
        # the existing kernels run unchanged with delta' = delta - dlse
        delta = delta - delta_adjust

    q2, k2, v2 = (x.reshape(BH, T, D) for x in (q, k, v))
    do2 = do.reshape(BH, T, D)
    Tb = T // block_q
    lse2 = lse                                   # [BH, Tb, block_q] (blocked)
    delta2 = delta.reshape(BH, Tb, block_q)

    kv_index = _kv_index_map(causal, block_q, block_k)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_k=block_k),
        grid=(BH, Tb, T // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((None, block_k, D), kv_index),
            pl.BlockSpec((None, block_k, D), kv_index),
            pl.BlockSpec((None, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((None, 1, block_q), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((None, 1, block_q), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(q2, k2, v2, do2, lse2, delta2)

    if causal:
        # mirror of _kv_index_map for the transposed (BH, nk, nq) grid:
        # pre-diagonal q tiles re-serve the diagonal block (DMA elided)
        def q_index(bh, ki, qi):
            first = (ki * block_k) // block_q
            return (bh, jnp.maximum(qi, first), 0)
    else:
        q_index = lambda bh, ki, qi: (bh, qi, 0)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q),
        grid=(BH, T // block_k, Tb),
        in_specs=[
            pl.BlockSpec((None, block_q, D), q_index),
            pl.BlockSpec((None, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((None, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((None, block_q, D), q_index),
            pl.BlockSpec((None, 1, block_q), q_index),
            pl.BlockSpec((None, 1, block_q), q_index),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((None, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(q2, k2, v2, do2, lse2, delta2)

    return (dq.reshape(B, H, T, D), dk.reshape(B, H, T, D), dv.reshape(B, H, T, D))


# ----------------------------------------------------------------------
# public op
# ----------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return out


def _flash_vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    return _flash_bwd(res, g, sm_scale, causal, block_q, block_k, interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_seq_tileable(T):
    """True when the kernel's 128-lane tiling divides T — the shard-shape
    contract ring attention (`parallel/ring.py`) checks before forcing the
    kernel on a per-rank T/sp shard, and the zoo's dispatch layer checks
    for the whole-sequence path. One definition, next to the lane width it
    encodes."""
    return T % _LANES == 0


def flash_max_seq(d_head, itemsize=2, hbm_budget=12 * 2**30):
    """Largest single-device T the STREAMING kernel can serve. K/V tiles are
    DMA'd from HBM per grid step, so VMEM no longer bounds the sequence —
    the bound is HBM holding the op's own operands through fwd+bwd: per
    (batch x head), ~8 [T, D] slabs (q/k/v/o + do/dq/dk/dv) plus two fp32
    [T] rows (lse, delta). The historical whole-slab VMEM cap this replaces
    was (14 MiB)/(4*D*itemsize) ~ 14k tokens at head_dim 128 bf16; the
    streaming bound at the same shape is ~6M tokens on a 16 GiB chip
    (12 GiB budgeted — activations elsewhere claim HBM first, so treat
    this as advisory, not a hard wall)."""
    return int(hbm_budget) // (8 * d_head * itemsize + 8)


def _default_blocks(T, block_q, block_k):
    """Measured-crossover default tiles (512/512 from T >= 1024 — see
    flash_attention docstring), shrunk to the largest power-of-two divisor
    of T >= the 128 lane width; explicit sizes pass through."""
    if block_q is None:
        block_q = 512 if T >= 1024 else DEFAULT_BLOCK_Q
        while block_q > DEFAULT_BLOCK_Q and T % block_q != 0:
            block_q //= 2
    if block_k is None:
        block_k = 512 if T >= 1024 else DEFAULT_BLOCK_K
        while block_k > DEFAULT_BLOCK_K and T % block_k != 0:
            block_k //= 2
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    assert T % block_q == 0 and T % block_k == 0, (T, block_q, block_k)
    return block_q, block_k


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    """(o, lse) variant for composition (ring attention): lse [BH, Tb, bq]
    participates in autodiff — its cotangent folds into the backward as a
    delta adjustment (see _flash_bwd)."""
    return _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)


def _flash_lse_vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_vjp_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    do, dlse = g
    q = res[0]
    B, H, T, D = q.shape
    # ds = p*(dp - delta + dlse) = p*(dp - (delta - dlse)) → delta' = delta - dlse
    dlse_rows = dlse.astype(jnp.float32).reshape(B, H, T)
    return _flash_bwd(res, do, sm_scale, causal, block_q, block_k, interpret,
                      delta_adjust=dlse_rows)


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def flash_attention_with_lse(q, k, v, causal=True, sm_scale=None, block_q=None,
                             block_k=None, interpret=None):
    """Differentiable (output, lse) flash attention, [B, H, T, D] layout.

    lse is returned as [B, H, T] (row log-sum-exp, fp32) — the combination
    statistic ring attention needs to merge per-shard partials
    (parallel/ring.py): out = Σ_i o_i · exp(lse_i − logsumexp_i lse_i)."""
    if interpret is None:
        interpret = _use_interpret()
    B, H, T, D = q.shape
    block_q, block_k = _default_blocks(T, block_q, block_k)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    out, lse = _flash_lse(q, k, v, float(sm_scale), bool(causal), int(block_q),
                          int(block_k), bool(interpret))
    # blocked [BH, Tb, bq] rows concatenate in order → [B, H, T]
    return out, lse.reshape(B, H, T)


def flash_attention(q, k, v, causal=True, sm_scale=None, block_q=None,
                    block_k=None, layout="BTHD", interpret=None):
    """Flash attention. q,k,v: [B,T,H,D] ("BTHD", zoo layout) or [B,H,T,D].

    Sequence length must be a multiple of the block size (the zoo pads to 128
    multiples; MXU-friendly anyway) and is otherwise bounded only by HBM
    (`flash_max_seq`) — K/V stream through VMEM one [block_k, D] tile at a
    time. Default blocks scale with T: 512/512 tiles from T >= 1024
    (measured r4 with native-dtype dots, fwd+bwd vs materialized XLA
    attention: 1.6x at 1k, 2.3x at 2k, 3.4x at 4k; 512/512 edged out
    512/1024 at both 2k and 4k); short sequences keep 128/128.
    """
    if interpret is None:
        interpret = _use_interpret()
    if layout == "BTHD":
        q, k, v = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    B, H, T, D = q.shape
    block_q, block_k = _default_blocks(T, block_q, block_k)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    out = _flash(q, k, v, float(sm_scale), bool(causal), int(block_q), int(block_k),
                 bool(interpret))
    if layout == "BTHD":
        out = jnp.swapaxes(out, 1, 2)
    return out
