"""Flash attention (Pallas, TPU).

The training-attention hot op — replaces the reference's fused softmax CUDA
kernels (`csrc/transformer/softmax_kernels.cu`, sparse/triton attention
`ops/sparse_attention/matmul.py`) with the memory-optimal streaming formulation:
online softmax over KV blocks, O(T) memory, fp32 accumulation, causal masking,
custom VJP with the standard recomputation backward.

Layout: [B, H, T, D] (wrapper transposes from the zoo's [B, T, H, D]).
K/V live whole per (batch, head) in VMEM — right up to ~8k sequence on v5e;
longer sequences go through ring attention (parallel/ring.py) on top of this
kernel per step.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _use_interpret():
    return jax.default_backend() not in ("tpu", "axon")


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal, block_k):
    # q_ref: [block_q, D]; k_ref/v_ref: [T, D]; o_ref: [block_q, D];
    # lse_ref: [T//block_q, block_q] (whole-array block; row qi written per program —
    # TPU grid iterations run sequentially, so disjoint row writes are safe)
    #
    # Dots run on NATIVE-dtype operands (bf16 in, fp32 out via
    # preferred_element_type): casting inputs to fp32 first forces the MXU's
    # fp32 path (~4x slower) and was measured to make the whole kernel lose
    # to XLA attention at seq 512. `p` narrows back to the input dtype for
    # the p@v dot — standard TPU flash practice; softmax stats stay fp32.
    qi = pl.program_id(1)
    block_q, D = q_ref.shape
    T = k_ref.shape[0]
    in_dtype = q_ref.dtype
    q = q_ref[:, :]

    nblocks = T // block_k
    if causal:
        # only kv blocks whose start <= q block end
        nblocks_dyn = jnp.minimum(((qi + 1) * block_q + block_k - 1) // block_k, nblocks)
    else:
        nblocks_dyn = nblocks

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(in_dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nblocks_dyn, body, (acc0, m0, l0))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[:, :] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[qi, :] = (m + jnp.log(l_safe)).astype(jnp.float32)


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    B, H, T, D = q.shape
    BH = B * H
    q2 = q.reshape(BH, T, D)
    k2 = k.reshape(BH, T, D)
    v2 = v.reshape(BH, T, D)
    grid = (BH, T // block_q)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal, block_k=block_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, T, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, T, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qi: (bh, qi, 0)),
            # blocked [Tb, bq] layout satisfies TPU (8,128) tiling via whole-array blocks
            pl.BlockSpec((None, T // block_q, block_q), lambda bh, qi: (bh, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T // block_q, block_q), jnp.float32),
        ],
        interpret=interpret,
    )(q2, k2, v2)
    return out.reshape(B, H, T, D), lse


# ----------------------------------------------------------------------
# backward
# ----------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, sm_scale, causal, block_k):
    qi = pl.program_id(1)
    block_q, D = q_ref.shape
    T = k_ref.shape[0]
    in_dtype = q_ref.dtype
    q = q_ref[:, :]
    do = do_ref[:, :]
    lse = lse_ref[qi, :]
    delta = delta_ref[qi, :]

    nblocks = T // block_k
    nblocks_dyn = jnp.minimum(((qi + 1) * block_q + block_k - 1) // block_k, nblocks) \
        if causal else nblocks

    def body(j, dq):
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(in_dtype)
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nblocks_dyn, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[:, :] = (dq * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                    *, sm_scale, causal, block_q):
    ki = pl.program_id(1)
    block_k, D = k_ref.shape
    T = q_ref.shape[0]
    in_dtype = k_ref.dtype
    k = k_ref[:, :]
    v = v_ref[:, :]

    nblocks = T // block_q
    start = (ki * block_k) // block_q if causal else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.ds(i * block_q, block_q), :]
        do = do_ref[pl.ds(i * block_q, block_q), :]
        lse = lse_ref[i, :]
        delta = delta_ref[i, :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                                 # [bq, bk]
        dv = dv + jax.lax.dot_general(p.astype(in_dtype), do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(in_dtype)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((block_k, D), jnp.float32)
    dv0 = jnp.zeros((block_k, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, nblocks, body, (dk0, dv0))
    dk_ref[:, :] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[:, :] = dv.astype(dv_ref.dtype)


def _flash_bwd(res, g, sm_scale, causal, block_q, block_k, interpret,
               delta_adjust=None):
    q, k, v, o, lse = res
    do = g
    B, H, T, D = q.shape
    BH = B * H
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [B,H,T]
    if delta_adjust is not None:
        # lse cotangent: d lse/d s = p, so ds = p*(dp - delta + dlse) — i.e.
        # the existing kernels run unchanged with delta' = delta - dlse
        delta = delta - delta_adjust

    q2, k2, v2 = (x.reshape(BH, T, D) for x in (q, k, v))
    do2 = do.reshape(BH, T, D)
    Tb = T // block_q
    lse2 = lse                                   # [BH, Tb, block_q] (blocked)
    delta2 = delta.reshape(BH, Tb, block_q)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal, block_k=block_k),
        grid=(BH, T // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, T, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, T, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, Tb, block_q), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, Tb, block_q), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        interpret=interpret,
    )(q2, k2, v2, do2, lse2, delta2)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q),
        grid=(BH, T // block_k),
        in_specs=[
            pl.BlockSpec((None, T, D), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((None, block_k, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, block_k, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, T, D), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((None, Tb, block_q), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((None, Tb, block_q), lambda bh, ki: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, block_k, D), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        ],
        interpret=interpret,
    )(q2, k2, v2, do2, lse2, delta2)

    return (dq.reshape(B, H, T, D), dk.reshape(B, H, T, D), dv.reshape(B, H, T, D))


# ----------------------------------------------------------------------
# public op
# ----------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return out


def _flash_vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    return _flash_bwd(res, g, sm_scale, causal, block_q, block_k, interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_max_seq(d_head, itemsize=2):
    """Largest single-device T the kernel can serve: it holds WHOLE [T, D]
    k/v slabs in VMEM and Pallas double-buffers them, so 4 x T*D*itemsize
    must fit ~14 MiB of the 16 MiB scoped budget (measured: T=16384 at
    D=128 bf16 overflows by ~0.7 MiB; T=8192 fits). Longer sequences belong
    to sequence parallelism (ring/Ulysses shards stay under this cap) or to
    `ops.chunked_attention` on one device."""
    return (14 * 2**20) // (4 * d_head * itemsize)


def _check_vmem_domain(T, D, dtype, interpret):
    if interpret:
        return
    cap = flash_max_seq(D, jnp.dtype(dtype).itemsize)
    if T > cap:
        raise ValueError(
            f"flash kernel: T={T} exceeds the ~{cap}-token single-device "
            f"VMEM domain at head_dim={D} (whole double-buffered [T, D] k/v "
            "slabs). Shard the sequence (parallel/ring.py, parallel/"
            "ulysses.py) or use ops.chunked_attention.chunked_attention")


def _default_blocks(T, block_q, block_k):
    """Measured-crossover default tiles (512/512 from T >= 1024 — see
    flash_attention docstring), shrunk to the largest power-of-two divisor
    of T >= the 128 lane width; explicit sizes pass through."""
    if block_q is None:
        block_q = 512 if T >= 1024 else DEFAULT_BLOCK_Q
        while block_q > DEFAULT_BLOCK_Q and T % block_q != 0:
            block_q //= 2
    if block_k is None:
        block_k = 512 if T >= 1024 else DEFAULT_BLOCK_K
        while block_k > DEFAULT_BLOCK_K and T % block_k != 0:
            block_k //= 2
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    assert T % block_q == 0 and T % block_k == 0, (T, block_q, block_k)
    return block_q, block_k


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    """(o, lse) variant for composition (ring attention): lse [BH, Tb, bq]
    participates in autodiff — its cotangent folds into the backward as a
    delta adjustment (see _flash_bwd)."""
    return _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)


def _flash_lse_vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_vjp_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    do, dlse = g
    q = res[0]
    B, H, T, D = q.shape
    # ds = p*(dp - delta + dlse) = p*(dp - (delta - dlse)) → delta' = delta - dlse
    dlse_rows = dlse.astype(jnp.float32).reshape(B, H, T)
    return _flash_bwd(res, do, sm_scale, causal, block_q, block_k, interpret,
                      delta_adjust=dlse_rows)


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def flash_attention_with_lse(q, k, v, causal=True, sm_scale=None, block_q=None,
                             block_k=None, interpret=None):
    """Differentiable (output, lse) flash attention, [B, H, T, D] layout.

    lse is returned as [B, H, T] (row log-sum-exp, fp32) — the combination
    statistic ring attention needs to merge per-shard partials
    (parallel/ring.py): out = Σ_i o_i · exp(lse_i − logsumexp_i lse_i)."""
    if interpret is None:
        interpret = _use_interpret()
    B, H, T, D = q.shape
    _check_vmem_domain(T, D, q.dtype, interpret)
    block_q, block_k = _default_blocks(T, block_q, block_k)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    out, lse = _flash_lse(q, k, v, float(sm_scale), bool(causal), int(block_q),
                          int(block_k), bool(interpret))
    # blocked [BH, Tb, bq] rows concatenate in order → [B, H, T]
    return out, lse.reshape(B, H, T)


def flash_attention(q, k, v, causal=True, sm_scale=None, block_q=None,
                    block_k=None, layout="BTHD", interpret=None):
    """Flash attention. q,k,v: [B,T,H,D] ("BTHD", zoo layout) or [B,H,T,D].

    Sequence length must be a multiple of the block size (the zoo pads to 128
    multiples; MXU-friendly anyway). Default blocks scale with T: 512/512
    tiles from T >= 1024 (measured r4 with native-dtype dots, fwd+bwd vs
    materialized XLA attention: 1.6x at 1k, 2.3x at 2k, 3.4x at 4k; 512/512
    edged out 512/1024 at both 2k and 4k); short sequences keep 128/128.
    """
    if interpret is None:
        interpret = _use_interpret()
    if layout == "BTHD":
        q, k, v = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    B, H, T, D = q.shape
    _check_vmem_domain(T, D, q.dtype, interpret)
    block_q, block_k = _default_blocks(T, block_q, block_k)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    out = _flash(q, k, v, float(sm_scale), bool(causal), int(block_q), int(block_k),
                 bool(interpret))
    if layout == "BTHD":
        out = jnp.swapaxes(out, 1, 2)
    return out
