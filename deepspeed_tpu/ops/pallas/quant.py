"""Groupwise int8 quantization (Pallas).

Analog of the reference's `csrc/quantization/` suite (quantize.cu, swizzled
quant, quant_reduce) powering ZeRO++ qwZ/qgZ and weight-only inference quant.
Symmetric per-group int8: scale = max|x| / 127 per group of `group_size`
contiguous elements along the last dim.

These ops are the building blocks for quantized collectives: all-gather/reduce
run over the int8 payload + f32 scales, dequantize after (runtime path in
runtime/quantized_collectives.py).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _use_interpret():
    return jax.default_backend() not in ("tpu", "axon")


def _quant_kernel(x_ref, q_ref, s_ref, *, group_size):
    x = x_ref[:, :].astype(jnp.float32)            # [rows, D]
    rows, D = x.shape
    g = D // group_size
    xg = x.reshape(rows, g, group_size)
    amax = jnp.max(jnp.abs(xg), axis=-1)           # [rows, g]
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xg / scale[..., None]), -127, 127).astype(jnp.int8)
    q_ref[:, :] = q.reshape(rows, D)
    s_ref[:, :] = scale


def _dequant_kernel(q_ref, s_ref, o_ref, *, group_size):
    q = q_ref[:, :].astype(jnp.float32)
    rows, D = q.shape
    g = D // group_size
    s = s_ref[:, :]
    x = q.reshape(rows, g, group_size) * s[..., None]
    o_ref[:, :] = x.reshape(rows, D).astype(o_ref.dtype)


def _block_rows(n):
    for b in (128, 64, 32, 16, 8, 4, 2, 1):
        if n % b == 0:
            return b
    return 1


def quantize_int8(x, group_size=128, interpret=None):
    """x: [..., D] → (q int8 [..., D], scales f32 [..., D//group_size])."""
    if interpret is None:
        interpret = _use_interpret()
    orig = x.shape
    D = orig[-1]
    assert D % group_size == 0, f"last dim {D} not divisible by group_size {group_size}"
    x2 = x.reshape(-1, D)
    N = x2.shape[0]
    bn = _block_rows(N)
    g = D // group_size
    q, s = pl.pallas_call(
        functools.partial(_quant_kernel, group_size=group_size),
        grid=(N // bn,),
        in_specs=[pl.BlockSpec((bn, D), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
            pl.BlockSpec((bn, g), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, D), jnp.int8),
            jax.ShapeDtypeStruct((N, g), jnp.float32),
        ],
        interpret=interpret,
    )(x2)
    return q.reshape(orig), s.reshape(orig[:-1] + (g,))


def _quant4_kernel(x_ref, q_ref, s_ref, *, group_size):
    # same scale/clip rule as the int8 kernel at qmax 7, then two values
    # packed per byte as biased [1, 15] nibbles (lo = even index, hi = odd)
    # — byte-identical to inference/quantization.quantize_tensor(bits=4)
    x = x_ref[:, :].astype(jnp.float32)            # [rows, D]
    rows, D = x.shape
    g = D // group_size
    xg = x.reshape(rows, g, group_size)
    amax = jnp.max(jnp.abs(xg), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(xg / scale[..., None]), -7, 7)
    qu = (q.reshape(rows, D).astype(jnp.int32) + 8).astype(jnp.uint8)
    packed = (qu[:, 0::2] | (qu[:, 1::2] << 4)).astype(jnp.uint8)
    q_ref[:, :] = jax.lax.bitcast_convert_type(packed, jnp.int8)
    s_ref[:, :] = scale


def _dequant4_kernel(q_ref, s_ref, o_ref, *, group_size):
    packed = jax.lax.bitcast_convert_type(q_ref[:, :], jnp.uint8)
    rows = packed.shape[0]
    D = packed.shape[1] * 2
    lo = (packed & 0xF).astype(jnp.int32) - 8
    hi = (packed >> 4).astype(jnp.int32) - 8
    q = jnp.stack([lo, hi], axis=-1).reshape(rows, D).astype(jnp.float32)
    g = D // group_size
    s = s_ref[:, :]
    x = q.reshape(rows, g, group_size) * s[..., None]
    o_ref[:, :] = x.reshape(rows, D).astype(o_ref.dtype)


def quantize_int4(x, group_size=128, interpret=None):
    """x: [..., D] → (packed int8 [..., D//2], scales f32 [..., D//g]).

    Two int4 values per byte (the ZeRO++ qgZ / WOQ storage form); packing
    layout and scale semantics are pinned against the pure-jnp
    `inference/quantization.quantize_tensor(bits=4)` by the parity tests."""
    if interpret is None:
        interpret = _use_interpret()
    orig = x.shape
    D = orig[-1]
    assert D % group_size == 0, \
        f"last dim {D} not divisible by group_size {group_size}"
    assert D % 2 == 0, f"int4 packs two values per byte: last dim {D} odd"
    x2 = x.reshape(-1, D)
    N = x2.shape[0]
    bn = _block_rows(N)
    g = D // group_size
    q, s = pl.pallas_call(
        functools.partial(_quant4_kernel, group_size=group_size),
        grid=(N // bn,),
        in_specs=[pl.BlockSpec((bn, D), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((bn, D // 2), lambda i: (i, 0)),
            pl.BlockSpec((bn, g), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, D // 2), jnp.int8),
            jax.ShapeDtypeStruct((N, g), jnp.float32),
        ],
        interpret=interpret,
    )(x2)
    return q.reshape(orig[:-1] + (D // 2,)), s.reshape(orig[:-1] + (g,))


def dequantize_int4(q, scales, dtype=jnp.bfloat16, group_size=128,
                    interpret=None):
    """Inverse of `quantize_int4`: packed [..., D//2] int8 + scales → [..., D]."""
    if interpret is None:
        interpret = _use_interpret()
    orig = q.shape
    D = orig[-1] * 2
    q2 = q.reshape(-1, orig[-1])
    s2 = scales.reshape(-1, D // group_size)
    N = q2.shape[0]
    bn = _block_rows(N)
    out = pl.pallas_call(
        functools.partial(_dequant4_kernel, group_size=group_size),
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((bn, orig[-1]), lambda i: (i, 0)),
            pl.BlockSpec((bn, D // group_size), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, D), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, D), dtype),
        interpret=interpret,
    )(q2, s2)
    return out.reshape(orig[:-1] + (D,))


def dequantize_int8(q, scales, dtype=jnp.bfloat16, group_size=128, interpret=None):
    if interpret is None:
        interpret = _use_interpret()
    orig = q.shape
    D = orig[-1]
    q2 = q.reshape(-1, D)
    s2 = scales.reshape(-1, D // group_size)
    N = q2.shape[0]
    bn = _block_rows(N)
    out = pl.pallas_call(
        functools.partial(_dequant_kernel, group_size=group_size),
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
            pl.BlockSpec((bn, D // group_size), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, D), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, D), dtype),
        interpret=interpret,
    )(q2, s2)
    return out.reshape(orig)
