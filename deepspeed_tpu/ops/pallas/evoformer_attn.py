"""Evoformer attention (DS4Science analog) — biased attention for
AlphaFold-style models, fused on TPU with Pallas.

Reference: `csrc/deepspeed4science/evoformer_attn/` (CUTLASS fused MHA with two
bias operands) exposed as `DS4Sci_EvoformerAttention(Q, K, V, [bias1, bias2])`:
  - Q/K/V: [B, N, S, H, D]  (batch, MSA rows / residue groups, seq, heads, dim)
  - bias1: [B, N, 1, 1, S]  mask bias (per-row key mask, broadcast over H and q)
  - bias2: [B, 1, H, S, S]  pair bias (shared across rows, per-head)
covering MSA row/column attention and triangle attention (start/end node).

TPU formulation: one streaming-softmax Pallas kernel with the two bias
operands read blockwise (the [B, N, H, S, S] logits tensor is never
materialized in the forward). Backward recomputes per-row (scan over N) so
its peak extra memory is [B, H, S, S] rather than N× that; pair-bias and
mask-bias gradients are produced like the reference kernel's dbias outputs.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deepspeed_tpu.ops.pallas.flash_attention import _use_interpret

NEG_INF = -1e30
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


# ----------------------------------------------------------------------
# forward kernel
# ----------------------------------------------------------------------


def _evo_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, pair_ref, o_ref,
                    *, sm_scale, block_k, has_mask, has_pair):
    # q_ref: [block_q, D]; k/v_ref: [S, D]; mask_ref: [1, S] additive;
    # pair_ref: [block_q, S] additive; o_ref: [block_q, D]
    block_q, D = q_ref.shape
    S = k_ref.shape[0]
    q = q_ref[:, :].astype(jnp.float32) * sm_scale

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [bq, bk]
        if has_mask:
            s = s + mask_ref[0, pl.ds(j * block_k, block_k)].astype(jnp.float32)[None, :]
        if has_pair:
            s = s + pair_ref[:, pl.ds(j * block_k, block_k)].astype(jnp.float32)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, S // block_k, body, (acc0, m0, l0))
    o_ref[:, :] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _evo_fwd_pallas(q, k, v, mask, pair, sm_scale, block_q, block_k, interpret):
    """q,k,v: [B, N, H, S, D]; mask: [B, N, 1, S] or None; pair: [B, H, S, S]
    or None → out [B, N, H, S, D]."""
    B, N, H, S, D = q.shape
    grid = (B, N, H, S // block_q)
    has_mask = mask is not None
    has_pair = pair is not None

    in_specs = [
        pl.BlockSpec((None, None, None, block_q, D), lambda b, n, h, qi: (b, n, h, qi, 0)),
        pl.BlockSpec((None, None, None, S, D), lambda b, n, h, qi: (b, n, h, 0, 0)),
        pl.BlockSpec((None, None, None, S, D), lambda b, n, h, qi: (b, n, h, 0, 0)),
    ]
    operands = [q, k, v]
    if has_mask:
        in_specs.append(pl.BlockSpec((None, None, 1, S), lambda b, n, h, qi: (b, n, 0, 0)))
        operands.append(mask)
    else:
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        operands.append(jnp.zeros((1, 1), q.dtype))
    if has_pair:
        in_specs.append(pl.BlockSpec((None, None, block_q, S), lambda b, n, h, qi: (b, h, qi, 0)))
        operands.append(pair)
    else:
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        operands.append(jnp.zeros((1, 1), q.dtype))

    out = pl.pallas_call(
        functools.partial(_evo_fwd_kernel, sm_scale=sm_scale, block_k=block_k,
                          has_mask=has_mask, has_pair=has_pair),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, None, None, block_q, D),
                               lambda b, n, h, qi: (b, n, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B, N, H, S, D), q.dtype),
        interpret=interpret,
    )(*operands)
    return out


# ----------------------------------------------------------------------
# reference math (jnp) — also the backward
# ----------------------------------------------------------------------


def _evo_attn_math(q, k, v, mask, pair, sm_scale):
    """Naive fp32-softmax attention on [B, N, H, S, D] internals."""
    s = jnp.einsum("bnhqd,bnhkd->bnhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if mask is not None:
        s = s + mask.astype(jnp.float32)[:, :, :, None, :]     # [B,N,1,1,S]
    if pair is not None:
        s = s + pair.astype(jnp.float32)[:, None]              # [B,1,H,S,S]
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnhqk,bnhkd->bnhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _evo_core(q, k, v, mask, pair, sm_scale, block_q, block_k, interpret):
    if interpret == "jnp":
        return _evo_attn_math(q, k, v, mask, pair, sm_scale)
    return _evo_fwd_pallas(q, k, v, mask, pair, sm_scale, block_q, block_k, interpret)


def _evo_core_fwd(q, k, v, mask, pair, sm_scale, block_q, block_k, interpret):
    out = _evo_core(q, k, v, mask, pair, sm_scale, block_q, block_k, interpret)
    return out, (q, k, v, mask, pair)


def _evo_core_bwd(sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, mask, pair = res
    B, N, H, S, D = q.shape

    def per_row(carry, inputs):
        dpair_acc = carry
        qn, kn, vn, maskn, gn = inputs        # [B, H, S, D] / [B, 1, S] / ...
        s = jnp.einsum("bhqd,bhkd->bhqk", qn.astype(jnp.float32),
                       kn.astype(jnp.float32)) * sm_scale
        if mask is not None:
            s = s + maskn.astype(jnp.float32)[:, :, None, :]
        if pair is not None:
            s = s + pair.astype(jnp.float32)
        p = jax.nn.softmax(s, axis=-1)
        do = gn.astype(jnp.float32)
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, do)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do, vn.astype(jnp.float32))
        ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
        dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kn.astype(jnp.float32)) * sm_scale
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qn.astype(jnp.float32)) * sm_scale
        dmask = jnp.sum(ds, axis=(1, 2))[:, None, :]          # [B, 1, S]
        if pair is not None:
            dpair_acc = dpair_acc + ds
        return dpair_acc, (dq, dk, dv, dmask)

    dpair0 = jnp.zeros((B, H, S, S), jnp.float32)
    maskN = (jnp.moveaxis(mask, 1, 0) if mask is not None
             else jnp.zeros((N, B, 1, S), q.dtype))
    dpair, (dq, dk, dv, dmask) = jax.lax.scan(
        per_row, dpair0,
        (jnp.moveaxis(q, 1, 0), jnp.moveaxis(k, 1, 0), jnp.moveaxis(v, 1, 0),
         maskN, jnp.moveaxis(g, 1, 0)))
    dq = jnp.moveaxis(dq, 0, 1).astype(q.dtype)
    dk = jnp.moveaxis(dk, 0, 1).astype(k.dtype)
    dv = jnp.moveaxis(dv, 0, 1).astype(v.dtype)
    dmask_out = (jnp.moveaxis(dmask, 0, 1).astype(mask.dtype)
                 if mask is not None else None)
    dpair_out = dpair.astype(pair.dtype) if pair is not None else None
    return dq, dk, dv, dmask_out, dpair_out


_evo_core.defvjp(_evo_core_fwd, _evo_core_bwd)


# ----------------------------------------------------------------------
# public op (reference DS4Sci_EvoformerAttention signature)
# ----------------------------------------------------------------------


def evoformer_attention(q, k, v, biases=(), sm_scale=None,
                        block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                        interpret=None):
    """Biased attention for Evoformer-style models.

    q, k, v: [B, N, S, H, D] (the reference kernel's layout). `biases` is a
    sequence of additive bias arrays in the two patterns the reference accepts
    (`evoformer_attn` op: bias1 mask [B, N, 1, 1, S], bias2 pair
    [B, 1, H, S, S]); each may appear at most once. Returns [B, N, S, H, D].
    Differentiable in q/k/v and both biases.
    """
    B, N, S, H, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    mask = None
    pair = None
    for b in biases:
        if b is None:
            continue
        if b.ndim != 5:
            raise ValueError(f"bias must be 5-D, got shape {b.shape}")
        if b.shape[2] == 1 and b.shape[3] == 1:        # [B, N, 1, 1, S] mask
            if mask is not None:
                raise ValueError("duplicate mask bias")
            mask = b.reshape(b.shape[0], b.shape[1], 1, b.shape[4])
            mask = jnp.broadcast_to(mask, (B, N, 1, S))
        elif b.shape[1] == 1:                          # [B, 1, H, S, S] pair
            if pair is not None:
                raise ValueError("duplicate pair bias")
            pair = jnp.broadcast_to(b[:, 0], (B, H, S, S))
        else:
            raise ValueError(
                f"unsupported bias shape {b.shape}: expected [B,N,1,1,S] "
                "(mask) or [B,1,H,S,S] (pair)")

    qi = jnp.moveaxis(q, 3, 2)   # [B, N, H, S, D]
    ki = jnp.moveaxis(k, 3, 2)
    vi = jnp.moveaxis(v, 3, 2)

    if interpret is None:
        interpret = _use_interpret()
    bq, bk = min(block_q, S), min(block_k, S)
    use_pallas = S % bq == 0 and S % bk == 0 and S >= 8
    if use_pallas and not interpret:
        # On real hardware require tile-aligned shapes (8-sublane blocks,
        # 128-lane head dim) — same conservatism as flash_attention; anything
        # else falls back to the XLA path until hardware-verified.
        use_pallas = bq % 8 == 0 and bk % 8 == 0 and D % 128 == 0
    mode = (bq, bk, interpret) if use_pallas else None
    if mode is None:
        out = _evo_core(qi, ki, vi, mask, pair, float(sm_scale), 0, 0, "jnp")
    else:
        out = _evo_core(qi, ki, vi, mask, pair, float(sm_scale),
                        int(mode[0]), int(mode[1]), mode[2])
    return jnp.moveaxis(out, 2, 3)
