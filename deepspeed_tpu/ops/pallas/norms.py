"""Fused layer/RMS norm (Pallas).

Analog of the reference's `normalize_kernels.cu` / `rms_norm.cu`
(`csrc/transformer/`, `csrc/transformer/inference/csrc/rms_norm.cu`): one pass over
the row in VMEM, fp32 statistics, optional residual-add fusion (the
`residual_add` + norm fusion the inference kernels do).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _use_interpret():
    return jax.default_backend() not in ("tpu", "axon")


def _ln_kernel(x_ref, scale_ref, bias_ref, o_ref, *, eps):
    x = x_ref[:, :].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    y = y * scale_ref[:].astype(jnp.float32) + bias_ref[:].astype(jnp.float32)
    o_ref[:, :] = y.astype(o_ref.dtype)


def _rms_kernel(x_ref, scale_ref, o_ref, *, eps):
    x = x_ref[:, :].astype(jnp.float32)
    y = x * jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)
    o_ref[:, :] = (y * scale_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rows_blocks(n_rows):
    for b in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if n_rows % b == 0:
            return b
    return 1


def fused_layer_norm(x, scale, bias, eps=1e-5, residual=None, interpret=None):
    """LayerNorm over the last dim; optional fused residual add (x+residual first)."""
    if interpret is None:
        interpret = _use_interpret()
    if residual is not None:
        x = x + residual
    orig_shape = x.shape
    D = orig_shape[-1]
    x2 = x.reshape(-1, D)
    N = x2.shape[0]
    bn = _rows_blocks(N)
    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
            pl.BlockSpec((D,), lambda i: (0,)),
            pl.BlockSpec((D,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((bn, D), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, D), x.dtype),
        interpret=interpret,
    )(x2, scale, bias)
    return out.reshape(orig_shape)


def fused_rms_norm(x, scale, eps=1e-5, residual=None, interpret=None):
    if interpret is None:
        interpret = _use_interpret()
    if residual is not None:
        x = x + residual
    orig_shape = x.shape
    D = orig_shape[-1]
    x2 = x.reshape(-1, D)
    N = x2.shape[0]
    bn = _rows_blocks(N)
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
            pl.BlockSpec((D,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((bn, D), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, D), x.dtype),
        interpret=interpret,
    )(x2, scale)
    return out.reshape(orig_shape)
