from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
from deepspeed_tpu.ops.pallas.norms import fused_layer_norm, fused_rms_norm
from deepspeed_tpu.ops.pallas.quant import quantize_int8, dequantize_int8
