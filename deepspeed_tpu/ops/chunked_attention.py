"""Query-chunked causal attention: O(T) live memory on pure XLA.

An EXPLICIT remat/memory escape hatch (`GPTConfig.chunked_attn_min_seq`):
since the flash kernel streams K/V from HBM it has no sequence cap anymore
(`ops/pallas/flash_attention.py` — the old ~14k whole-slab VMEM domain is
gone) and is the fast path at every long T; this path remains for shapes
where activation residuals at extreme T squeeze HBM. It scans over query
blocks — each step computes a full [block_q, T] attention row strip and is
`jax.checkpoint`-rematerialized, so the live footprint is one strip forward
AND backward (the scan recomputes strips instead of saving B*H*T*T
probabilities). Historical datum: it carried gpt2-760m at seq 16384
(~0.24 attn-incl MFU) before the streaming kernel took that shape in-kernel.
"""

import math
from functools import partial

import jax
import jax.numpy as jnp


def chunked_attention(q, k, v, causal=True, sm_scale=None, block_q=1024):
    """q, k, v: [B, H, T, D] -> [B, H, T, D]. Differentiable. The softmax
    (max-subtract, exp, length-T denominator) runs fully in fp32 — strips
    are transient, so the bf16-softmax HBM-traffic trade the materialized
    path offers does not apply, and a bf16 sum over 16k terms would erode
    exactly the long-sequence probabilities this module exists to serve.
    Dots run on the input dtype (MXU-native) with fp32 accumulation."""
    B, H, T, D = q.shape
    assert k.shape[2] == T and v.shape[2] == T, (
        "chunked_attention is self-attention: q/k/v must share T "
        f"(got q T={T}, k T={k.shape[2]}, v T={v.shape[2]})")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    block_q = min(block_q, T)
    # pad the QUERY axis up to a whole number of blocks instead of shrinking
    # block_q to a divisor of T (the old `block_q //= 2` search degraded to
    # block_q=1 strips on odd T — pathologically slow, ADVICE r5 #4). Padded
    # rows attend real keys only (k/v are NOT padded), compute garbage that
    # the final slice drops, and contribute zero cotangent in backward.
    pad = -T % block_q
    in_dtype = q.dtype
    qs = (q.astype(jnp.float32) * sm_scale).astype(in_dtype)
    if pad:
        qs = jnp.pad(qs, ((0, 0), (0, 0), (0, pad), (0, 0)))
    nq = (T + pad) // block_q
    q_blocks = qs.reshape(B, H, nq, block_q, D)

    @partial(jax.checkpoint, prevent_cse=False)
    def strip(q_blk, qi):
        # [B, H, block_q, T] score strip for one query block
        s = jax.lax.dot_general(
            q_blk, k, (((3,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, T), 0)
            k_pos = jax.lax.broadcasted_iota(jnp.int32, (block_q, T), 1)
            s = jnp.where((q_pos >= k_pos)[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jax.lax.dot_general(
            p.astype(in_dtype), v, (((3,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32).astype(in_dtype)

    def body(_, xs):
        q_blk, qi = xs
        return None, strip(q_blk, qi)

    _, out = jax.lax.scan(
        body, None,
        (jnp.moveaxis(q_blocks, 2, 0), jnp.arange(nq, dtype=jnp.int32)))
    # out: [nq, B, H, block_q, D] -> [B, H, T(+pad), D]
    out = jnp.moveaxis(out, 0, 2).reshape(B, H, T + pad, D)
    return out[:, :, :T] if pad else out
