"""Query-chunked causal attention: O(T) live memory on pure XLA.

The tier ABOVE the flash kernel's single-device VMEM domain
(`ops/pallas/flash_attention.py::flash_max_seq`, ~14k tokens at head_dim
128): the kernel holds whole [T, D] k/v slabs in VMEM, and a materialized
[T, T] score tensor is already infeasible long before that. This path scans
over query blocks — each step computes a full [block_q, T] attention row
strip and is `jax.checkpoint`-rematerialized, so the live footprint is one
strip forward AND backward (the scan recomputes strips instead of saving
B*H*T*T probabilities).

Sequence-parallel deployments don't need this (ring/Ulysses shards stay
inside the kernel's domain — reference capability analog
`blogs/deepspeed-ulysses`); it serves very long single-device sequences,
e.g. gpt2-760m at seq 16384 on one v5e.
"""

import math
from functools import partial

import jax
import jax.numpy as jnp


def chunked_attention(q, k, v, causal=True, sm_scale=None, block_q=1024):
    """q, k, v: [B, H, T, D] -> [B, H, T, D]. Differentiable. The softmax
    (max-subtract, exp, length-T denominator) runs fully in fp32 — strips
    are transient, so the bf16-softmax HBM-traffic trade the materialized
    path offers does not apply, and a bf16 sum over 16k terms would erode
    exactly the long-sequence probabilities this module exists to serve.
    Dots run on the input dtype (MXU-native) with fp32 accumulation."""
    B, H, T, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    block_q = min(block_q, T)
    while T % block_q != 0:
        block_q //= 2
    nq = T // block_q
    in_dtype = q.dtype
    qs = (q.astype(jnp.float32) * sm_scale).astype(in_dtype)
    q_blocks = qs.reshape(B, H, nq, block_q, D)

    @partial(jax.checkpoint, prevent_cse=False)
    def strip(q_blk, qi):
        # [B, H, block_q, T] score strip for one query block
        s = jax.lax.dot_general(
            q_blk, k, (((3,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, T), 0)
            k_pos = jax.lax.broadcasted_iota(jnp.int32, (block_q, T), 1)
            s = jnp.where((q_pos >= k_pos)[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jax.lax.dot_general(
            p.astype(in_dtype), v, (((3,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32).astype(in_dtype)

    def body(_, xs):
        q_blk, qi = xs
        return None, strip(q_blk, qi)

    _, out = jax.lax.scan(
        body, None,
        (jnp.moveaxis(q_blocks, 2, 0), jnp.arange(nq, dtype=jnp.int32)))
    # out: [nq, B, H, block_q, D] -> [B, H, T, D]
    return jnp.moveaxis(out, 0, 2).reshape(B, H, T, D)
