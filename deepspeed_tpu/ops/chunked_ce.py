"""Chunked-vocab softmax cross-entropy (custom VJP, O(N*Vc) live memory).

The role of the reference's fused logits/softmax inference+training epilogue
kernels (`csrc/transformer/inference/csrc/softmax.cu` and the
`vocab_parallel_cross_entropy` pattern its Megatron clients use): the naive
formulation materializes [B*T, V] logits — 618 MB bf16 at the bench shape and
over twice that again for dlogits in the backward.  This op never holds more
than one [N, Vc] chunk: the forward streams the head matmul chunk-by-chunk
through an online logsumexp (same m/s recurrence as flash attention), and the
backward recomputes each chunk's logits to form (softmax - onehot) locally.

Trade: the backward re-runs the head matmul once (+2*N*D*V flops) in exchange
for never writing/reading the [N, V] logits+dlogits tensors (~4 HBM passes).
On v5e at GPT-2 vocab/width ratios that is roughly flops-neutral but frees
~1.2 GB of peak HBM — the binding constraint on the 1.3B single-chip lane.

Pure XLA (lax.scan over weight chunks) — no Pallas needed: each chunk's
matmul is a full-width MXU op already, and XLA fuses the logsumexp update
into its epilogue.
"""

import functools

import jax
import jax.numpy as jnp


def _pad_rows(w, n_chunks):
    """Pad [V, D] to a multiple of n_chunks*128 rows; returns (w3, Vc, V_pad)."""
    V = w.shape[0]
    per = -(-V // n_chunks)            # ceil
    per = -(-per // 128) * 128         # round chunk up to the 128 lane width
    V_pad = per * n_chunks
    if V_pad != V:
        w = jnp.pad(w, ((0, V_pad - V), (0, 0)))
    return w.reshape(n_chunks, per, w.shape[-1]), per, V_pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def chunked_softmax_xent(x, w, labels, n_chunks=12):
    """Per-token negative log-likelihood without materializing [N, V].

    x: [N, D] activations (any float dtype); w: [V, D] head/embedding table
    (vocab-major, matching the zoo's tied `wte`); labels: [N] int32 — entries
    < 0 are treated as index 0 (callers mask the returned nll; the cotangent
    of a masked token is 0, so its gradient contribution vanishes).
    Returns nll [N] float32.
    """
    nll, _ = _fwd(x, w, labels, n_chunks)
    return nll


def _fwd(x, w, labels, n_chunks):
    N, D = x.shape
    V = w.shape[0]
    w3, per, V_pad = _pad_rows(w, n_chunks)
    safe = jnp.maximum(labels, 0)

    def body(carry, inputs):
        m, s, gold = carry
        ci, w_c = inputs
        off = ci * per
        l_c = jax.lax.dot_general(x, w_c, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)  # [N, per]
        if V_pad != V:
            col = off + jax.lax.broadcasted_iota(jnp.int32, l_c.shape, 1)
            l_c = jnp.where(col < V, l_c, -jnp.inf)
        m_c = jnp.max(l_c, axis=-1)
        m_new = jnp.maximum(m, m_c)
        s = s * jnp.exp(m - m_new) + jnp.sum(jnp.exp(l_c - m_new[:, None]), axis=-1)
        idx = jnp.clip(safe - off, 0, per - 1)
        in_chunk = (safe >= off) & (safe < off + per)
        picked = jnp.take_along_axis(l_c, idx[:, None], axis=-1)[:, 0]
        gold = jnp.where(in_chunk, picked, gold)
        return (m_new, s, gold), None

    m0 = jnp.full((N,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((N,), jnp.float32)
    g0 = jnp.zeros((N,), jnp.float32)
    (m, s, gold), _ = jax.lax.scan(
        body, (m0, s0, g0), (jnp.arange(n_chunks), w3))
    lse = m + jnp.log(s)
    return lse - gold, (x, w, labels, lse)


def _fwd_vjp(x, w, labels, n_chunks):
    return _fwd(x, w, labels, n_chunks)


def _bwd_vjp(n_chunks, res, g):
    x, w, labels, lse = res
    N, D = x.shape
    V = w.shape[0]
    w3, per, V_pad = _pad_rows(w, n_chunks)
    safe = jnp.maximum(labels, 0)
    in_dtype = x.dtype

    def body(dx, inputs):
        ci, w_c = inputs
        off = ci * per
        l_c = jax.lax.dot_general(x, w_c, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        p = jnp.exp(l_c - lse[:, None])                       # softmax chunk
        if V_pad != V:
            col = off + jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
            p = jnp.where(col < V, p, 0.0)
        onehot = ((safe[:, None] - off) ==
                  jax.lax.broadcasted_iota(jnp.int32, p.shape, 1))
        dl = ((p - onehot.astype(jnp.float32)) * g[:, None]).astype(in_dtype)
        dx = dx + jax.lax.dot_general(dl, w_c, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dw_c = jax.lax.dot_general(dl, x, (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        return dx, dw_c.astype(w.dtype)

    dx0 = jnp.zeros((N, D), jnp.float32)
    dx, dw3 = jax.lax.scan(body, dx0, (jnp.arange(n_chunks), w3))
    dw = dw3.reshape(-1, D)[:V]
    return dx.astype(in_dtype), dw, None


chunked_softmax_xent.defvjp(_fwd_vjp, _bwd_vjp)
