"""Lion (reference `deepspeed/ops/lion/fused_lion.py:17`, `cpu_lion.py:13`)."""

import optax


def FusedLion(params=None, lr=1e-4, betas=(0.9, 0.99), weight_decay=0.0):
    return optax.lion(lr, b1=betas[0], b2=betas[1], weight_decay=weight_decay)


def DeepSpeedCPULion(model_params=None, lr=1e-4, betas=(0.9, 0.99), weight_decay=0.0):
    from deepspeed_tpu.ops.optim import mark_host_offload
    return mark_host_offload(FusedLion(model_params, lr=lr, betas=betas, weight_decay=weight_decay))
