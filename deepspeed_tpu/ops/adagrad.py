"""Adagrad (reference `deepspeed/ops/adagrad/cpu_adagrad.py:11`)."""

import optax


def DeepSpeedCPUAdagrad(model_params=None, lr=1e-2, eps=1e-10, weight_decay=0.0):
    from deepspeed_tpu.ops.optim import mark_host_offload
    tx = optax.adagrad(lr, eps=eps)
    if weight_decay:
        tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    return mark_host_offload(tx)
