"""Block-sparse attention.

Reference: `ops/sparse_attention/` (2.3k LoC Triton) — `SparseSelfAttention`
with sparsity configs (Fixed, BigBird, BSLongformer, Variable) over block
layouts. The config classes are ported semantically (same layout math).

Compute path: a real Pallas block-sparse flash kernel
(`ops/pallas/block_sparse_attention.py` — per-row visit lists over the block
layout, analog of the reference's Triton SDD/DSD kernels
`ops/sparse_attention/matmul.py:17`) whenever T is a 128-multiple; measured
on v5e at T=8k / 26% density: 3.9 ms vs 8.8 ms for the dense masked path
(2.3x), scaling with density. `rpe` / batch-shared `attn_mask` /
`key_padding_mask` stream IN-KERNEL (additive bias slabs + key-padding row,
like the reference's Triton softmax `ops/sparse_attention/softmax.py`); only
a batched [B, T, T] attn_mask or an odd T still falls back to the dense
masked fp32 einsum, with a loud warning.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np


class SparsityConfig:
    """Base (reference `sparsity_config.py`): builds a [num_blocks, num_blocks]
    bool layout, True = attend."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len):
        assert seq_len % self.block == 0, f"seq {seq_len} % block {self.block} != 0"
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), dtype=bool), n

    def make_layout(self, seq_len):
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    def make_layout(self, seq_len):
        layout, n = self.setup_layout(seq_len)
        layout[:] = True
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Fixed pattern: local windows of `num_local_blocks` + global attention to
    the last `num_global_blocks` of each window (reference same semantics)."""

    def __init__(self, num_heads, block=16, num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional", horizontal_global_attention=False,
                 num_different_global_patterns=1, different_layout_per_head=False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global = horizontal_global_attention

    def make_layout(self, seq_len):
        layout, n = self.setup_layout(seq_len)
        L, G = self.num_local_blocks, self.num_global_blocks
        for i in range(n):
            w = i // L
            # local window
            start = w * L
            for j in range(start, min(start + L, n)):
                layout[:, i, j] = True
            # global: last G blocks of every previous window
            for pw in range(w + 1):
                g0 = (pw + 1) * L - G
                for j in range(max(g0, 0), min((pw + 1) * L, n)):
                    layout[:, i, j] = True
                    if self.horizontal_global:
                        layout[:, j, i] = True
        if self.attention == "unidirectional":
            tril = np.tril(np.ones((n, n), dtype=bool))
            layout &= tril[None]
        return layout


class BigBirdSparsityConfig(SparsityConfig):
    """random + sliding-window + global blocks (reference same knobs)."""

    def __init__(self, num_heads, block=16, num_random_blocks=1,
                 num_sliding_window_blocks=3, num_global_blocks=1,
                 attention="bidirectional", seed=0, different_layout_per_head=False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding = num_sliding_window_blocks
        self.num_global = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len):
        layout, n = self.setup_layout(seq_len)
        rng = np.random.default_rng(self.seed)
        w = self.num_sliding // 2
        for i in range(n):
            for j in range(max(0, i - w), min(n, i + w + 1)):
                layout[:, i, j] = True
        layout[:, :, :self.num_global] = True
        layout[:, :self.num_global, :] = True
        for h in range(self.num_heads if self.different_layout_per_head else 1):
            for i in range(n):
                for j in rng.choice(n, size=min(self.num_random_blocks, n), replace=False):
                    layout[h if self.different_layout_per_head else slice(None), i, j] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return layout


class BSLongformerSparsityConfig(SparsityConfig):
    """sliding window + selected global block indices."""

    def __init__(self, num_heads, block=16, num_sliding_window_blocks=3,
                 global_block_indices=(0,), global_block_end_indices=None,
                 attention="bidirectional", different_layout_per_head=False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding = num_sliding_window_blocks
        self.global_idx = list(global_block_indices)
        self.global_end = (list(global_block_end_indices)
                           if global_block_end_indices is not None else None)
        self.attention = attention

    def make_layout(self, seq_len):
        layout, n = self.setup_layout(seq_len)
        w = self.num_sliding // 2
        for i in range(n):
            for j in range(max(0, i - w), min(n, i + w + 1)):
                layout[:, i, j] = True
        _apply_globals(layout, n, self.global_idx, self.global_end, horizontal=True)
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return layout


class VariableSparsityConfig(SparsityConfig):
    """local windows of varying sizes + globals (reference `VariableSparsityConfig`)."""

    def __init__(self, num_heads, block=16, num_random_blocks=0,
                 local_window_blocks=(4,), global_block_indices=(0,),
                 global_block_end_indices=None, attention="bidirectional",
                 horizontal_global_attention=False, different_layout_per_head=False,
                 seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_windows = list(local_window_blocks)
        self.global_idx = list(global_block_indices)
        self.global_end = (list(global_block_end_indices)
                           if global_block_end_indices is not None else None)
        self.attention = attention
        self.horizontal_global = horizontal_global_attention
        self.seed = seed

    def make_layout(self, seq_len):
        layout, n = self.setup_layout(seq_len)
        start = 0
        wi = 0
        while start < n:
            size = self.local_windows[min(wi, len(self.local_windows) - 1)]
            end = min(start + size, n)
            layout[:, start:end, start:end] = True
            start = end
            wi += 1
        _apply_globals(layout, n, self.global_idx, self.global_end,
                       horizontal=self.horizontal_global)
        if self.num_random_blocks > 0:
            rng = np.random.default_rng(self.seed)
            heads = range(self.num_heads) if self.different_layout_per_head else [slice(None)]
            for h in heads:
                for i in range(n):
                    for j in rng.choice(n, size=min(self.num_random_blocks, n),
                                        replace=False):
                        layout[h, i, j] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return layout


def _apply_globals(layout, n, global_idx, global_end, horizontal):
    """Global attention blocks: single indices, or ranges when end indices given
    (reference `sparsity_config.py` global_block_end_indices semantics)."""
    if global_end is not None:
        cols = []
        for s, e in zip(global_idx, global_end):
            cols.extend(range(s, min(e, n)))
    else:
        cols = [g for g in global_idx if g < n]
    for g in cols:
        layout[:, :, g] = True
        if horizontal:
            layout[:, g, :] = True


class SparseSelfAttention:
    """Reference `sparse_self_attention.py` API: __call__(q, k, v) with layout
    masking. q,k,v: [B, H, T, hd] (reference layout)."""

    def __init__(self, sparsity_config=None, softmax_scale=None, attn_mask_mode="mul",
                 rpe_requires_grad=True):
        self.config = sparsity_config or FixedSparsityConfig(num_heads=4)
        self.softmax_scale = softmax_scale
        self.attn_mask_mode = attn_mask_mode
        # rpe_requires_grad=False marks the rpe as a frozen/constant table:
        # the kernel then skips the dense [B,Hb,nbq,nbk,bq,bk] fp32 dbias
        # output in backward (full-T^2 HBM — ~256MB x B x Hb at T=8k), which
        # is exactly the memory regime the sparse kernel exists to avoid
        # (ADVICE r5 #1). Leave True for learned rpe tables.
        self.rpe_requires_grad = rpe_requires_grad
        self._layouts = {}
        self._warned = set()

    def _warn_once(self, key, msg):
        """Dense-fallback warnings dedup per (reason, shape): an eager
        per-token loop would otherwise emit one warning per call."""
        if key not in self._warned:
            self._warned.add(key)
            from deepspeed_tpu.utils.logging import logger
            logger.warning(msg)

    def _mask(self, seq_len):
        if seq_len not in self._layouts:
            layout = self.config.make_layout(seq_len)       # [H, n, n] blocks
            mask = np.kron(layout, np.ones((self.config.block, self.config.block),
                                           dtype=bool))    # [H, T, T]
            self._layouts[seq_len] = jnp.asarray(mask)
        return self._layouts[seq_len]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None):
        B, H, T, hd = query.shape
        scale = self.softmax_scale or 1.0 / math.sqrt(hd)
        # kernel path: rpe and a batch-shared attn_mask stream in-kernel as an
        # additive [Hb, T, T] bias, key_padding_mask as a [B, T] additive row
        # (reference streams the same operands through its Triton softmax,
        # `ops/sparse_attention/softmax.py`). Only a BATCHED [B, T, T]
        # attn_mask (or a non-128-multiple T) still takes the dense path.
        kernel_ok = T % 128 == 0
        bias = None
        if kernel_ok and rpe is not None:
            r = jnp.asarray(rpe)
            if r.ndim == 2:
                r = r[None]
            if r.ndim == 3 and r.shape[-2:] == (T, T) and r.shape[0] in (1, H):
                bias = r.astype(jnp.float32)
            else:
                kernel_ok = False
        if kernel_ok and attn_mask is not None:
            m = jnp.asarray(attn_mask)
            # batch-shared masks arrive as [1, T, T] / [1, 1, T, T] as often
            # as [T, T]; squeeze leading size-1 dims before the gate so they
            # take the kernel instead of silently falling dense (ADVICE r5
            # #2 — mirrors the rpe handling, which accepts a leading 1)
            while m.ndim > 2 and m.shape[0] == 1:
                m = m[0]
            if m.ndim == 2 and m.shape == (T, T):
                mb = (jnp.where(m != 0, 0.0, -1e30)
                      if self.attn_mask_mode == "mul"
                      else m.astype(jnp.float32))[None]
                bias = mb if bias is None else bias + mb
            else:
                kernel_ok = False
        kpm = None
        if kernel_ok and key_padding_mask is not None:
            p = jnp.asarray(key_padding_mask)
            if p.shape == (B, T):
                kpm = p if p.dtype == jnp.bool_ else p != 0
            else:
                kernel_ok = False
        if kernel_ok:
            from deepspeed_tpu.ops.pallas.block_sparse_attention import \
                BiasVmemBudgetError, block_sparse_attention
            key_ = ("layout", T)
            if key_ not in self._layouts:
                self._layouts[key_] = self.config.make_layout(T)
            try:
                return block_sparse_attention(
                    query, key, value, self._layouts[key_],
                    block=self.config.block, sm_scale=scale, bias=bias,
                    key_padding_mask=kpm,

                    # the (dense-T^2) dbias output is emitted exactly where
                    # the dense path was differentiable: a LEARNED rpe
                    # (rpe_requires_grad), and ADDITIVE attn_masks (a
                    # mul-mode mask only feeds a where() condition — zero
                    # gradient there too)
                    bias_needs_grad=((rpe is not None
                                      and self.rpe_requires_grad)
                                     or (attn_mask is not None and
                                         self.attn_mask_mode == "add")))
            except BiasVmemBudgetError as e:
                # only the VMEM budget downgrades to dense — any other
                # kernel error is a real bug and surfaces normally
                self._warn_once(
                    ("vmem", T),
                    f"SparseSelfAttention: kernel path unavailable ({e})")
        self._warn_once(
            ("dense", T),
            f"SparseSelfAttention: dense O(T^2) fallback engaged (T={T}; "
            "kernel needs T % 128 == 0 and batch-shared masks) — at long "
            "sequences this defeats the sparse kernel's memory/compute "
            "savings")
        mask = self._mask(T)                                # [H, T, T]
        s = jnp.einsum("bhtd,bhsd->bhts", query.astype(jnp.float32),
                       key.astype(jnp.float32)) * scale
        if rpe is not None:
            s = s + rpe
        s = jnp.where(mask[None], s, -1e30)
        if attn_mask is not None:
            # reference attn_mask_mode: "mul" = boolean/0-1 keep mask, "add" =
            # additive bias on scores. Mask broadcasts over [B?, T, T].
            attn_mask = jnp.asarray(attn_mask)
            while attn_mask.ndim < 4:
                attn_mask = attn_mask[None]
            if self.attn_mask_mode == "mul":
                s = jnp.where(attn_mask != 0, s, -1e30)
            else:
                s = s + attn_mask.astype(s.dtype)
        if key_padding_mask is not None:
            s = jnp.where(key_padding_mask[:, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhts,bhsd->bhtd", p, value.astype(jnp.float32)) \
            .astype(query.dtype)


class BertSparseSelfAttention(SparseSelfAttention):
    """Name-parity wrapper (reference `bert_sparse_self_attention.py`)."""
    pass


def sparse_attn_fn(sparsity_config, softmax_scale=None, causal=None):
    """Adapter for the model zoo's `attn_fn` slot (`models/gpt.py::_attention`:
    q,k,v as [B, T, H, hd]) — GPT-style training/inference with block-sparse
    attention, the reference's `SparseSelfAttention` drop-in for long
    sequences.

    Causality: a config with attention="unidirectional" tril-masks the layout
    at BLOCK granularity only (a diagonal block is fully open), so this
    adapter additionally applies TOKEN-granular causal masking inside the
    kernel for such configs (`causal` defaults to that inference; override
    explicitly for encoder use).

        model = make_gpt_model(cfg=cfg, attn_fn=sparse_attn_fn(
            FixedSparsityConfig(num_heads=cfg.n_head, attention="unidirectional")))
    """
    from deepspeed_tpu.ops.pallas.block_sparse_attention import \
        block_sparse_attention
    if causal is None:
        causal = getattr(sparsity_config, "attention", "") == "unidirectional"
    layouts = {}

    def fn(q, k, v):
        q, k, v = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))   # -> [B,H,T,hd]
        B, H, T, hd = q.shape
        scale = softmax_scale or 1.0 / math.sqrt(hd)
        if T not in layouts:
            layouts[T] = sparsity_config.make_layout(T)
        out = block_sparse_attention(q, k, v, layouts[T],
                                     block=sparsity_config.block,
                                     sm_scale=scale, causal=causal)
        return jnp.swapaxes(out, 1, 2)

    return fn
