"""Native op build system.

Analog of the reference's `op_builder/builder.py:102` (`OpBuilder` ABC with JIT
build at `:448`): compiles the C++ host libraries on first use with g++ and
loads them via ctypes. No CUDA/torch-extension machinery — the TPU compute path
is Pallas/XLA; native code here is host-side (AIO swap, CPU optimizers).
"""

import ctypes
import os
import pathlib
import subprocess
import threading

from deepspeed_tpu.utils.logging import logger

_REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
_CSRC = _REPO_ROOT / "csrc"
_BUILD_DIR = pathlib.Path(__file__).resolve().parent / "_native"
_LOCK = threading.Lock()
_LOADED = {}


class OpBuilder:
    """Base: named native library, lazily JIT-built and ctypes-loaded."""

    NAME = None
    SOURCES = ()

    def lib_path(self):
        return _BUILD_DIR / f"lib{self.NAME}.so"

    def is_compatible(self):
        return os.name == "posix"

    def sources(self):
        return [str(_CSRC / s) for s in self.SOURCES]

    def build(self, verbose=False):
        out = self.lib_path()
        srcs = self.sources()
        if out.exists() and all(out.stat().st_mtime >= pathlib.Path(s).stat().st_mtime
                                for s in srcs):
            return out
        _BUILD_DIR.mkdir(parents=True, exist_ok=True)
        cmd = ["g++", "-O3", "-march=native", "-fopenmp", "-fPIC", "-std=c++17",
               *srcs, "-shared", "-lpthread", "-o", str(out)]
        logger.info(f"building native op {self.NAME}: {' '.join(cmd)}")
        subprocess.run(cmd, check=True, capture_output=not verbose)
        return out

    def load(self, verbose=False):
        with _LOCK:
            if self.NAME in _LOADED:
                return _LOADED[self.NAME]
            path = self.build(verbose=verbose)
            lib = ctypes.CDLL(str(path))
            self.annotate(lib)
            _LOADED[self.NAME] = lib
            return lib

    def annotate(self, lib):
        pass


class AsyncIOBuilder(OpBuilder):
    """Reference `op_builder/async_io.py` role."""

    NAME = "dstpu_aio"
    SOURCES = ("aio/dstpu_aio.cpp",)

    def annotate(self, lib):
        lib.dstpu_aio_create.restype = ctypes.c_void_p
        lib.dstpu_aio_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.dstpu_aio_create_ex.restype = ctypes.c_void_p
        lib.dstpu_aio_create_ex.argtypes = [ctypes.c_int, ctypes.c_int,
                                            ctypes.c_int, ctypes.c_int]
        lib.dstpu_aio_destroy.argtypes = [ctypes.c_void_p]
        for fn in (lib.dstpu_aio_pread, lib.dstpu_aio_pwrite):
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                           ctypes.c_int64, ctypes.c_int64]
        lib.dstpu_aio_wait.restype = ctypes.c_int64
        lib.dstpu_aio_wait.argtypes = [ctypes.c_void_p]
        lib.dstpu_aio_pending.restype = ctypes.c_int64
        lib.dstpu_aio_pending.argtypes = [ctypes.c_void_p]


class CPUAdamBuilder(OpBuilder):
    """Reference `op_builder/cpu_adam.py` role (also carries Lion/Adagrad)."""

    NAME = "dstpu_cpu_optim"
    SOURCES = ("cpu_optim/dstpu_cpu_adam.cpp",)

    def annotate(self, lib):
        lib.dstpu_cpu_adam_step.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_int, ctypes.c_int64,
            ctypes.c_int]
        lib.dstpu_cpu_lion_step.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float]
        lib.dstpu_cpu_adagrad_step.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float]
        lib.dstpu_fp32_to_bf16.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                           ctypes.c_int64]


class DataLoaderBuilder(OpBuilder):
    """Native prefetching token-dataset loader (the torch-DataLoader-worker
    role of the reference's `runtime/dataloader.py`)."""

    NAME = "dstpu_dataloader"
    SOURCES = ("dataloader/dstpu_dataloader.cpp",)

    def annotate(self, lib):
        lib.dstpu_dl_create.restype = ctypes.c_void_p
        lib.dstpu_dl_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int]
        lib.dstpu_dl_num_tokens.restype = ctypes.c_int64
        lib.dstpu_dl_num_tokens.argtypes = [ctypes.c_void_p]
        lib.dstpu_dl_next.restype = ctypes.c_int64
        lib.dstpu_dl_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.dstpu_dl_destroy.argtypes = [ctypes.c_void_p]


ALL_OPS = {b.NAME: b for b in (AsyncIOBuilder(), CPUAdamBuilder(),
                               DataLoaderBuilder())}


def get_op_builder(name):
    return ALL_OPS[name]
