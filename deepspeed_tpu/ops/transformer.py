"""Reference-name frontends for the fused transformer (BERT-era) layer.

Reference: `deepspeed/ops/transformer/transformer.py:296` — a torch module
wrapping the ~5k-line fused CUDA encoder layer (`csrc/transformer/`). On TPU
the fused layer is `models/bert.py::_bert_block` compiled by XLA (norm/gelu/
bias chains fuse automatically; flash attention engages at long seq), so the
class here is a thin *name-parity* frontend: the reference constructor
surface, a per-layer params pytree, and `__call__`/`forward` applying one
encoder block. Knobs that steer the CUDA kernel's memory strategy
(normalize_invertible, gelu_checkpoint, attn_dropout_checkpoint,
stochastic_mode) are accepted and ignored — remat policies own that tradeoff
here (`runtime/activation_checkpointing.py`). Dropout ratios are accepted for
constructor parity but NOT applied (the TPU zoo trains dropout-free, like
modern LLM pretraining); a nonzero ratio logs a warning rather than silently
regularizing differently.
"""

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import logger


class DeepSpeedTransformerConfig:
    """Constructor-parity config (reference `transformer.py:33`)."""

    def __init__(self, batch_size=1, hidden_size=768, intermediate_size=None,
                 heads=12, attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
                 num_hidden_layers=12, initializer_range=0.02, layer_norm_eps=1e-12,
                 local_rank=-1, seed=0, fp16=False, bf16=True,
                 pre_layer_norm=True, normalize_invertible=False,
                 gelu_checkpoint=False, adjust_init_range=True,
                 attn_dropout_checkpoint=False, stochastic_mode=False,
                 return_tuple=False, training=True):
        self.batch_size = batch_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.heads = heads
        self.attn_dropout_ratio = attn_dropout_ratio
        self.hidden_dropout_ratio = hidden_dropout_ratio
        if attn_dropout_ratio or hidden_dropout_ratio:
            logger.warning("DeepSpeedTransformerConfig: dropout ratios are "
                           "accepted for parity but not applied on this path")
        self.num_hidden_layers = num_hidden_layers
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps
        self.local_rank = local_rank
        self.seed = seed
        self.fp16 = fp16
        self.bf16 = bf16
        self.pre_layer_norm = pre_layer_norm
        # memory-strategy knobs of the CUDA kernel: accepted, remat owns this
        self.normalize_invertible = normalize_invertible
        self.gelu_checkpoint = gelu_checkpoint
        self.adjust_init_range = adjust_init_range
        self.attn_dropout_checkpoint = attn_dropout_checkpoint
        self.stochastic_mode = stochastic_mode
        self.return_tuple = return_tuple
        self.training = training
        self.layer_id = -1

    @classmethod
    def from_dict(cls, json_object):
        cfg = cls()
        for key, value in json_object.items():
            setattr(cfg, key, value)
        if "hidden_size" in json_object and "intermediate_size" not in json_object:
            cfg.intermediate_size = 4 * cfg.hidden_size  # re-derive, don't keep stale
        if cfg.attn_dropout_ratio or cfg.hidden_dropout_ratio:
            # setattr bypassed __init__'s check — re-warn here
            logger.warning("DeepSpeedTransformerConfig: dropout ratios are "
                           "accepted for parity but not applied on this path")
        return cfg


class DeepSpeedTransformerLayer:
    """One fused encoder layer (reference `transformer.py:296`).

    Owns its params (a pytree of jnp arrays, initializer matching the
    reference's truncated-normal-ish init incl. the sqrt(2L) output
    adjustment) and applies `models/bert.py::_bert_block` on call.
    """

    def __init__(self, config: DeepSpeedTransformerConfig, initial_weights=None,
                 initial_biases=None, layer_id=None):
        """`layer_id`: explicit layer index. Default None auto-increments a
        process-global counter (the reference's static layer_id behavior) —
        note that makes the seeded init depend on how many layers were EVER
        constructed in the process; pass layer_id explicitly for
        reproducible seeded initialization."""
        from deepspeed_tpu.models.bert import BertConfig

        self.config = config
        if layer_id is None:
            layer_id = getattr(DeepSpeedTransformerLayer, "_layer_id", 0)
        # explicit ids also advance the counter past themselves so a later
        # default-constructed layer can't duplicate an explicit id (and its
        # seeded init)
        DeepSpeedTransformerLayer._layer_id = max(
            getattr(DeepSpeedTransformerLayer, "_layer_id", 0), layer_id + 1)
        self.config.layer_id = layer_id

        dtype = (jnp.float16 if config.fp16
                 else jnp.bfloat16 if config.bf16 else jnp.float32)
        self._bert_cfg = BertConfig(
            n_layer=1, n_head=config.heads, d_model=config.hidden_size,
            d_ff=config.intermediate_size, norm_eps=config.layer_norm_eps,
            pre_layer_norm=config.pre_layer_norm, remat=False, dtype=dtype)

        D, F = config.hidden_size, config.intermediate_size
        rng = np.random.default_rng(config.seed + self.config.layer_id)
        std = config.initializer_range
        out_std = (std / np.sqrt(2.0 * config.num_hidden_layers)
                   if config.adjust_init_range else std)

        def norm(shape, scale):
            return jnp.asarray(rng.normal(0.0, scale, shape), dtype)

        self.params = {
            "attn_qkv_w": norm((D, 3 * D), std),
            "attn_qkv_b": jnp.zeros((3 * D,), dtype),
            "attn_out_w": norm((D, D), out_std),
            "attn_out_b": jnp.zeros((D,), dtype),
            "ln1_scale": jnp.ones((D,), dtype),
            "ln1_bias": jnp.zeros((D,), dtype),
            "mlp_up_w": norm((D, F), std),
            "mlp_up_b": jnp.zeros((F,), dtype),
            "mlp_down_w": norm((F, D), out_std),
            "mlp_down_b": jnp.zeros((D,), dtype),
            "ln2_scale": jnp.ones((D,), dtype),
            "ln2_bias": jnp.zeros((D,), dtype),
        }
        if initial_weights is not None or initial_biases is not None:
            # reference 8-entry layout (`transformer.py:339-358`):
            # weights [q, k, v, attn_ow, attn_nw, inter_w, output_w, norm_w],
            # biases  [-, -, -, attn_ob, attn_nb, inter_b, output_b, norm_b]
            # (qkv biases are ZEROED by the reference). torch Linear weights
            # are [out, in] → transposed into this file's [in, out] layout;
            # LN entries are 1-D and copied directly. attn_n* is the
            # attention-ADJACENT LN and norm_* the MLP/final-adjacent LN in
            # both residual placements (post-LN: after the attention add /
            # after the MLP add; pre-LN: before attention / before MLP), so
            # the ln1/ln2 mapping below holds for either pre_layer_norm.
            assert initial_weights is not None and initial_biases is not None \
                and len(initial_weights) == 8 and len(initial_biases) == 8, \
                "initial_weights/initial_biases must be the reference's " \
                "8-entry lists (transformer.py:339-358)"

            def w(i):
                return jnp.asarray(np.asarray(initial_weights[i]), dtype)

            def b(i):
                return jnp.asarray(np.asarray(initial_biases[i]), dtype)

            self.params["attn_qkv_w"] = jnp.concatenate(
                [w(0), w(1), w(2)], axis=0).T
            self.params["attn_qkv_b"] = jnp.zeros((3 * D,), dtype)
            self.params["attn_out_w"] = w(3).T
            self.params["attn_out_b"] = b(3)
            self.params["ln1_scale"] = w(4)
            self.params["ln1_bias"] = b(4)
            self.params["mlp_up_w"] = w(5).T
            self.params["mlp_up_b"] = b(5)
            self.params["mlp_down_w"] = w(6).T
            self.params["mlp_down_b"] = b(6)
            self.params["ln2_scale"] = w(7)
            self.params["ln2_bias"] = b(7)

    def __call__(self, hidden_states, attention_mask=None, params=None):
        """hidden_states [B, T, D]; attention_mask [B, T] (1 = keep) or an
        additive [B, 1, 1, T] bias, like the reference's forward."""
        from deepspeed_tpu.models.bert import _bert_block

        x = jnp.asarray(hidden_states, self._bert_cfg.dtype)
        if attention_mask is None:
            mask_bias = jnp.zeros((x.shape[0], 1, 1, x.shape[1]), jnp.float32)
        elif attention_mask.ndim == 2:
            mask_bias = jnp.where(attention_mask[:, None, None, :] != 0,
                                  0.0, -1e30).astype(jnp.float32)
        else:
            mask_bias = jnp.asarray(attention_mask, jnp.float32)
        out = _bert_block(x, params or self.params, mask_bias, self._bert_cfg)
        return (out,) if self.config.return_tuple else out

    forward = __call__


__all__ = ["DeepSpeedTransformerConfig", "DeepSpeedTransformerLayer"]
