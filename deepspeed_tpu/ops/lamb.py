"""LAMB (reference `deepspeed/ops/lamb/fused_lamb.py:14` over
`csrc/lamb/fused_lamb_cuda_kernel.cu`) as an optax transformation."""

import optax


def FusedLamb(params=None,
              lr=1e-3,
              bias_correction=True,
              betas=(0.9, 0.999),
              eps=1e-8,
              weight_decay=0.0,
              max_grad_norm=0.0,
              max_coeff=10.0,
              min_coeff=0.01):
    tx = optax.lamb(lr, b1=betas[0], b2=betas[1], eps=eps, weight_decay=weight_decay)
    if max_grad_norm and max_grad_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(max_grad_norm), tx)
    return tx
