"""Fault-injection harness for the crash-safe checkpoint / recovery paths.

Three fault families, matching the failure modes that actually brick TPU-pod
runs:

  * `crash_save(point)` — kill a `save_checkpoint` at a precise moment of the
    atomic-commit protocol (state written / manifest written / committed but
    `latest` not advanced), via the hook points `checkpoint/saver.py` exposes.
  * `corrupt_checkpoint` / `corrupt_file` — bit-flip, truncate or delete
    checkpoint payload or manifest files on disk, the way a partial write or
    storage fault would.
  * `poison_batch` — plant a NaN in a batch so the very next step produces
    non-finite gradients/loss at a chosen moment, driving the engine's
    bad-state sentinel (`runtime/sentinel.py`).

Used by `tests/test_fault_tolerance.py` to prove every recovery path
end-to-end; safe to use in integration harnesses (the context managers always
deinstall their hooks).
"""

import contextlib
import copy
import os
import pathlib

import numpy as np

from deepspeed_tpu.checkpoint import manifest as manifest_mod
from deepspeed_tpu.checkpoint import saver as saver_mod


class FaultInjected(RuntimeError):
    """The simulated failure raised by installed fault hooks."""


# ----------------------------------------------------------------------
# mid-save crash injection
# ----------------------------------------------------------------------

SAVE_CRASH_POINTS = ("after_state_save", "before_commit", "after_commit")


@contextlib.contextmanager
def crash_save(point="before_commit", match_tag=None):
    """Make the next `save_checkpoint` die at `point`:

      after_state_save — state durable, no metadata/manifest yet (the classic
                         preemption-during-save): tag stays uncommitted
      before_commit    — manifest written but rename-commit never runs: the
                         staging dir is orphaned, `latest` untouched
      after_commit     — tag committed but `latest` never advances: the scan
                         fallback must still find it

    `match_tag` restricts the crash to one tag (other saves pass through).
    The exception surfaces as `FaultInjected` (sync saves) or out of
    `wait_pending_save` / the async engine's `wait()` (async saves).
    """
    assert point in SAVE_CRASH_POINTS, f"unknown crash point {point!r}"

    def hook(point=None, tag=None, **_ctx):
        if match_tag is not None and str(tag) != str(match_tag):
            return
        raise FaultInjected(f"injected crash at {point} (tag={tag})")

    prev = saver_mod._FAULT_HOOKS.get(point)
    saver_mod._FAULT_HOOKS[point] = hook
    try:
        yield
    finally:
        if prev is None:
            saver_mod._FAULT_HOOKS.pop(point, None)
        else:
            saver_mod._FAULT_HOOKS[point] = prev


# ----------------------------------------------------------------------
# on-disk corruption
# ----------------------------------------------------------------------


def corrupt_file(path, n_bytes=16, offset=None, mode="flip"):
    """Damage a file in place: `flip` XORs `n_bytes` at `offset` (default:
    the middle of the file), `truncate` drops the second half, `delete`
    removes it."""
    path = pathlib.Path(path)
    assert path.is_file(), f"cannot corrupt missing file {path}"
    if mode == "delete":
        path.unlink()
        return
    size = path.stat().st_size
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 0))
        return
    assert mode == "flip", f"unknown corruption mode {mode!r}"
    if size == 0:
        with open(path, "ab") as f:
            f.write(b"\xff")
        return
    off = size // 2 if offset is None else min(offset, size - 1)
    with open(path, "r+b") as f:
        f.seek(off)
        chunk = f.read(n_bytes)
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in chunk))


def corrupt_checkpoint(save_dir, tag=None, target="state", mode="flip"):
    """Corrupt a committed checkpoint tag. `target`:

      state    — the largest state payload file (bit-flip a real shard)
      manifest — the integrity manifest itself
      client   — client.json

    Returns the corrupted file's path."""
    save_dir = pathlib.Path(save_dir)
    tag = tag or saver_mod.get_latest_tag(save_dir)
    assert tag is not None, f"no checkpoint tag to corrupt in {save_dir}"
    ckpt_dir = save_dir / str(tag)
    if target == "manifest":
        victim = ckpt_dir / manifest_mod.MANIFEST_FILE
    elif target == "client":
        victim = ckpt_dir / "client.json"
    else:
        assert target == "state", f"unknown corruption target {target!r}"
        state_files = [p for p in (ckpt_dir / "state").rglob("*")
                       if p.is_file()]
        assert state_files, f"no state files under {ckpt_dir / 'state'}"
        victim = max(state_files, key=lambda p: p.stat().st_size)
    corrupt_file(victim, mode=mode)
    return str(victim)


# ----------------------------------------------------------------------
# NaN-gradient injection
# ----------------------------------------------------------------------


def poison_batch(batch, value=np.nan):
    """Return a copy of `batch` with `value` planted in its first float leaf —
    the next `train_batch` on it produces non-finite loss/gradients, which is
    how a real numeric blow-up presents to the engine's sentinel."""
    poisoned = copy.deepcopy(batch)

    def _plant(tree):
        if isinstance(tree, dict):
            for k in tree:
                if _plant_leaf(tree, k):
                    return True
            for k in tree:
                if isinstance(tree[k], dict) and _plant(tree[k]):
                    return True
        return False

    def _plant_leaf(d, k):
        leaf = d[k]
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f" and arr.size:
            arr = np.array(arr)  # writable copy
            arr.flat[0] = value
            d[k] = arr
            return True
        return False

    assert isinstance(poisoned, dict), "poison_batch expects a dict batch"
    assert _plant(poisoned), \
        "poison_batch: batch has no float leaf to plant a NaN in " \
        "(token-only batches: poison the loss/labels path instead)"
    return poisoned


class NaNAtStep:
    """Stateful wrapper around a batch source: yields clean batches except at
    the chosen global steps, where the batch is poisoned. Drives "inject NaN
    gradients at step k" scenarios without touching compiled code."""

    def __init__(self, make_batch, nan_steps):
        self.make_batch = make_batch
        self.nan_steps = set(int(s) for s in nan_steps)
        self.calls = 0

    def __call__(self):
        batch = self.make_batch()
        if self.calls in self.nan_steps:
            batch = poison_batch(batch)
        self.calls += 1
        return batch
