"""Shared tiny serving-engine factory for the multi-process fabric.

The kill -9 soak, the fabric bench lane, `bin/dstpu_pool`'s demo config and
the in-thread transport tests all need the SAME engine on both sides of a
process boundary: parameters are seeded (`seed`), so a replica subprocess
built from this factory is bit-identical to the parent's oracle engine —
greedy decoding then makes token parity a hard equality, not a tolerance.

This lives in `deepspeed_tpu.testing` (shipped with the package, like
`chaos.py`) because the replica-server child resolves the factory by
import path: ``--factory deepspeed_tpu.testing.fabric:tiny_serving_engine``.
"""

from typing import Any, Dict

TINY_DEFAULTS: Dict[str, Any] = dict(
    n_layer=2, n_head=4, d_model=64, max_seq_len=256, vocab_size=256)
BS = 16   # kv_block_size == prefill_chunk, the test_router convention


def tiny_serving_engine(seed: int = 0, max_slots: int = 2,
                        max_context: int = 96, telemetry=False,
                        **model_overrides):
    """A fresh `ServingEngine` over a tiny seeded fp32 GPT on a 1-chip
    mesh. Every kwarg is JSON-safe, so the whole recipe ships through
    `dstpu_replica --kwargs`.

    `telemetry` is either a bool (True = bare enabled registry) or a full
    telemetry config dict — the pod-observability tests pass
    ``{"enabled": True, "tracing": True, "output_path": <per-replica dir>}``
    so each subprocess replica records (and spools) into its OWN dir."""
    import jax.numpy as jnp

    from deepspeed_tpu.comm import mesh as mesh_mod
    from deepspeed_tpu.config.core import MeshConfig
    from deepspeed_tpu.inference.engine import init_inference
    from deepspeed_tpu.models.gpt import GPTConfig, make_gpt_decode_model

    mk = dict(TINY_DEFAULTS)
    mk.update(model_overrides)
    cfg = GPTConfig(dtype=jnp.float32, remat=False, **mk)
    if mesh_mod._CURRENT_MESH is None:
        mesh_mod.init_mesh(MeshConfig(data=1, tensor=1, sequence=1,
                                      expert=1, pipe=1))
    spec = make_gpt_decode_model(cfg=cfg, name="fabric-tiny", seed=seed)
    inf_cfg: Dict[str, Any] = {
        "dtype": "float32", "kv_cache_dtype": "float32", "greedy": True,
        "kv_block_size": BS, "max_out_tokens": 64}
    if isinstance(telemetry, dict):
        inf_cfg["telemetry"] = dict(telemetry)
    elif telemetry:
        inf_cfg["telemetry"] = {"enabled": True}
    engine = init_inference(model=spec, config=inf_cfg)
    return engine.serving(max_slots=max_slots, max_context=max_context,
                          prefill_chunk=BS, enable_prefix_caching=True)


def tiny_oracle(prompts, news, seed: int = 0, **model_overrides):
    """Single-engine greedy reference completions for `prompts` — the
    parity baseline every fabric test compares the pool against."""
    import numpy as np

    serving = tiny_serving_engine(seed=seed, **model_overrides)
    refs = [serving.engine.generate(np.asarray(p)[None], max_new_tokens=n,
                                    stop_on_eos=False)[0]
            for p, n in zip(prompts, news)]
    return refs
