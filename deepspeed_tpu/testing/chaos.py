"""Chaos harness for the self-healing serving pool.

The self-healing layer (PoolAuditor, the hung-replica watchdog, hard
deadlines + hedged dispatch, the degradation ladder) defends against
failures that never raise: replicas that hang instead of crash, host-side
pool bookkeeping that drifts one refcount at a time, steps that silently
slow down. None of those appear in a normal test run, so this module
manufactures them — deterministically, so a soak failure replays
bit-for-bit from its seed:

  * `ChaosClock` — a manually-driven monotonic clock injected into the
    router (which fans it out to every replica via `set_clock`), so step
    delays, deadlines, TTLs, watchdog strikes and hedge timers are all
    driven by the schedule, not by wall time;
  * `corrupt_pool(engine, kind, rng)` — reach into a live engine's
    allocator / prefix-cache bookkeeping and break ONE invariant the
    auditor checks (leak, refcount drift, double-reference, free-list
    duplicate, stale hash entry);
  * `ChaosReplica` — a transparent `ReplicaHandle` wrapper whose `step()`
    fires a `ChaosSchedule` of injections keyed by step count: clock
    delays (slow steps the watchdog must tolerate), hangs (no progress +
    failing health probe — the watchdog must quarantine), crashes
    (exception out of step() — the PR 6 failover path), and pool
    corruptions (the scheduled audit must catch + repair);
  * `ChaosSchedule.seeded(...)` — a reproducible random schedule over
    those event kinds for the soak test.

Corruption kinds are split into SAFE and UNSAFE sets. Safe kinds (leak,
refcount over-count, stale hash) degrade capacity or bookkeeping but can
never make the engine emit wrong tokens before the next scheduled audit
repairs them — they are what the soak test injects while asserting greedy
parity. Unsafe kinds (refcount under-count, double-reference, free-list
duplicate) can hand one physical block to two writers if the engine keeps
admitting before an audit runs; unit tests inject them quiesced, audit,
and assert the repair — exactly the offline forensics workflow
`bin/dstpu_audit` supports.
"""

import dataclasses
import os
import signal
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.serving.replica import ReplicaHandle

__all__ = ["ChaosClock", "ChaosInjected", "ChaosReplica", "ChaosSchedule",
           "CORRUPTION_KINDS", "SAFE_CORRUPTIONS", "corrupt_pool",
           "kill_replica_process"]


def kill_replica_process(handle, sig: int = signal.SIGKILL) -> int:
    """The PROCESS-level chaos fault: deliver `sig` (default `kill -9`) to
    a `RemoteReplica`'s OS process and return the pid. This is the real
    thing the in-process `crash` event simulates — the multi-process soak
    uses it to prove the heartbeat/quarantine/respawn path against an
    actual dead process. SIGSTOP makes a hung-not-dead replica (heartbeats
    stop, process survives) — the detection-latency arm of the fabric
    bench."""
    proc = getattr(handle, "process", None)
    if proc is None or proc.pid is None:
        raise ValueError(f"handle {handle!r} has no OS process to kill")
    os.kill(proc.pid, sig)
    return proc.pid


class ChaosInjected(RuntimeError):
    """The simulated replica crash raised out of `ChaosReplica.step()`."""


class ChaosClock:
    """Deterministic injectable monotonic clock.

    `now` only moves when the harness moves it: `advance(dt)` explicitly,
    or `tick` seconds automatically per reading (so code that measures a
    duration by calling the clock twice sees time pass). Inject one
    instance into `ServingRouter(clock=...)` and the router propagates it
    to every replica — TTL, deadlines, TTFT/TPOT stamps, watchdog and
    hedge timers then share this single schedule-driven time source.
    """

    def __init__(self, start: float = 0.0, tick: float = 0.0):
        self.now = float(start)
        self.tick = float(tick)

    def __call__(self) -> float:
        t = self.now
        self.now += self.tick
        return t

    def advance(self, dt: float) -> float:
        self.now += float(dt)
        return self.now


# ----------------------------------------------------------------------
# pool corruption — break exactly one audited invariant
# ----------------------------------------------------------------------

# kinds that cannot produce wrong tokens before the next audit repairs them
SAFE_CORRUPTIONS = ("leak", "refcount_over", "stale_hash")
# kinds that can alias one block to two writers if admission keeps running
UNSAFE_CORRUPTIONS = ("refcount_under", "double_ref", "free_dup")
CORRUPTION_KINDS = SAFE_CORRUPTIONS + UNSAFE_CORRUPTIONS


def corrupt_pool(engine, kind: str, rng: np.random.Generator
                 ) -> Optional[Dict[str, Any]]:
    """Inject one bookkeeping corruption into a live `ServingEngine`'s
    pool. Returns a description of what was broken (for assertions), or
    None when the pool has no state the kind applies to right now (e.g.
    no refcounted blocks yet) — the caller treats that as a no-op.

      leak            drop a block from the free list (and shadow set):
                      it is now neither free nor tracked (audit I5)
      refcount_over   +1 a live block's refcount: a retire will leave it
                      pinned forever (audit I2)
      refcount_under  -1 a shared block's refcount: its KV can be freed
                      under a live reader (audit I2)
      double_ref      push a slot-referenced block onto the free list:
                      the next alloc hands it to a second writer (audit I1)
      free_dup        duplicate a free-list entry (list only, not the
                      shadow set): one block, two future owners (audit I1
                      structure + shadow-set drift)
      stale_hash      register a fabricated hash -> block entry with no
                      reverse mapping (audit I3)
    """
    alloc = engine.allocator
    if kind == "leak":
        if not alloc._free:
            return None
        b = alloc._free.pop(int(rng.integers(len(alloc._free))))
        alloc._free_set.discard(b)
        return {"kind": kind, "block": b}
    if kind in ("refcount_over", "refcount_under"):
        live = sorted(b for b, c in alloc._refs.items() if c >= 1)
        if not live:
            return None
        b = live[int(rng.integers(len(live)))]
        alloc._refs[b] += 1 if kind == "refcount_over" else -1
        return {"kind": kind, "block": b}
    if kind == "double_ref":
        live = sorted(b for b, c in alloc._refs.items() if c >= 1)
        if not live:
            return None
        b = live[int(rng.integers(len(live)))]
        alloc._free.append(b)
        alloc._free_set.add(b)
        return {"kind": kind, "block": b}
    if kind == "free_dup":
        if not alloc._free:
            return None
        b = alloc._free[int(rng.integers(len(alloc._free)))]
        alloc._free.append(b)
        return {"kind": kind, "block": b}
    if kind == "stale_hash":
        if engine.prefix_cache is None:
            return None
        # a fabricated digest that can never match a real chained hash —
        # deterministic from the rng, no os.urandom
        fake = bytes(rng.integers(0, 256, (32,), dtype=np.uint8))
        b = int(rng.integers(1, alloc.num_blocks))
        engine.prefix_cache._by_hash[fake] = b
        return {"kind": kind, "block": b, "hash": fake.hex()}
    raise ValueError(f"unknown corruption kind {kind!r} "
                     f"(expected one of {CORRUPTION_KINDS})")


# ----------------------------------------------------------------------
# the schedule + the wrapper replica
# ----------------------------------------------------------------------


@dataclasses.dataclass
class ChaosEvent:
    step: int        # ChaosReplica step() count at which the event fires
    kind: str        # "delay" | "hang" | "crash" | "corrupt"
    arg: Any = None  # delay: seconds · corrupt: corruption kind ·
                     # hang: seconds the fake stuck step appears to take


class ChaosSchedule:
    """An ordered set of `ChaosEvent`s for ONE replica, keyed by that
    replica's step count. Build explicitly for unit tests, or with
    `seeded()` for the soak — either way the schedule is a plain list the
    failing run prints, so any soak failure is replayable."""

    def __init__(self, events: Sequence[ChaosEvent] = ()):
        self.events: Dict[int, List[ChaosEvent]] = {}
        for ev in events:
            self.events.setdefault(int(ev.step), []).append(ev)

    @classmethod
    def seeded(cls, seed: int, steps: int, delay_rate: float = 0.0,
               delay_s: float = 0.0, corrupt_rate: float = 0.0,
               corruptions: Sequence[str] = SAFE_CORRUPTIONS,
               crash_at: Sequence[int] = (), hang_at: Optional[int] = None,
               hang_s: float = 0.0) -> "ChaosSchedule":
        """Deterministic random schedule over `steps` replica steps:
        per-step Bernoulli delays and corruptions (kinds drawn from
        `corruptions`), plus explicit crash steps and at most one hang."""
        rng = np.random.default_rng(seed)
        events: List[ChaosEvent] = []
        for s in range(steps):
            if delay_rate and rng.random() < delay_rate:
                events.append(ChaosEvent(s, "delay", delay_s))
            if corrupt_rate and rng.random() < corrupt_rate:
                kind = corruptions[int(rng.integers(len(corruptions)))]
                events.append(ChaosEvent(s, "corrupt", kind))
        events.extend(ChaosEvent(int(s), "crash") for s in crash_at)
        if hang_at is not None:
            events.append(ChaosEvent(int(hang_at), "hang", hang_s))
        return cls(events)

    def at(self, step: int) -> List[ChaosEvent]:
        return self.events.get(step, [])

    def __repr__(self):
        flat = [ev for evs in sorted(self.events.items())
                for ev in evs[1]]
        return f"ChaosSchedule({flat!r})"


class ChaosReplica(ReplicaHandle):
    """Transparent `ReplicaHandle` wrapper that fires a `ChaosSchedule`.

    Every protocol verb forwards to the wrapped handle (an
    `InProcessReplica`, normally), so the router cannot tell the
    difference — which is the point: every recovery path is exercised
    through the exact interfaces production uses.

    Event semantics, applied at the step count where they fire:

      delay    advance the injected clock by `arg` seconds BEFORE the real
               step runs — the router's watchdog sees one slow step() that
               still made progress (a strike that must NOT kill a replica
               whose health probe answers);
      hang     permanent until `restart()`: step() advances the clock by
               `arg` and returns NO completions, the health probe answers
               False — the watchdog must converge this onto the
               quarantine/reroute path a crash takes;
      crash    raise `ChaosInjected` out of step() — the PR 6 failover
               path, for calibrating that hangs and crashes land in the
               same place;
      corrupt  run `corrupt_pool(engine, arg, rng)` AFTER the real step
               returns, so the injected damage sits in the bookkeeping
               until the engine's own scheduled audit catches it.

    The corruption rng is seeded per-replica (`seed`), so block choices
    inside events replay too.
    """

    def __init__(self, inner, schedule: ChaosSchedule,
                 clock: Optional[ChaosClock] = None, seed: int = 0):
        self._inner = inner
        self._schedule = schedule
        self._clock = clock
        self._rng = np.random.default_rng(seed)
        self._steps = 0
        self._hung = False
        self._hang_s = 0.0
        self.injected: List[Tuple[int, str, Any]] = []   # fired-event log
        self.replica_id = inner.replica_id
        self.role = inner.role

    # -- chaos-bearing surface -----------------------------------------

    def step(self):
        step = self._steps
        self._steps += 1
        if self._hung:
            # a hung backend: time passes, nothing returns
            if self._clock is not None and self._hang_s:
                self._clock.advance(self._hang_s)
            return []
        fired = self._schedule.at(step)
        for ev in fired:
            if ev.kind == "delay" and self._clock is not None:
                self._clock.advance(float(ev.arg or 0.0))
            elif ev.kind == "hang":
                self._hung = True
                self._hang_s = float(ev.arg or 0.0)
                self.injected.append((step, "hang", ev.arg))
                if self._clock is not None and self._hang_s:
                    self._clock.advance(self._hang_s)
                return []
            elif ev.kind == "crash":
                self.injected.append((step, "crash", None))
                raise ChaosInjected(
                    f"replica {self.replica_id}: injected crash at "
                    f"step {step}")
        out = self._inner.step()
        for ev in fired:
            if ev.kind == "delay":
                self.injected.append((step, "delay", ev.arg))
            elif ev.kind == "corrupt":
                done = corrupt_pool(self._inner.engine, str(ev.arg),
                                    self._rng)
                if done is not None:
                    self.injected.append((step, "corrupt", done))
        return out

    def health_probe(self):
        if self._hung:
            return False
        return self._inner.health_probe()

    def restart(self):
        self._inner.restart()
        self._hung = False
        self._hang_s = 0.0

    # -- everything else is the wrapped replica (the base class defines
    # the protocol with raising stubs, so each verb forwards explicitly;
    # __getattr__ backstops non-protocol attrs like `.engine`) ----------

    def submit(self, request, prefill_only=False, hashes=None, trace=None,
               deadline_at=None):
        self._inner.submit(request, prefill_only=prefill_only, hashes=hashes,
                           trace=trace, deadline_at=deadline_at)

    def attach_observability(self, tracer=None, flightrec=None, tid=None):
        self._inner.attach_observability(tracer=tracer, flightrec=flightrec,
                                         tid=tid)

    def set_clock(self, clock):
        self._inner.set_clock(clock)

    def cancel(self, uid, queued_only=False):
        return self._inner.cancel(uid, queued_only=queued_only)

    def drain_queued(self):
        return self._inner.drain_queued()

    def check_admissible(self, prompt_len, max_new, prefill_only=False,
                         uid="?", padded_prompt=None):
        return self._inner.check_admissible(prompt_len, max_new,
                                            prefill_only=prefill_only,
                                            uid=uid,
                                            padded_prompt=padded_prompt)

    def progress(self):
        return self._inner.progress()

    @property
    def prefill_chunk(self):
        return self._inner.prefill_chunk

    def affinity(self, hashes):
        return self._inner.affinity(hashes)

    def hash_chain(self, prompt):
        return self._inner.hash_chain(prompt)

    @property
    def queue_depth(self):
        return self._inner.queue_depth

    @property
    def num_active(self):
        return self._inner.num_active

    @property
    def available_blocks(self):
        return self._inner.available_blocks

    @property
    def has_free_slot(self):
        return self._inner.has_free_slot

    def handoff_ready(self):
        return self._inner.handoff_ready()

    def export_handoff(self, uid):
        return self._inner.export_handoff(uid)

    def receive_handoff(self, state, src_pool):
        return self._inner.receive_handoff(state, src_pool)

    def release_handoff(self, uid):
        return self._inner.release_handoff(uid)

    @property
    def can_restart(self):
        return self._inner.can_restart

    def has_output(self, uid):
        return self._inner.has_output(uid)

    def audit(self, repair=False):
        return self._inner.audit(repair=repair)

    def audit_state(self):
        return self._inner.audit_state()

    def stats(self):
        return self._inner.stats()

    def compile_stats(self):
        return self._inner.compile_stats()

    # base-class DEFAULTS (not raising stubs) — these must forward
    # explicitly too, or Python resolves them on ReplicaHandle and the
    # wrapped replica's real answer never surfaces

    def memory_snapshot(self):
        return self._inner.memory_snapshot()

    def compat_descriptor(self):
        return self._inner.compat_descriptor()

    def close(self):
        return self._inner.close()

    def __getattr__(self, name):
        return getattr(self._inner, name)
