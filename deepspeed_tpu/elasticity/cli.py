"""`ds_elastic` CLI — inspect an elastic config and its admissible world sizes.

Behavioral analog of the reference's `bin/ds_elastic` (argparse over a config
json, prints elasticity block + computed final batch / valid device counts /
micro-batch for an intended world size).
"""

import argparse
import json

import deepspeed_tpu
from deepspeed_tpu.elasticity.elasticity import compute_elastic_config


def main(argv=None):
    parser = argparse.ArgumentParser(description="deepspeed-tpu elasticity inspector")
    parser.add_argument("-c", "--config", type=str, required=True,
                        help="deepspeed-tpu config json")
    parser.add_argument("-w", "--world-size", type=int, default=0,
                        help="intended/current world size (device count)")
    args = parser.parse_args(argv)

    with open(args.config) as f:
        ds_config = json.load(f)

    print("-" * 42)
    print("Elasticity config:")
    print("-" * 42)
    print(json.dumps(ds_config.get("elasticity", {}), indent=4, sort_keys=True))

    if args.world_size > 0:
        final_batch_size, valid_gpus, micro_batch_size = compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version=deepspeed_tpu.__version__,
            world_size=args.world_size, return_microbatch=True)
        print("-" * 42)
        print(f"Calculated results for world size {args.world_size}:")
        print("-" * 42)
        print(f"final_batch_size .... {final_batch_size}")
        print(f"valid_device_counts . {valid_gpus}")
        print(f"micro_batch_size .... {micro_batch_size}")
    else:
        final_batch_size, valid_gpus = compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version=deepspeed_tpu.__version__)
        print("-" * 42)
        print("Calculated results:")
        print("-" * 42)
        print(f"final_batch_size .... {final_batch_size}")
        print(f"valid_device_counts . {valid_gpus}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
