"""Elastic agent — restart-on-membership-change supervision with a
restart-cause taxonomy, per-cause budgets, backoff, and resume-tag
negotiation.

Reference: `elasticity/elastic_agent.py:28` (`DSElasticAgent`, a torch-elastic
agent subclass that restarts worker groups when the rendezvous membership
changes, injecting DeepSpeed env).

TPU analog: there is no torch-elastic; recovery is supervised restart. The agent
runs a training callable (or subprocess) in a loop; when it exits with a
membership-change/failure condition, the agent re-reads the resource view,
validates the new world size against the elastic config
(`compute_elastic_config`, elasticity.py), negotiates the resume tag (newest
COMMITTED checkpoint — a mid-save crash leaves `latest` at the previous good
tag, see checkpoint/saver.py), and restarts — orbax restores the reshardable
checkpoint onto whatever mesh now exists.

Restart causes are classified so budgets can differ: a flapping pod slice
(membership) deserves more patience than a training loop that keeps producing
NaNs (bad_state) — the latter restarting forever would burn the pod on a bug.
"""

import inspect
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from deepspeed_tpu.elasticity.elasticity import (compute_elastic_config,
                                                 ElasticityIncompatibleWorldSize)
from deepspeed_tpu.elasticity.restart_policy import RestartBudget, RestartPolicy
from deepspeed_tpu.runtime.sentinel import BadStateError
from deepspeed_tpu.utils.logging import logger


class MembershipChanged(Exception):
    """Raised by a worker (or watcher) when the device/host membership changed."""


class RestartCause:
    """Why the previous attempt ended — the agent's restart taxonomy."""
    MEMBERSHIP = "membership_change"
    BAD_STATE = "bad_state"
    CRASH = "crash"
    INADMISSIBLE = "inadmissible_world"
    ALL = (MEMBERSHIP, BAD_STATE, CRASH, INADMISSIBLE)


def classify_failure(exc) -> str:
    if isinstance(exc, MembershipChanged):
        return RestartCause.MEMBERSHIP
    if isinstance(exc, BadStateError):
        return RestartCause.BAD_STATE
    return RestartCause.CRASH


@dataclass
class AgentSpec:
    """What the agent supervises.

    `run_fn(world_size, micro_batch[, resume_tag])` — the training entry; must
    resume from the negotiated checkpoint tag itself (engine.load_checkpoint).
    The third parameter is optional: the agent passes the negotiated tag only
    when the callable accepts it.
    `world_size_fn()` — current resource view (e.g. len of reachable hosts ×
    chips/host); re-queried before every (re)start.
    `checkpoint_dir` — checkpoint root for resume-tag negotiation (None: the
    run_fn manages resume on its own).
    `max_restarts` — global budget; `max_restarts_per_cause` overrides per
    RestartCause key (unlisted causes fall back to the global budget).
    Backoff between restarts is exponential (`restart_backoff_s` base,
    `backoff_factor` growth, capped at `max_backoff_s`) with proportional
    jitter so a pod of agents doesn't stampede the scheduler in lockstep.
    """
    run_fn: Callable
    world_size_fn: Callable[[], int]
    ds_config: dict
    max_restarts: int = 100
    restart_backoff_s: float = 5.0
    backoff_factor: float = 2.0
    max_backoff_s: float = 300.0
    backoff_jitter: float = 0.1
    max_restarts_per_cause: Dict[str, int] = field(default_factory=dict)
    checkpoint_dir: Optional[str] = None
    monitor: Any = None
    on_restart: Optional[Callable[[int], None]] = None


class ElasticAgent:
    """Supervises one training job with elastic world-size revalidation."""

    def __init__(self, spec: AgentSpec):
        self.spec = spec
        # budget/backoff live in the shared RestartBudget (restart_policy.py);
        # the agent keeps its historical surface (`restarts`,
        # `restart_causes`, `last_cause`) as views onto it
        self.budget = RestartBudget(RestartPolicy(
            max_restarts=spec.max_restarts,
            base_backoff_s=spec.restart_backoff_s,
            backoff_factor=spec.backoff_factor,
            max_backoff_s=spec.max_backoff_s,
            jitter=spec.backoff_jitter,
            per_cause=dict(spec.max_restarts_per_cause)))
        self.budget.causes.update({c: 0 for c in RestartCause.ALL})
        self.last_resume_tag: Optional[str] = None
        self._run_fn_takes_tag = self._accepts_resume_tag(spec.run_fn)

    @property
    def restarts(self) -> int:
        return self.budget.restarts

    @restarts.setter
    def restarts(self, n: int):
        self.budget.restarts = n

    @property
    def restart_causes(self) -> Dict[str, int]:
        return self.budget.causes

    @property
    def last_cause(self) -> Optional[str]:
        return self.budget.last_cause

    @staticmethod
    def _accepts_resume_tag(fn):
        try:
            params = list(inspect.signature(fn).parameters.values())
        except (TypeError, ValueError):
            return False
        if any(p.kind == inspect.Parameter.VAR_POSITIONAL for p in params):
            return True
        positional = [p for p in params if p.kind in
                      (inspect.Parameter.POSITIONAL_ONLY,
                       inspect.Parameter.POSITIONAL_OR_KEYWORD)]
        return len(positional) >= 3

    def _admissible(self, world_size):
        """(final_batch, micro_batch) for this world size, or raises."""
        final_batch, _valid, micro = compute_elastic_config(
            self.spec.ds_config, world_size=world_size, return_microbatch=True)
        return final_batch, micro

    def _negotiate_resume_tag(self):
        """Newest committed (manifest-carrying) tag in the checkpoint root —
        NOT simply the `latest` pointer, which a crash may have left stale or
        missing. Validation of content happens at load; this picks the tag
        every restarting worker will agree on."""
        if self.spec.checkpoint_dir is None:
            return None
        try:
            from deepspeed_tpu.checkpoint.saver import get_latest_tag
            tag = get_latest_tag(self.spec.checkpoint_dir)
        except Exception as e:
            logger.warning(f"elastic agent: resume-tag negotiation failed "
                           f"({e}); run_fn must resolve resume itself")
            return None
        self.last_resume_tag = tag
        return tag

    def _backoff_delay(self):
        return self.budget.next_delay()

    def _consume_restart(self, cause):
        ok = self.budget.consume(cause)
        self._emit_restart_events()
        if not ok:
            cap = self.spec.max_restarts_per_cause.get(cause)
            if cap is not None and self.restart_causes[cause] > cap:
                logger.error(f"elastic agent: restart budget for cause "
                             f"'{cause}' exhausted ({cap})")
            else:
                logger.error("elastic agent: global restart budget exhausted")
        return ok

    def _emit_restart_events(self):
        from deepspeed_tpu.monitor.monitor import write_recovery_events
        events = [("Recovery/restarts_total", float(self.restarts), self.restarts)]
        events += [(f"Recovery/restarts/{c}", float(n), self.restarts)
                   for c, n in self.restart_causes.items() if n]
        write_recovery_events(self.spec.monitor, events)

    def _pause_then_continue(self, cause):
        """Account the restart against its cause's budget; back off. Returns
        False when budgets are exhausted (the run loop then gives up)."""
        if not self._consume_restart(cause):
            return False
        if self.spec.on_restart is not None:
            self.spec.on_restart(self.restarts)
        delay = self._backoff_delay()
        if delay > 0:
            logger.info(f"elastic agent: backing off {delay:.1f}s before "
                        f"restart #{self.restarts} (cause: {cause})")
        time.sleep(delay)
        return True

    def run(self):
        """Run until clean exit or restart budget exhausted. Returns True on
        clean completion."""
        while True:
            world = self.spec.world_size_fn()
            try:
                final_batch, micro = self._admissible(world)
            except ElasticityIncompatibleWorldSize:
                # wait for the resource view to move into the valid set
                logger.warning(f"elastic agent: world size {world} inadmissible; "
                               "waiting for an admissible resource view")
                if not self._pause_then_continue(RestartCause.INADMISSIBLE):
                    return False
                continue

            resume_tag = self._negotiate_resume_tag()
            logger.info(f"elastic agent: starting run | world={world} "
                        f"batch={final_batch} micro={micro} "
                        f"resume_tag={resume_tag} restart #{self.restarts}")
            try:
                if self._run_fn_takes_tag:
                    self.spec.run_fn(world, micro, resume_tag)
                else:
                    self.spec.run_fn(world, micro)
                return True
            except Exception as e:
                cause = classify_failure(e)
                logger.warning(f"elastic agent: worker ended ({e!r}); "
                               f"cause={cause}; restarting from checkpoint")
            if not self._pause_then_continue(cause):
                return False
