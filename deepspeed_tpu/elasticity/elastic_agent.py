"""Elastic agent — restart-on-membership-change supervision.

Reference: `elasticity/elastic_agent.py:28` (`DSElasticAgent`, a torch-elastic
agent subclass that restarts worker groups when the rendezvous membership
changes, injecting DeepSpeed env).

TPU analog: there is no torch-elastic; recovery is supervised restart. The agent
runs a training callable (or subprocess) in a loop; when it exits with a
membership-change/failure condition, the agent re-reads the resource view,
validates the new world size against the elastic config
(`compute_elastic_config`, elasticity.py), and restarts — resume comes from the
latest (reshardable) checkpoint, which orbax restores onto whatever mesh now
exists.
"""

import time
from dataclasses import dataclass
from typing import Callable, Optional

from deepspeed_tpu.elasticity.elasticity import (compute_elastic_config,
                                                 ElasticityIncompatibleWorldSize)
from deepspeed_tpu.utils.logging import logger


class MembershipChanged(Exception):
    """Raised by a worker (or watcher) when the device/host membership changed."""


@dataclass
class AgentSpec:
    """What the agent supervises.

    `run_fn(world_size, micro_batch)` — the training entry; must resume from the
    latest checkpoint itself (engine.load_checkpoint).
    `world_size_fn()` — current resource view (e.g. len of reachable hosts ×
    chips/host); re-queried before every (re)start.
    """
    run_fn: Callable[[int, int], None]
    world_size_fn: Callable[[], int]
    ds_config: dict
    max_restarts: int = 100
    restart_backoff_s: float = 5.0
    on_restart: Optional[Callable[[int], None]] = None


class ElasticAgent:
    """Supervises one training job with elastic world-size revalidation."""

    def __init__(self, spec: AgentSpec):
        self.spec = spec
        self.restarts = 0

    def _admissible(self, world_size):
        """(final_batch, micro_batch) for this world size, or raises."""
        final_batch, _valid, micro = compute_elastic_config(
            self.spec.ds_config, world_size=world_size, return_microbatch=True)
        return final_batch, micro

    def run(self):
        """Run until clean exit or restart budget exhausted. Returns True on
        clean completion."""
        while True:
            world = self.spec.world_size_fn()
            try:
                final_batch, micro = self._admissible(world)
            except ElasticityIncompatibleWorldSize:
                # wait for the resource view to move into the valid set
                logger.warning(f"elastic agent: world size {world} inadmissible; "
                               f"waiting {self.spec.restart_backoff_s}s")
                if not self._consume_restart():
                    return False
                time.sleep(self.spec.restart_backoff_s)
                continue

            logger.info(f"elastic agent: starting run | world={world} "
                        f"batch={final_batch} micro={micro} "
                        f"restart #{self.restarts}")
            try:
                self.spec.run_fn(world, micro)
                return True
            except MembershipChanged as e:
                logger.warning(f"elastic agent: membership changed ({e}); restarting")
            except Exception as e:  # worker fault → restart from checkpoint
                logger.warning(f"elastic agent: worker failed ({e!r}); restarting")
            if not self._consume_restart():
                return False
            if self.spec.on_restart is not None:
                self.spec.on_restart(self.restarts)
            time.sleep(self.spec.restart_backoff_s)

    def _consume_restart(self):
        self.restarts += 1
        if self.restarts > self.spec.max_restarts:
            logger.error("elastic agent: restart budget exhausted")
            return False
        return True
