"""Elastic training — admissible world-size math + restart-based recovery.

Reference: `elasticity/elasticity.py:233` (`compute_elastic_config`: which chip
counts keep the global batch compatible with micro-batch × GAS divisibility) and
`elasticity/elastic_agent.py:28` (torch-elastic agent).

The batch-compatibility math is framework-agnostic and ported semantically.
The recovery mechanism on TPU is restart-based: pod-slice membership changes
restart the job, `init_distributed` re-forms the mesh, and resume comes from the
(reshardable) checkpoint — orbax restores to whatever new mesh exists, which is
what the reference needs the universal checkpoint for.
"""

from deepspeed_tpu.utils.logging import logger

LATEST_ELASTICITY_VERSION = 0.2


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


def get_valid_gpus(batch_size, micro_batches, min_valid_gpus, max_valid_gpus):
    """Chip counts g such that batch_size % (mb * g) == 0 for some micro-batch."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb != 0:
            continue
        max_gpus = batch_size // mb
        for g in range(1, max_gpus + 1):
            if batch_size % (mb * g) == 0 and min_valid_gpus <= g <= max_valid_gpus:
                valid.add(g)
    return sorted(valid)


def get_best_candidates(max_acceptable_batch_size, micro_batches, min_gpus, max_gpus,
                        prefer_larger):
    """Search batch sizes downward for the one admitting the most chip counts
    (reference `_get_compatible_gpus_v01`)."""
    base = min(micro_batches)
    best = (0, None, [])  # (n_valid, batch, gpus)
    for batch_size in range(max_acceptable_batch_size, base - 1, -1):
        if batch_size % base != 0:
            continue
        valid = get_valid_gpus(batch_size, micro_batches, min_gpus, max_gpus)
        if len(valid) > best[0] or (len(valid) == best[0] and prefer_larger
                                    and best[1] is not None and batch_size > best[1]):
            best = (len(valid), batch_size, valid)
        if best[0] and batch_size < max_acceptable_batch_size // 2:
            break
    return best[1], best[2]


def get_compatible_gpus(max_acceptable_batch_size, micro_batches, min_gpus=1,
                        max_gpus=10000, prefer_larger=True):
    final_batch, valid_gpus = get_best_candidates(
        max_acceptable_batch_size, micro_batches, min_gpus, max_gpus, prefer_larger)
    if final_batch is None:
        raise ElasticityError(
            f"no batch size <= {max_acceptable_batch_size} works with micro-batches "
            f"{micro_batches}")
    return final_batch, valid_gpus


def compute_elastic_config(ds_config, target_deepspeed_version=None, world_size=0,
                           return_microbatch=False):
    """Reference signature (`elasticity.py:233`): returns (final_batch_size,
    valid_gpus[, micro_batch]) and validates the actual world size."""
    if hasattr(ds_config, "elasticity"):
        e = ds_config.elasticity
        max_batch = e.max_train_batch_size
        micro_batches = list(e.micro_batch_sizes)
        min_gpus, max_gpus = e.min_gpus, e.max_gpus
        prefer_larger = e.prefer_larger_batch
        enabled = e.enabled
    else:
        e = ds_config.get("elasticity", {})
        max_batch = e.get("max_train_batch_size", 2000)
        micro_batches = e.get("micro_batch_sizes", [2, 4, 6])
        min_gpus, max_gpus = e.get("min_gpus", 1), e.get("max_gpus", 10000)
        prefer_larger = e.get("prefer_larger_batch", True)
        enabled = e.get("enabled", False)
    if not enabled:
        raise ElasticityConfigError("elasticity not enabled in config")

    final_batch_size, valid_gpus = get_compatible_gpus(
        max_batch, micro_batches, min_gpus, max_gpus, prefer_larger)

    if world_size > 0 and world_size not in valid_gpus:
        raise ElasticityIncompatibleWorldSize(
            f"world size {world_size} not in valid set {valid_gpus}")

    if return_microbatch:
        if world_size > 0:
            candidates = sorted((mb for mb in micro_batches
                                 if final_batch_size % (mb * world_size) == 0),
                                reverse=prefer_larger)
            if not candidates:
                raise ElasticityError("no compatible micro batch for world size")
            return final_batch_size, valid_gpus, candidates[0]
        return final_batch_size, valid_gpus, micro_batches[0]
    return final_batch_size, valid_gpus
