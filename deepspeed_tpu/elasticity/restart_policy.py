"""Reusable restart budget: exponential backoff + jitter + per-cause caps.

Extracted from `ElasticAgent` (PR 2) so every supervisor in the tree shares
ONE budget/backoff implementation instead of re-deriving it: the elastic
agent uses it to pace training-job restarts, and the serving router
(`deepspeed_tpu/serving/router.py`) uses a per-replica budget to decide
whether a quarantined engine replica gets rebuilt or stays dead. The two
callers have very different cadences (minutes vs scheduler steps) but the
same semantics: N restarts total, optionally fewer for specific causes, and
a growing-but-capped delay between attempts so a flapping resource doesn't
get hammered in a tight loop.

`RestartPolicy` is the immutable description; `RestartBudget` is the mutable
account. Splitting them keeps one policy shareable across many budgets (the
router hands the SAME policy to every replica's budget).
"""

import random
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional


@dataclass(frozen=True)
class RestartPolicy:
    """How many restarts are allowed and how long to wait between them.

    `max_restarts` is the global cap; `per_cause` overrides it for specific
    cause strings (unlisted causes fall back to the global cap). Backoff for
    restart #n is ``min(base_backoff_s * backoff_factor**(n-1),
    max_backoff_s)``, scaled by ``1 + jitter * U[0,1)`` so a fleet of
    supervisors doesn't stampede a shared scheduler in lockstep.
    """
    max_restarts: int = 100
    base_backoff_s: float = 5.0
    backoff_factor: float = 2.0
    max_backoff_s: float = 300.0
    jitter: float = 0.1
    per_cause: Dict[str, int] = field(default_factory=dict)


class RestartBudget:
    """Mutable restart account against a `RestartPolicy`.

    `consume(cause)` records one restart and returns False the moment any
    budget (per-cause or global) is exhausted — the caller then stops
    restarting. `next_delay()` is the backoff for the restart just consumed
    (it reads the CURRENT restart count, so call it after `consume`).
    """

    def __init__(self, policy: RestartPolicy,
                 rng: Optional[Callable[[], float]] = None):
        self.policy = policy
        self.restarts = 0
        self.causes: Dict[str, int] = {}
        self.last_cause: Optional[str] = None
        self._rng = rng if rng is not None else random.random

    def consume(self, cause: str) -> bool:
        """Account one restart against `cause`; False = budget exhausted."""
        self.restarts += 1
        self.last_cause = cause
        self.causes[cause] = self.causes.get(cause, 0) + 1
        cap = self.policy.per_cause.get(cause)
        if cap is not None and self.causes[cause] > cap:
            return False
        return self.restarts <= self.policy.max_restarts

    @property
    def exhausted(self) -> bool:
        """True once a consume() has failed (or would fail globally)."""
        if self.restarts > self.policy.max_restarts:
            return True
        return any(self.causes.get(c, 0) > cap
                   for c, cap in self.policy.per_cause.items())

    def next_delay(self) -> float:
        """Backoff (seconds) before the restart the budget just consumed:
        exponential in the restart count, capped, with proportional jitter.
        Monotone nondecreasing in `restarts` at jitter=0."""
        p = self.policy
        if p.base_backoff_s <= 0:
            return 0.0
        delay = min(p.base_backoff_s *
                    (p.backoff_factor ** max(self.restarts - 1, 0)),
                    p.max_backoff_s)
        return delay * (1.0 + p.jitter * self._rng())
