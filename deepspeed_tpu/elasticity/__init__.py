from deepspeed_tpu.elasticity.elasticity import (
    compute_elastic_config,
    get_compatible_gpus,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
)
from deepspeed_tpu.elasticity.elastic_agent import (
    ElasticAgent,
    AgentSpec,
    MembershipChanged,
)
from deepspeed_tpu.elasticity.restart_policy import (
    RestartBudget,
    RestartPolicy,
)
