"""Metrics core: named Counters, Gauges, and log-scale Histograms.

Stdlib-only by design — the registry updates on every scheduler step and every
train step, so a metric update must cost no more than a dict lookup plus a
bisect (no locks on the hot path, no numpy, no allocation). The reference
stack's analog is the MonitorMaster event stream plus the
SynchronizedWallClockTimer means; this layer adds what raw `(tag, value,
step)` scalars cannot express: distributions. p50/p99 TTFT under a mixed
trace is a property of a histogram, not of any single event.

Histograms use FIXED log-scale buckets (vLLM/Prometheus style): bucket edges
are precomputed at construction as `lo * 10^(i/buckets_per_decade)`, so an
observation is one bisect into a ~40-entry list. Quantiles interpolate
linearly inside the winning bucket and clamp to the exact observed min/max —
at 5 buckets per decade the relative error is bounded by the bucket ratio
(~58% worst case, far tighter in practice since min/max clamp the tails),
which is the standard latency-histogram trade: O(1) memory, mergeable,
monotone-correct percentiles.
"""

import bisect
import math
import threading

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "merge_snapshots"]


class Counter:
    """Monotonically increasing value (requests served, tokens generated)."""

    kind = "counter"
    __slots__ = ("name", "value")

    def __init__(self, name):
        self.name = name
        self.value = 0.0

    def inc(self, n=1.0):
        self.value += n

    def snapshot(self):
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-write-wins value (queue depth, free blocks, MFU)."""

    kind = "gauge"
    __slots__ = ("name", "value")

    def __init__(self, name):
        self.name = name
        self.value = 0.0

    def set(self, v):
        self.value = float(v)

    def snapshot(self):
        return {"type": "gauge", "value": self.value}


class Histogram:
    """Fixed-bucket log-scale histogram with p50/p90/p99/mean snapshots.

    Default edges cover 0.1 .. 1e6 (in whatever unit the caller observes —
    the serving layer uses milliseconds, so the range spans 100us noise to a
    ~17-minute outlier) at 5 buckets per decade. Pass explicit `bounds`
    (sorted upper edges) for deterministic golden-output tests or odd units.
    Values below the first edge land in bucket 0, values above the last in
    the overflow bucket; exact min/max/sum/count ride alongside so the mean
    is exact and quantiles clamp to the true observed range.
    """

    kind = "histogram"
    __slots__ = ("name", "bounds", "counts", "count", "sum", "min", "max")

    def __init__(self, name, lo=0.1, hi=1e6, buckets_per_decade=5,
                 bounds=None):
        self.name = name
        if bounds is not None:
            self.bounds = sorted(float(b) for b in bounds)
        else:
            n = int(math.ceil(math.log10(hi / lo) * buckets_per_decade))
            step = 1.0 / buckets_per_decade
            self.bounds = [lo * 10.0 ** (i * step) for i in range(n + 1)]
        self.counts = [0] * (len(self.bounds) + 1)   # +1 = overflow (+Inf)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v):
        v = float(v)
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def quantile(self, q):
        """Linear interpolation inside the winning bucket, clamped to the
        exact observed [min, max]."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        cum = 0
        lower = 0.0
        for i, c in enumerate(self.counts):
            if c and cum + c >= target:
                upper = self.bounds[i] if i < len(self.bounds) else self.max
                v = lower + (upper - lower) * (target - cum) / c
                return min(max(v, self.min), self.max)
            cum += c
            if i < len(self.bounds):
                lower = self.bounds[i]
        return self.max

    def cumulative_buckets(self):
        """[(upper_edge, cumulative_count), ...] ending with (inf, count) —
        the Prometheus `_bucket{le=...}` series."""
        out, cum = [], 0
        for edge, c in zip(self.bounds, self.counts):
            cum += c
            out.append((edge, cum))
        out.append((math.inf, self.count))
        return out

    def snapshot(self):
        empty = self.count == 0
        return {
            "type": "histogram",
            "count": self.count,
            "sum": self.sum,
            "mean": 0.0 if empty else self.sum / self.count,
            "min": 0.0 if empty else self.min,
            "max": 0.0 if empty else self.max,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
            # full bucket state: a snapshot is MERGEABLE (bucket-wise, and
            # exact because every process builds the same log-scale edges),
            # so pool-level percentiles come from merged buckets instead of
            # averaging per-replica p99s
            "bounds": list(self.bounds),
            "counts": list(self.counts),
        }

    @classmethod
    def from_state(cls, name, snap):
        """Rebuild a histogram from a `snapshot()` dict (the wire/JSONL
        form) so quantiles can be recomputed on the restored — or merged —
        bucket state."""
        h = cls(name, bounds=snap["bounds"])
        h.counts = [int(c) for c in snap["counts"]]
        h.count = int(snap["count"])
        h.sum = float(snap["sum"])
        if h.count:
            h.min = float(snap["min"])
            h.max = float(snap["max"])
        return h

    def merge(self, other):
        """Accumulate another histogram bucket-wise (exact: identical
        bounds required — the pool shares one bucket layout by
        construction)."""
        if self.bounds != other.bounds:
            raise ValueError(
                f"histogram {self.name!r}: cannot merge mismatched bucket "
                f"bounds ({len(self.bounds)} vs {len(other.bounds)} edges)")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        if other.min < self.min:
            self.min = other.min
        if other.max > self.max:
            self.max = other.max


class MetricsRegistry:
    """Named metrics with get-or-create access and deterministic snapshots.

    Creation takes a lock (checkpoint finalizer threads record events too);
    updates on an existing metric are lock-free — a torn float add is an
    acceptable failure mode for telemetry, a hot-path mutex is not.
    """

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get(self, name, cls, **kwargs):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name, **kwargs)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(f"metric {name!r} is a {m.kind}, not a "
                            f"{cls.kind}")
        return m

    def counter(self, name) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name, **kwargs) -> Histogram:
        """Get-or-create; bucket kwargs apply on first creation only."""
        return self._get(name, Histogram, **kwargs)

    def metrics(self):
        """(name, metric) pairs in name order — the one iteration order every
        exporter uses, so Prometheus/JSONL/bridge output is deterministic."""
        return [(n, self._metrics[n]) for n in sorted(self._metrics)]

    def snapshot(self):
        return {n: m.snapshot() for n, m in self.metrics()}

    def clear(self):
        with self._lock:
            self._metrics = {}


def merge_snapshots(per_source):
    """Merge per-source registry snapshots into one pool-level snapshot.

    `per_source` maps a source tag (replica id) to that source's
    `MetricsRegistry.snapshot()` dict. Merge semantics, per metric type:

      * **counters** sum across sources;
      * **gauges** are NEVER summed blindly — the merged entry keeps a
        per-source map (`"sources"`) as the authoritative record, with
        `"value"` set to the across-source sum for the common pool-additive
        gauges (queue depth, active slots); readers that need a different
        aggregation (max degradation rung, min headroom) take it from
        `"sources"`;
      * **histograms** merge bucket-wise via the full bucket state the
        snapshot carries — exact, because every process builds identical
        log-scale edges — and percentiles are recomputed from the merged
        buckets. The merged count equals the sum of per-source counts by
        construction.

    A type mismatch for one name across sources is a caller bug and raises;
    the output dict is itself a valid snapshot (merged histograms carry
    bounds/counts), so merges compose.
    """
    merged = {}
    hists = {}
    for src in sorted(per_source):
        for name, snap in per_source[src].items():
            kind = snap.get("type")
            cur = merged.get(name)
            if cur is not None and cur.get("type") != kind:
                raise ValueError(
                    f"metric {name!r}: type conflict across sources "
                    f"({cur.get('type')} vs {kind} from {src!r})")
            if kind == "counter":
                if cur is None:
                    merged[name] = {"type": "counter", "value": 0.0}
                merged[name]["value"] += snap["value"]
            elif kind == "gauge":
                if cur is None:
                    merged[name] = {"type": "gauge", "value": 0.0,
                                    "sources": {}}
                merged[name]["value"] += snap["value"]
                merged[name]["sources"][src] = snap["value"]
            elif kind == "histogram":
                h = hists.get(name)
                if h is None:
                    hists[name] = Histogram.from_state(name, snap)
                    merged[name] = {"type": "histogram"}  # placeholder
                else:
                    h.merge(Histogram.from_state(name, snap))
            else:
                raise ValueError(f"metric {name!r}: unknown snapshot type "
                                 f"{kind!r} from {src!r}")
    for name, h in hists.items():
        merged[name] = h.snapshot()
    return merged
