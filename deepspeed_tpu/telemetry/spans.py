"""Host-side spans: named timed regions layered on the nvtx shim.

A span does two things at once:

  * enters a `jax.profiler.TraceAnnotation` via `utils/nvtx.annotate` (a hard
    no-op when `jax.profiler` is unavailable), so the region shows up in a
    real xprof/TensorBoard trace when one is being captured;
  * optionally records `(name, start, duration)` into a `ChromeTraceSink`,
    so a scheduler-step timeline (admit / prefill chunk / decode window) can
    be opened in Perfetto WITHOUT a TPU profiler session — the host-side
    phases are exactly the ones a device trace cannot see.

The sink writes the Chrome trace event format as streamed JSON: an opening
`[` then one complete event object per line, comma-terminated. Perfetto and
chrome://tracing both accept the unterminated-array form, which is what
makes the sink append-only and crash-safe. Beyond the duration ("X") events
the sink also speaks the metadata ("M": `process_name`/`thread_name`, so
every replica of a serving pool gets its own NAMED Perfetto track) and flow
("s"/"f": the arrows that connect a request's spans across tracks when the
router re-routes or hands a slot off) subsets of the format — the request
tracer (`telemetry/tracing.py`) drives those.
"""

import json
import os
import threading
import time

from deepspeed_tpu.utils import nvtx

__all__ = ["Span", "ChromeTraceSink", "span"]


class ChromeTraceSink:
    """Streamed chrome-trace event log (open directly in Perfetto). One sink
    = one run = one file: the file is truncated at first write so a re-run
    into the same output path cannot interleave two runs' timelines (every
    event's `ts` is relative to THIS sink's construction). Within the run
    events append and flush one by one — the timeline is readable mid-run
    and survives a crash."""

    def __init__(self, path):
        self.path = str(path)
        self._f = None
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()

    def write(self, ev):
        """Append one raw chrome-trace event dict (already carrying its own
        `ts`/`dur` in trace microseconds). The structured-span tracer uses
        this directly so its events stay on ONE caller-owned clock domain;
        `add` below converts from this sink's perf_counter baseline."""
        with self._lock:
            if self._f is None:
                self._f = open(self.path, "w")
                self._f.write("[\n")
            self._f.write(json.dumps(ev) + ",\n")
            self._f.flush()     # crash-safe: the timeline is readable mid-run

    def add(self, name, start_s, dur_s, tid=0):
        """Record one complete event; timestamps are seconds on the
        `time.perf_counter` clock (converted to trace microseconds).
        `tid` picks the Perfetto track — per-replica tids keep a serving
        pool's timelines from collapsing onto one row."""
        self.write({"name": name, "ph": "X", "pid": os.getpid(), "tid": tid,
                    "ts": round((start_s - self._t0) * 1e6, 3),
                    "dur": round(dur_s * 1e6, 3)})

    def add_meta(self, kind, value, tid=0):
        """Metadata event: kind is "process_name" or "thread_name"; value
        labels this pid (or `tid`'s track) in the Perfetto UI."""
        self.write({"name": kind, "ph": "M", "pid": os.getpid(), "tid": tid,
                    "ts": 0, "args": {"name": str(value)}})

    def close(self):
        with self._lock:
            if self._f is not None:
                try:
                    self._f.flush()
                    self._f.close()
                finally:
                    self._f = None


class Span:
    """Context manager: nvtx annotation + optional chrome-trace event +
    optional histogram observation (duration in ms). `tid` selects the
    chrome-trace track (default 0 — single-engine timelines; the serving
    stack passes its replica tid so pool timelines stay separated)."""

    __slots__ = ("name", "sink", "histogram", "tid", "_t0", "_nvtx")

    def __init__(self, name, sink=None, histogram=None, tid=0):
        self.name = name
        self.sink = sink
        self.histogram = histogram
        self.tid = tid
        self._t0 = 0.0
        self._nvtx = None

    def __enter__(self):
        self._nvtx = nvtx.annotate(self.name)
        self._nvtx.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        self._nvtx.__exit__(exc_type, exc, tb)
        self._nvtx = None
        if self.sink is not None:
            self.sink.add(self.name, self._t0, dur, tid=self.tid)
        if self.histogram is not None:
            self.histogram.observe(dur * 1e3)
        return False


def span(name, sink=None, histogram=None, tid=0):
    """Open a named span (see `Span`); usable as `with span("admit"): ...`."""
    return Span(name, sink=sink, histogram=histogram, tid=tid)
