"""Unified telemetry: one registry, many sinks.

`Telemetry` is the object the instrumented subsystems hold: it owns a
`MetricsRegistry` (`registry.py`), the configured exporters
(`exporters.py` — Prometheus textfile, JSONL log, monitor bridge) and an
optional chrome-trace span sink (`spans.py`). Construction from a
`TelemetryConfig` (config/core.py) with `enabled=False` — the default — is a
complete no-op: no directory is created, no file is written, `span()`
returns a shared null context and every record method returns immediately,
so the serving scheduler and the train loop can instrument unconditionally.

Wiring (all opt-in via the `telemetry` config block):

  * ServingEngine (`inference/scheduler.py`): per-request
    `serving/ttft_ms` / `serving/tpot_ms` / `serving/queue_wait_ms` /
    `serving/e2e_ms` histograms, queue/slot/pool gauges, per-phase spans;
  * training Engine (`runtime/engine.py`): `train/step_time_ms` histogram,
    tokens/s + achieved-MFU gauges, device-memory watermarks;
  * checkpoint saver / recovery paths: their `(tag, value, step)` events
    route through `record_events`, turning save latency into a histogram.

Three per-request diagnostics ride on the same config block and the same
disabled-by-default contract:

  * `tracer` (`tracing.py`, `telemetry.tracing` flag) — request-scoped
    span trees (`<subsystem>.trace.jsonl` + a flow-linked chrome trace);
  * `flightrec` (`flight_recorder.py`, `telemetry.flight_recorder` flag)
    — bounded ring of scheduling events, dumped on failure;
  * `watchdog` (always armed while telemetry is enabled) — recompile
    detection over the persistent jitted serving programs.

`bin/dstpu_metrics` renders the JSONL log (`telemetry/cli.py`);
`bin/dstpu_trace` reconstructs request timelines (`telemetry/tracing.py`).
"""

import contextlib
import pathlib

from deepspeed_tpu.telemetry.registry import (Counter, Gauge, Histogram,
                                              MetricsRegistry,
                                              merge_snapshots)
from deepspeed_tpu.telemetry.exporters import (JsonlExporter, MonitorBridge,
                                               PrometheusFileExporter,
                                               prometheus_text)
from deepspeed_tpu.telemetry import spans
from deepspeed_tpu.telemetry.spans import ChromeTraceSink, Span
from deepspeed_tpu.telemetry.tracing import (NULL_TRACER, TraceContext,
                                             Tracer)
from deepspeed_tpu.telemetry.flight_recorder import (NULL_RECORDER,
                                                     CompileWatchdog,
                                                     FlightRecorder)
from deepspeed_tpu.telemetry.memscope import (MemoryPlan, PredictedOOMError,
                                              ServingMemScope, TrainMemScope,
                                              fmt_bytes, max_kv_blocks,
                                              plan_serving, plan_training,
                                              plan_training_from_infinity,
                                              tree_bytes)

__all__ = ["Telemetry", "MetricsRegistry", "Counter", "Gauge", "Histogram",
           "merge_snapshots",
           "PrometheusFileExporter", "JsonlExporter", "MonitorBridge",
           "prometheus_text", "ChromeTraceSink", "Span", "Tracer",
           "TraceContext", "FlightRecorder", "CompileWatchdog",
           "MemoryPlan", "PredictedOOMError", "ServingMemScope",
           "TrainMemScope", "plan_training", "plan_serving",
           "plan_training_from_infinity", "max_kv_blocks",
           "fmt_bytes", "tree_bytes"]

_NULL_SPAN = contextlib.nullcontext()


class Telemetry:
    """Registry + exporters behind enable flags. See module docstring."""

    def __init__(self, config=None, subsystem="metrics", monitor=None,
                 registry=None):
        self.config = config
        self.subsystem = subsystem
        self.enabled = bool(config is not None and
                            getattr(config, "enabled", False))
        self.registry = registry if registry is not None else MetricsRegistry()
        self._exporters = []
        self._trace = None
        self._closed = False
        self.tracer = NULL_TRACER
        self.flightrec = NULL_RECORDER
        self.watchdog = CompileWatchdog(self if self.enabled else None)
        if not self.enabled:
            return
        out = pathlib.Path(config.output_path or "telemetry")
        tracing = bool(getattr(config, "tracing", False))
        flight = bool(getattr(config, "flight_recorder", False))
        if config.prometheus or config.jsonl or config.chrome_trace \
                or tracing or flight:
            # registry-only configurations (all file sinks off — the bench
            # lanes) must not litter an empty directory
            out.mkdir(parents=True, exist_ok=True)
        if config.prometheus:
            self._exporters.append(
                PrometheusFileExporter(out / f"{subsystem}.prom"))
        if config.jsonl:
            self._exporters.append(JsonlExporter(out / f"{subsystem}.jsonl"))
        if config.monitor_bridge and monitor is not None and \
                getattr(monitor, "enabled", False):
            self._exporters.append(MonitorBridge(monitor))
        if config.chrome_trace or tracing:
            # one shared chrome sink: phase spans (span()) and request
            # traces (tracer) land on one Perfetto timeline
            self._trace = ChromeTraceSink(out / f"{subsystem}.trace.json")
        if tracing:
            self.tracer = Tracer(out / f"{subsystem}.trace.jsonl",
                                 chrome=self._trace)
        if flight:
            self.flightrec = FlightRecorder(
                out, subsystem=subsystem,
                capacity=int(getattr(config, "flight_recorder_events", 256)))
        self.watchdog.recorder = self.flightrec

    # ---- recording ---------------------------------------------------

    def observe(self, name, value):
        if self.enabled:
            self.registry.histogram(name).observe(value)

    def set_gauge(self, name, value):
        if self.enabled:
            self.registry.gauge(name).set(value)

    def inc(self, name, n=1.0):
        if self.enabled:
            self.registry.counter(name).inc(n)

    def record_events(self, event_list):
        """Route monitor-style `(tag, value, step)` events into the registry:
        `*_ms` / `*_seconds` tags become histogram observations (save latency
        as a DISTRIBUTION, not a point value), everything else a gauge."""
        if not self.enabled:
            return
        for tag, value, _step in event_list:
            if tag.endswith(("_ms", "_seconds")):
                self.registry.histogram(tag).observe(value)
            else:
                self.registry.gauge(tag).set(value)

    def span(self, name, tid=0):
        """Timed/annotated region; a shared null context when disabled.
        `tid` selects the chrome-trace track (per-replica tids keep a
        serving pool's phase timelines separated in Perfetto)."""
        if not self.enabled:
            return _NULL_SPAN
        return spans.span(name, sink=self._trace, tid=tid)

    # ---- export ------------------------------------------------------

    def maybe_export(self, step):
        """Export every `export_interval`-th step (cheap modulo when idle)."""
        if not self.enabled:
            return
        interval = max(1, int(getattr(self.config, "export_interval", 1)))
        if step % interval == 0:
            self.export(step)

    def export(self, step=None):
        if not self.enabled:
            return
        snap = self.registry.snapshot()
        for e in self._exporters:
            e.export(self.registry, step=step, snapshot=snap)

    def peak_flops(self):
        """Per-chip peak FLOPs/s: the config override (TFLOPs) when set,
        else the generation table in `profiling/flops_profiler.py`."""
        override = float(getattr(self.config, "peak_tflops", 0.0) or 0.0)
        if override > 0:
            return override * 1e12
        from deepspeed_tpu.profiling.flops_profiler import _peak_flops
        return _peak_flops()

    def close(self):
        if self._closed:
            return
        self._closed = True
        # final export so runs shorter than export_interval (and the tail of
        # longer ones) still land in the files; guarded — close() also runs
        # from __del__ during interpreter teardown
        try:
            if self.enabled and self.registry.metrics():
                self.export()
        except Exception:
            pass
        for e in self._exporters:
            try:
                e.close()
            except Exception:
                pass
        if self._trace is not None:
            try:
                self._trace.close()
            except Exception:
                pass
        try:
            self.tracer.close()
        except Exception:
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
