"""Failure flight recorder + compile watchdog: the serving black box.

When a replica dies in production the aggregate histograms say *that* it
died, not *what the scheduler was doing* in the seconds before. The
`FlightRecorder` is the aircraft-style answer: a bounded ring buffer of
recent structured scheduling events (admission decisions with their
scores, evictions, re-routes, rollbacks, quarantines, recompiles) that
costs one deque append per event while everything is healthy, and dumps to
disk — together with whatever state snapshot the caller hands it
(`engine.stats()`, `router.stats()`) — the moment something goes wrong: a
replica throws, the bad-state sentinel fires, or an operator sends the
dump signal.

The `CompileWatchdog` covers the silent killer of TPU serving latency:
unexpected recompiles. The serving engine's promise is ONE compile per
persistent program for its lifetime; a shape regression anywhere upstream
turns that into a multi-second stall per novel shape, invisible in mean
throughput until the p99 explodes. The watchdog wraps each jitted program,
watches its jit cache size across calls, and on any compile AFTER the
warmup compile records which program recompiled (and the observed wall
time of the compiling call) into the telemetry registry
(`telemetry/recompiles`, `telemetry/compile_ms`) and the flight recorder.

Both are disabled by default and free when disabled: the recorder's
`record` is one flag check, and `CompileWatchdog.wrap` returns the jitted
function UNWRAPPED, so the hot path is byte-identical to a build without
the watchdog.
"""

import collections
import json
import os
import time
from typing import Any, Dict, Optional

__all__ = ["FlightRecorder", "CompileWatchdog", "NULL_RECORDER"]


class FlightRecorder:
    """Bounded ring of structured events + dump-on-failure.

    `record(kind, **fields)` appends `{"seq", "t", "kind", **fields}`;
    `dump(reason, state=...)` writes the ring plus the state snapshot to
    `<out_dir>/<subsystem>.flightrec.<n>.json` and returns the path. The
    ring keeps only the last `capacity` events — post-mortems need the
    recent past, not the whole run — and survives any number of dumps
    (each dump gets a fresh numbered file; the ring keeps rolling)."""

    def __init__(self, out_dir=None, subsystem="serving", capacity=256,
                 enabled=True, clock=None):
        self.enabled = bool(enabled) and out_dir is not None
        self.out_dir = str(out_dir) if out_dir is not None else None
        self.subsystem = subsystem
        self._clock = clock if clock is not None else time.monotonic
        self._ring = collections.deque(maxlen=max(1, int(capacity)))
        self._seq = 0
        self._dumps = 0
        # event tap: the replica server's observability spool subscribes
        # here so flight events can ship over the wire to the router. None
        # (the default) costs one attribute read per record.
        self.on_record = None

    def record(self, kind, **fields):
        if not self.enabled:
            return
        self._seq += 1
        ev = {"seq": self._seq, "t": self._clock(), "kind": kind, **fields}
        self._ring.append(ev)
        cb = self.on_record
        if cb is not None:
            cb(ev)

    def events(self):
        return list(self._ring)

    def dump(self, reason, state=None) -> Optional[str]:
        """Write the black box: last-N events + a state snapshot. Returns
        the dump path (None when disabled). Never raises — the dump runs
        inside failure paths that must keep failing over."""
        if not self.enabled:
            return None
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            if self._dumps == 0:
                # resume numbering past dumps left by a previous process in
                # the same output dir — a restart after a crash is exactly
                # when the PREVIOUS black box must survive, not be clobbered
                prefix = f"{self.subsystem}.flightrec."
                for name in os.listdir(self.out_dir):
                    if name.startswith(prefix) and name.endswith(".json"):
                        try:
                            n = int(name[len(prefix):-len(".json")])
                        except ValueError:
                            continue
                        self._dumps = max(self._dumps, n + 1)
            path = os.path.join(
                self.out_dir,
                f"{self.subsystem}.flightrec.{self._dumps:03d}.json")
            self._dumps += 1
            with open(path, "w") as f:
                json.dump({"reason": str(reason), "time": time.time(),
                           "clock": self._clock(),
                           "events": self.events(),
                           "state": _jsonable(state)}, f, indent=1,
                          default=str)
            return path
        except Exception:
            return None

    def install_signal_handler(self, state_fn=None, signum=None):
        """Operator dump signal: SIGUSR2 (default) writes a dump with the
        current state snapshot without disturbing the process. Opt-in —
        never installed implicitly (libraries must not steal signals)."""
        if not self.enabled:
            return
        import signal

        signum = signal.SIGUSR2 if signum is None else signum

        def _handler(_sig, _frame):
            self.dump("dump signal",
                      state=state_fn() if state_fn is not None else None)

        signal.signal(signum, _handler)


def _jsonable(obj):
    """Best-effort JSON coercion for state snapshots (stats() dicts carry
    numpy scalars); anything stubborn stringifies via `default=str`."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if hasattr(obj, "item") and not isinstance(obj, (str, bytes)):
        try:
            return obj.item()
        except Exception:
            return str(obj)
    return obj


NULL_RECORDER = FlightRecorder(out_dir=None, enabled=False)


class _WatchedProgram:
    """A jitted program under watch. Transparent to callers: `__call__`
    forwards, `_cache_size` delegates (so `compile_stats()` keeps
    working). The jit cache size is read before and after each call — a
    growth is a compile; any growth past the first is a RECOMPILE."""

    __slots__ = ("watchdog", "name", "fn")

    def __init__(self, watchdog, name, fn):
        self.watchdog = watchdog
        self.name = name
        self.fn = fn

    def _cache_size(self):
        return self.fn._cache_size()

    def __call__(self, *args, **kwargs):
        try:
            before = self.fn._cache_size()
        except Exception:
            return self.fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        if self.fn._cache_size() > before:
            self.watchdog._on_compile(
                self.name, (time.perf_counter() - t0) * 1e3,
                [tuple(a.shape) for a in args if hasattr(a, "shape")])
        return out


class CompileWatchdog:
    """Per-engine recompile detector over the persistent jitted programs.

    `wrap(name, fn)` returns `fn` untouched when disabled; when enabled it
    returns a `_WatchedProgram` that reports every cache miss. The first
    compile of each program is the expected warmup; every later one
    increments `telemetry/recompiles`, lands a `compile_ms` observation
    (wall time of the compiling call — compile + one step, the latency the
    caller actually felt), and files a flight-recorder event naming the
    program and the argument shapes that triggered it."""

    def __init__(self, telemetry=None, recorder=None):
        self.telemetry = telemetry
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self.enabled = bool(telemetry is not None and
                            getattr(telemetry, "enabled", False))
        self.recompiles = 0
        self.programs: Dict[str, Dict[str, Any]] = {}

    def wrap(self, name, fn):
        if not self.enabled:
            return fn
        self.programs[name] = {"compiles": 0, "recompiles": 0,
                               "last_shapes": None}
        return _WatchedProgram(self, name, fn)

    def _on_compile(self, name, elapsed_ms, shapes):
        entry = self.programs[name]
        entry["compiles"] += 1
        entry["last_shapes"] = shapes
        if self.telemetry is not None:
            self.telemetry.observe("telemetry/compile_ms", elapsed_ms)
        if entry["compiles"] <= 1:
            return                     # warmup: the one expected compile
        entry["recompiles"] += 1
        self.recompiles += 1
        if self.telemetry is not None:
            self.telemetry.inc("telemetry/recompiles")
        self.recorder.record("recompile", program=name,
                             shapes=[list(s) for s in shapes],
                             compile_ms=round(elapsed_ms, 3),
                             nth_compile=entry["compiles"])

    def summary(self) -> Dict[str, Any]:
        return {"recompiles": self.recompiles,
                "programs": {n: dict(e) for n, e in self.programs.items()}}
