"""Request-scoped tracing: one connected timeline per serving request.

The PR 5 histograms answer "what are the p99s"; this layer answers "why was
THIS request slow". A `TraceContext` (trace id + span ids) is minted once
per request — by the `ServingRouter` at submit, or by a standalone
`ServingEngine` when no router is involved — and rides the request through
every hop: router queue, dispatch decision, replica admission, each prefill
chunk, every decode window / spec-decode verify step, KV handoff, failover
re-route, completion. Each hop records a span into the shared `Tracer`,
which exports two views of the same tree:

  * **structured JSONL** (`<subsystem>.trace.jsonl`) — one span per line
    (`trace`/`span`/`parent`/`name`/`uid`/`tid`/`ts`/`dur`/`attrs`), the
    machine-readable record `dstpu_trace` reconstructs timelines from;
  * **chrome trace** (`<subsystem>.trace.json`) — the same spans as "X"
    events on per-replica tids with `process_name`/`thread_name` metadata,
    plus FLOW events ("s"/"f") linking cross-replica hops, so a handoff or
    a failover re-route renders as one connected arrow in Perfetto.

Design constraints, inherited from the PR 5 telemetry contract:

  * disabled by default — a disabled tracer records nothing, writes no
    file, and the instrumented hot paths pay one `is None` check per site;
  * clockless — every span's `t0`/`dur` comes from the CALLER's clock
    (`ServingEngine`/`ServingRouter` already own injectable monotonic
    clocks), so traces from injected-clock tests are deterministic and all
    timestamps of one pool share a single clock domain. The tracer's only
    time math is rebasing chrome `ts` onto the first timestamp it sees;
  * one tracer per POOL — the router injects its tracer into every replica
    (`InProcessReplica.attach_observability`), so a request that crosses
    replicas still lands every span in one file under one trace id.
"""

import dataclasses
import itertools
import json
import os
import threading
from typing import Any, Dict, List, Optional

__all__ = ["TraceContext", "Tracer", "NULL_TRACER", "load_spans",
           "trace_main"]


@dataclasses.dataclass
class TraceContext:
    """One request's place in a trace: carried through submit/dispatch/
    admission instead of thread-locals (the serving stack is an explicit
    host-side state machine — context travels with the request record).

    `parent_id` is the span new child spans attach under; the router moves
    it to each dispatch span so a re-routed request's second attempt nests
    under the re-route, not interleaved with the first. `flow_id` is a
    pending chrome flow arrow: set at the sending hop, consumed (and the
    "f" event emitted) by the receiving hop."""

    trace_id: str
    root_id: int
    uid: Any = None
    owner: str = "engine"          # who closes the root span at completion
    parent_id: int = 0             # current parent for new child spans
    flow_id: Optional[int] = None  # pending cross-track flow arrow
    t0: float = 0.0                # submit time on the owner's clock

    def __post_init__(self):
        if not self.parent_id:
            self.parent_id = self.root_id


class Tracer:
    """Span recorder behind an enabled flag. All methods are no-ops when
    disabled; when enabled they append one JSONL line (and mirror into the
    chrome sink when one is attached) per span, under a lock — cheap, and
    the serving stack records a handful of spans per scheduler step."""

    def __init__(self, path=None, chrome=None, enabled=True):
        self.enabled = bool(enabled) and path is not None
        self.path = str(path) if path is not None else None
        self.chrome = chrome if self.enabled else None
        self._f = None
        self._ids = itertools.count(1)     # span AND trace sequence numbers
        self._t0 = None                    # chrome ts baseline (first stamp)
        self._lock = threading.Lock()
        self._named_tids = set()
        # completed-span tap: the replica server's observability spool
        # subscribes here so finished spans can ship over the wire to the
        # router. None (the default) costs one attribute read per record.
        self.on_record = None

    # ---- context lifecycle -------------------------------------------

    def start(self, uid, t0=0.0, owner="engine") -> Optional[TraceContext]:
        """Mint a trace for one request (None when disabled — the request
        records carry None and every record site skips on it)."""
        if not self.enabled:
            return None
        n = next(self._ids)
        return TraceContext(trace_id=f"t{n:06d}", root_id=next(self._ids),
                            uid=uid, owner=owner, t0=t0)

    # ---- recording ----------------------------------------------------

    def record(self, ctx, name, t0, dur=0.0, tid=0, attrs=None,
               parent=None, span_id=None) -> int:
        """Record one complete span under `ctx`. Times are seconds on the
        caller's clock. Returns the span id (callers that re-parent — the
        router's dispatch span — keep it)."""
        if not self.enabled or ctx is None:
            return 0
        sid = span_id if span_id is not None else next(self._ids)
        rec = {"trace": ctx.trace_id, "span": sid,
               "parent": ctx.parent_id if parent is None else parent,
               "name": name, "uid": ctx.uid, "tid": tid,
               "ts": round(float(t0), 9), "dur": round(float(dur), 9)}
        if attrs:
            rec["attrs"] = attrs
        self._write(rec)
        if self.chrome is not None:
            self.chrome.write({"name": name, "ph": "X", "pid": os.getpid(),
                               "tid": tid, "ts": self._chrome_ts(t0),
                               "dur": round(dur * 1e6, 3),
                               "args": dict(attrs or {}, uid=str(ctx.uid),
                                            trace=ctx.trace_id)})
        return sid

    def event(self, ctx, name, t, tid=0, attrs=None) -> int:
        """Instant event (a zero-duration span in the JSONL tree, an "i"
        mark in the chrome view)."""
        if not self.enabled or ctx is None:
            return 0
        sid = next(self._ids)
        rec = {"trace": ctx.trace_id, "span": sid, "parent": ctx.parent_id,
               "name": name, "uid": ctx.uid, "tid": tid,
               "ts": round(float(t), 9), "dur": 0.0}
        if attrs:
            rec["attrs"] = attrs
        self._write(rec)
        if self.chrome is not None:
            self.chrome.write({"name": name, "ph": "i", "s": "t",
                               "pid": os.getpid(), "tid": tid,
                               "ts": self._chrome_ts(t),
                               "args": dict(attrs or {}, uid=str(ctx.uid),
                                            trace=ctx.trace_id)})
        return sid

    def finish(self, ctx, t_end, name="request", tid=0, attrs=None):
        """Close the root span (whole-request e2e). Called once, by the
        context's owner (router `_complete`, or a standalone engine's
        retirement path)."""
        if not self.enabled or ctx is None:
            return
        self.record(ctx, name, ctx.t0, max(0.0, t_end - ctx.t0), tid=tid,
                    attrs=attrs, parent=0, span_id=ctx.root_id)

    # ---- cross-track flow arrows (chrome-only linking) ------------------

    def flow_begin(self, ctx, t, tid=0):
        """Open a flow arrow at the sending hop (dispatch, re-route,
        handoff); the receiving hop calls `flow_end` and Perfetto draws the
        connecting arrow between the two tracks."""
        if not self.enabled or ctx is None:
            return
        fid = next(self._ids)
        ctx.flow_id = fid
        if self.chrome is not None:
            self.chrome.write({"name": "request-flow", "cat": "flow",
                               "ph": "s", "id": fid, "pid": os.getpid(),
                               "tid": tid, "ts": self._chrome_ts(t)})

    def flow_end(self, ctx, t, tid=0):
        if not self.enabled or ctx is None or ctx.flow_id is None:
            return
        fid, ctx.flow_id = ctx.flow_id, None
        if self.chrome is not None:
            self.chrome.write({"name": "request-flow", "cat": "flow",
                               "ph": "f", "bp": "e", "id": fid,
                               "pid": os.getpid(), "tid": tid,
                               "ts": self._chrome_ts(t)})

    # ---- track naming ---------------------------------------------------

    def name_process(self, name):
        if self.enabled and self.chrome is not None:
            self.chrome.add_meta("process_name", name)

    def name_track(self, tid, name):
        """Label a Perfetto track (idempotent per tid) — the router names
        tid 0 after itself and one tid per replica."""
        if not self.enabled or self.chrome is None or tid in self._named_tids:
            return
        self._named_tids.add(tid)
        self.chrome.add_meta("thread_name", name, tid=tid)

    # ---- plumbing -------------------------------------------------------

    def _chrome_ts(self, t):
        # share the chrome sink's perf_counter baseline when one is
        # attached: the default tracer clock (time.monotonic) reads the
        # same Linux CLOCK_MONOTONIC, so phase spans (Span/telemetry.span)
        # and request-trace events align on ONE Perfetto timeline instead
        # of drifting apart by the init-to-first-request offset. Injected
        # test clocks fall back to a first-stamp baseline (chrome ts is
        # cosmetic; the JSONL record keeps the caller's raw stamps).
        if self._t0 is None:
            sink_t0 = getattr(self.chrome, "_t0", None)
            self._t0 = sink_t0 if sink_t0 is not None \
                and abs(t - sink_t0) < 3600.0 else t
        return round((t - self._t0) * 1e6, 3)

    def _write(self, rec):
        with self._lock:
            if self._f is None:
                self._f = open(self.path, "a")
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
        cb = self.on_record
        if cb is not None:
            cb(rec)

    def close(self):
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                finally:
                    self._f = None


NULL_TRACER = Tracer(path=None, enabled=False)


# ----------------------------------------------------------------------
# dstpu_trace: reconstruct request timelines from the JSONL span log
# ----------------------------------------------------------------------


def load_spans(path) -> List[Dict[str, Any]]:
    """All span records of a trace log (a torn final line — crash
    mid-append — is skipped, like the metrics CLI)."""
    spans = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                spans.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return spans


def _group_traces(spans):
    traces: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        traces.setdefault(s["trace"], []).append(s)
    for tr in traces.values():
        tr.sort(key=lambda s: (s["ts"], s["span"]))
    return traces


def _root(tr):
    for s in tr:
        if s.get("parent") == 0:
            return s
    return None


def _fmt_table(rows):
    widths = [max(len(str(r[i])) for r in rows) for i in range(len(rows[0]))]
    return "\n".join("  ".join(str(c).ljust(w)
                               for c, w in zip(r, widths)).rstrip()
                     for r in rows)


def render_timeline(tr) -> str:
    """One request's spans as a table: offset/duration relative to the
    trace start, depth-indented by parent links."""
    t0 = min(s["ts"] for s in tr)
    by_id = {s["span"]: s for s in tr}

    def depth(s):
        d, seen = 0, set()
        while s["parent"] in by_id and s["span"] not in seen:
            seen.add(s["span"])
            s = by_id[s["parent"]]
            d += 1
        return d

    rows = [("at_ms", "dur_ms", "tid", "span", "attrs")]
    for s in tr:
        attrs = s.get("attrs") or {}
        a = " ".join(f"{k}={v}" for k, v in attrs.items())
        rows.append((f"{(s['ts'] - t0) * 1e3:10.3f}",
                     f"{s['dur'] * 1e3:9.3f}", s["tid"],
                     "  " * depth(s) + s["name"], a))
    return _fmt_table(rows)


def _phase_breakdown(tr) -> Dict[str, float]:
    """dur-ms summed per span name, root excluded — the per-phase view
    `--slowest` ranks with."""
    out: Dict[str, float] = {}
    for s in tr:
        if s.get("parent") == 0:
            continue
        out[s["name"]] = out.get(s["name"], 0.0) + s["dur"] * 1e3
    return out


def render_slowest(traces, n) -> str:
    """Top-n traces by root (e2e) duration with per-phase dur-ms columns."""
    roots = [(tr, _root(tr)) for tr in traces.values()]
    roots = [(tr, r) for tr, r in roots if r is not None]
    roots.sort(key=lambda x: -x[1]["dur"])
    roots = roots[:n]
    phases = sorted({name for tr, _ in roots
                     for name in _phase_breakdown(tr)})
    rows = [("uid", "trace", "e2e_ms", *phases)]
    for tr, r in roots:
        br = _phase_breakdown(tr)
        rows.append((str(r.get("uid")), r["trace"], f"{r['dur'] * 1e3:.3f}",
                     *(f"{br.get(p, 0.0):.3f}" for p in phases)))
    return _fmt_table(rows)


def trace_main(argv=None):
    """`dstpu_trace` — reconstruct request timelines from a trace JSONL."""
    import argparse
    import pathlib
    import sys

    ap = argparse.ArgumentParser(
        prog="dstpu_trace",
        description="Reconstruct per-request timelines from a deepspeed-tpu "
                    "trace log (<subsystem>.trace.jsonl).")
    ap.add_argument("path", nargs="?", default="telemetry",
                    help="trace .jsonl file or telemetry output dir "
                         "(default: ./telemetry)")
    ap.add_argument("--uid", default=None,
                    help="print one request's span timeline (by request uid)")
    ap.add_argument("--slowest", type=int, default=None, metavar="N",
                    help="rank the N slowest requests by e2e with a "
                         "per-phase duration breakdown")
    args = ap.parse_args(argv)

    p = pathlib.Path(args.path)
    if p.is_dir():
        logs = sorted(p.glob("*.trace.jsonl"), key=lambda f: f.stat().st_mtime)
        p = logs[-1] if logs else p
    if not p.is_file():
        print(f"dstpu_trace: no trace log at {args.path!r}", file=sys.stderr)
        return 1
    traces = _group_traces(load_spans(p))
    if not traces:
        print(f"dstpu_trace: {p} holds no spans", file=sys.stderr)
        return 1

    if args.uid is not None:
        matches = [tr for tr in traces.values()
                   if any(str(s.get("uid")) == args.uid for s in tr)]
        if not matches:
            print(f"dstpu_trace: no trace for uid {args.uid!r}",
                  file=sys.stderr)
            return 1
        for tr in matches:
            print(f"trace {tr[0]['trace']} uid={args.uid} "
                  f"({len(tr)} spans)")
            print(render_timeline(tr))
        return 0

    if args.slowest is not None:
        print(render_slowest(traces, args.slowest))
        return 0

    rows = [("trace", "uid", "spans", "e2e_ms")]
    for tid_, tr in sorted(traces.items()):
        r = _root(tr)
        rows.append((tid_, str(r.get("uid")) if r else "?", len(tr),
                     f"{r['dur'] * 1e3:.3f}" if r else "?"))
    print(_fmt_table(rows))
    return 0
