"""Exporters: Prometheus text exposition, append-only JSONL, monitor bridge.

Three sinks for one registry, each serving a different consumer:

  * `PrometheusFileExporter` — the text exposition format written atomically
    (tmp + rename), so a node-exporter-style textfile collector or a sidecar
    `cat` can scrape mid-write without tearing;
  * `JsonlExporter` — one JSON object per export (step, wall time, full
    snapshot), append-only; `bin/dstpu_metrics` tails this file and the
    bench records its latest snapshot into BENCH_*.json;
  * `MonitorBridge` — flattens snapshots into `(tag, value, step)` scalars
    through `monitor.write_events_safe`, so existing TB/WandB/CSV dashboards
    keep working: a histogram fans out to `<name>/p50|p90|p99|mean|count`.
"""

import json
import math
import os
import time

from deepspeed_tpu.telemetry.registry import Counter, Gauge, Histogram

__all__ = ["prometheus_text", "PrometheusFileExporter", "JsonlExporter",
           "MonitorBridge"]


def _prom_name(name):
    """Sanitize a metric name for Prometheus ([a-zA-Z0-9_:] only, and a
    leading digit gets an underscore prefix — the name grammar is
    `[a-zA-Z_:][a-zA-Z0-9_:]*`)."""
    pn = "".join(c if c.isalnum() or c in "_:" else "_" for c in name)
    return "_" + pn if pn[:1].isdigit() else pn


def _escape_help(text):
    """HELP-line escaping per the text format: backslash and newline."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value):
    """Label-value escaping per the text format: backslash, double quote,
    newline."""
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt(v):
    if v != v or v in (math.inf, -math.inf):     # NaN / +-Inf
        return {math.inf: "+Inf", -math.inf: "-Inf"}.get(v, "NaN")
    if float(v).is_integer():
        return str(int(v))
    return f"{v:.10g}"


def prometheus_text(registry, help_map=None):
    """Render a registry in the Prometheus text exposition format.

    Conformance points external scrapers check (pinned by the exporter
    conformance test): every metric family carries `# HELP` then `# TYPE`
    exactly once, HELP text and label values are escaped, counters end in
    `_total`, and every histogram exposes the mandatory `+Inf` bucket whose
    cumulative count equals `_count` (with `_sum` alongside). `help_map`
    overrides the per-metric HELP text (original metric name -> text);
    the default text is the registry name itself, which carries the unit
    suffix convention (`*_ms`) the catalog documents."""
    help_map = help_map or {}
    lines = []
    for name, m in registry.metrics():
        pn = _prom_name(name)
        help_text = _escape_help(help_map.get(name, f"deepspeed-tpu {name}"))
        if isinstance(m, Counter):
            if not pn.endswith("_total"):
                pn += "_total"
            lines.append(f"# HELP {pn} {help_text}")
            lines.append(f"# TYPE {pn} counter")
            lines.append(f"{pn} {_fmt(m.value)}")
        elif isinstance(m, Gauge):
            lines.append(f"# HELP {pn} {help_text}")
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn} {_fmt(m.value)}")
        elif isinstance(m, Histogram):
            lines.append(f"# HELP {pn} {help_text}")
            lines.append(f"# TYPE {pn} histogram")
            for edge, cum in m.cumulative_buckets():
                lines.append(
                    f'{pn}_bucket{{le="{_escape_label(_fmt(edge))}"}} {cum}')
            lines.append(f"{pn}_sum {_fmt(m.sum)}")
            lines.append(f"{pn}_count {m.count}")
    return "\n".join(lines) + "\n"


class PrometheusFileExporter:
    """Atomic textfile exposition — write tmp, fsync-free rename (the file is
    derived state; losing the last interval on a crash is fine, a half-
    written scrape is not)."""

    def __init__(self, path):
        self.path = str(path)

    def export(self, registry, step=None, snapshot=None):
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(prometheus_text(registry))
        os.replace(tmp, self.path)

    def close(self):
        pass


class JsonlExporter:
    """Append-only metrics log: one `{"step", "time", "metrics"}` object per
    export. Opened lazily so an enabled-but-never-exported telemetry block
    leaves no empty file behind."""

    def __init__(self, path):
        self.path = str(path)
        self._f = None

    def export(self, registry, step=None, snapshot=None):
        if self._f is None:
            self._f = open(self.path, "a")
        snap = snapshot if snapshot is not None else registry.snapshot()
        self._f.write(json.dumps({"step": step, "time": time.time(),
                                  "metrics": snap}) + "\n")
        self._f.flush()

    def close(self):
        if self._f is not None:
            try:
                self._f.close()
            finally:
                self._f = None


class MonitorBridge:
    """Registry snapshots -> MonitorMaster scalars (never-die contract)."""

    def __init__(self, monitor):
        self.monitor = monitor

    def export(self, registry, step=None, snapshot=None):
        from deepspeed_tpu.monitor.monitor import write_events_safe
        snap = snapshot if snapshot is not None else registry.snapshot()
        step = int(step or 0)
        events = []
        for name, m in snap.items():
            if m["type"] == "histogram":
                for stat in ("p50", "p90", "p99", "mean"):
                    events.append((f"{name}/{stat}", float(m[stat]), step))
                events.append((f"{name}/count", float(m["count"]), step))
            else:
                events.append((name, float(m["value"]), step))
        write_events_safe(self.monitor, events)

    def close(self):
        pass
