"""`dstpu_metrics` — render the latest snapshot from a telemetry JSONL log.

The JSONL exporter appends one `{"step", "time", "metrics"}` object per
export interval; this CLI tails that file (or the newest `*.jsonl` in a
telemetry directory) and prints the latest snapshot as a table, as raw JSON
(`--json`, for scripting / the golden round-trip test), or continuously
(`--watch`).
"""

import argparse
import json
import pathlib
import sys
import time


def find_log(path):
    """Resolve a metrics log: a .jsonl file as-is, a directory to its newest
    `*.jsonl` by mtime. Returns None when nothing is there."""
    p = pathlib.Path(path)
    if p.is_file():
        return p
    if p.is_dir():
        logs = sorted(p.glob("*.jsonl"), key=lambda f: f.stat().st_mtime)
        if logs:
            return logs[-1]
    return None


def load_latest(path):
    """Last valid JSON record of the log (None when empty/absent). A torn
    final line — the exporter crashed mid-append — falls back to the
    previous record instead of erroring."""
    log = find_log(path)
    if log is None:
        return None
    record = None
    with open(log) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
    return record


def load_pool(path):
    """`--pool`: the latest record from EVERY `*.jsonl` metrics log under
    the directory, merged into ONE pool snapshot (`merge_snapshots`:
    counters sum, gauges keep a per-source map, histograms merge
    bucket-wise exactly) keyed by file stem. Non-metrics JSONL files in
    the same dir (trace/spool logs) are skipped — their records carry no
    `metrics` field."""
    p = pathlib.Path(path)
    if p.is_file():
        logs = [p]
    elif p.is_dir():
        logs = sorted(p.glob("*.jsonl"))
    else:
        logs = []
    per, step, when = {}, 0, 0
    for log in logs:
        rec = load_latest(log)
        if rec and rec.get("metrics"):
            per[log.stem] = rec["metrics"]
            step = max(step, int(rec.get("step", 0) or 0))
            when = max(when, rec.get("time", 0) or 0)
    if not per:
        return None
    from deepspeed_tpu.telemetry.registry import merge_snapshots
    return {"step": step, "time": when, "sources": sorted(per),
            "metrics": merge_snapshots(per)}


def counter_rate(name, cur, prev):
    """Per-second rate of a counter between two snapshot records, or None
    when it cannot be computed (no previous record, metric absent/not a
    counter there, no wall-time delta, or a reset — the counter going
    BACKWARD between snapshots, e.g. a restarted process)."""
    if prev is None:
        return None
    pm = prev.get("metrics", {}).get(name)
    if pm is None or pm.get("type") != "counter":
        return None
    dt = cur.get("time", 0) - prev.get("time", 0)
    if dt <= 0:
        return None
    delta = cur["metrics"][name]["value"] - pm["value"]
    if delta < 0:
        return None
    return delta / dt


def render(record, prev=None):
    """Human table for one snapshot record. With `prev` (the previously
    rendered record — `--watch` threads it through), counters grow a
    per-interval rate column: the thing you actually watch is tokens/s or
    requests/s, not a raw monotonic total."""
    metrics = record.get("metrics", {})
    when = time.strftime("%Y-%m-%d %H:%M:%S",
                         time.localtime(record.get("time", 0)))
    lines = [f"step {record.get('step')} @ {when}", ""]
    rows = [("metric", "type", "value / count", "rate/s", "mean", "p50",
             "p90", "p99")]
    for name in sorted(metrics):
        m = metrics[name]
        if m.get("type") == "histogram":
            rows.append((name, "hist", str(m["count"]), "",
                         f"{m['mean']:.3f}", f"{m['p50']:.3f}",
                         f"{m['p90']:.3f}", f"{m['p99']:.3f}"))
        else:
            rate = counter_rate(name, record, prev) \
                if m.get("type") == "counter" else None
            val = m.get("value", 0)
            if "bytes" in name.replace("/", "_").split("_"):
                # byte-valued gauges/counters (the memscope ledger, HBM
                # watermarks) render human-readably in the table; --json
                # keeps the raw integer untouched
                from deepspeed_tpu.telemetry.memscope import fmt_bytes
                shown = fmt_bytes(val)
            else:
                shown = f"{val:g}"
            rows.append((name, m.get("type", "?"), shown,
                         "" if rate is None else f"{rate:.3g}/s",
                         "", "", "", ""))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="dstpu_metrics",
        description="Summarize a deepspeed-tpu telemetry JSONL metrics log.")
    ap.add_argument("path", nargs="?", default="telemetry",
                    help="metrics .jsonl file or telemetry output dir "
                         "(default: ./telemetry)")
    ap.add_argument("--json", action="store_true",
                    help="print the latest snapshot record as raw JSON")
    ap.add_argument("--pool", action="store_true",
                    help="merge the latest snapshot of EVERY *.jsonl in the "
                         "dir into one pool view (counters sum, histograms "
                         "merge bucket-wise — pool-exact percentiles)")
    ap.add_argument("--watch", action="store_true",
                    help="re-render every --interval seconds")
    ap.add_argument("--interval", type=float, default=2.0)
    args = ap.parse_args(argv)

    def emit(prev=None):
        record = (load_pool(args.path) if args.pool
                  else load_latest(args.path))
        if record is None:
            print(f"dstpu_metrics: no metrics log at {args.path!r}",
                  file=sys.stderr)
            return 1, prev
        print(json.dumps(record) if args.json
              else render(record, prev=prev))
        return 0, record

    if not args.watch:
        return emit()[0]
    prev = None
    try:
        while True:
            sys.stdout.write("\x1b[2J\x1b[H")
            # thread the previous snapshot through so counters render
            # per-interval rates, not just monotonic totals
            _, prev = emit(prev)
            time.sleep(max(args.interval, 0.2))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
