"""HBM memory ledger, pre-flight capacity planner, and OOM forensics.

Every marquee scenario this stack targets — ZeRO sharding, offload,
bigger-than-HBM inference, quantized KV — is won or lost in device-memory
bytes, and until now nobody could say where those bytes GO: the PR 5
histograms time things, the PR 8 traces order them, but no layer attributed
HBM. This module is the byte layer, with three coordinated faces:

  * **Live ledger** (`ServingMemScope` / `TrainMemScope`): per-subsystem HBM
    attribution — params, KV block pool, prefix-cache-held blocks, draft
    mirror, optimizer state / fp32 master, compiled-program temp (XLA
    ``memory_analysis()`` of the persistent jitted programs) — published as
    ``mem/*`` gauges through the telemetry registry, next to the raw
    ``device.memory_stats()`` watermarks and an honest *unattributed*
    residual line. A serving router aggregates its replicas' ledgers into
    pool-level gauges.

  * **Pre-flight capacity planner** (`plan_training` / `plan_serving` —
    the `estimate_zero*_model_states_mem_needs` analog): given a model size
    x mesh x ZeRO stage/offload flags, or a serving pool geometry, predict
    resident bytes BEFORE anything compiles, warn or refuse on predicted
    OOM, and answer the inverse question deployment actually asks
    (`max_kv_blocks`: the largest pool that fits). Predictions are
    validated against ``memory_analysis()`` of the real compiled programs
    in tier-1 (documented tolerances: serving 5%, training 10% — the slack
    is the small non-modeled arguments: token ids, tables, rng keys,
    bookkeeping scalars, the batch).

  * **OOM forensics**: the engine/scheduler dispatch boundaries catch
    RESOURCE_EXHAUSTED, dump the ledger + the planner delta (predicted vs
    observed — the line that says whether the OOM was *foreseeable*) + the
    PR 8 flight-recorder ring to ``<subsystem>.memscope.oom.NNN.json``, and
    re-raise. ``mem/headroom_frac`` also feeds the PR 9 PressureController
    as an optional pressure signal (`degradation.headroom_low`).

Disabled by default like every observability layer here: without
``telemetry.memscope`` no scope object is built, no gauge exists, no file
is written, and ``compile_stats()`` is untouched (the ``memory_analysis()``
reads go through the AOT ``lower().compile()`` path, which never populates
the jit call cache — asserted in tests).

This module stays import-light on purpose (no module-level jax import):
the planner half runs anywhere `bin/dstpu_memscope --plan` does.
"""

import dataclasses
import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

__all__ = [
    "MemoryPlan", "PredictedOOMError", "ServingMemScope", "TrainMemScope",
    "plan_training", "plan_serving", "plan_training_from_engine",
    "plan_training_from_infinity",
    "plan_serving_prealloc", "serving_pool_bytes", "max_kv_blocks",
    "estimate_zero2_model_states_mem_needs",
    "estimate_zero3_model_states_mem_needs",
    "aot_memory_analysis", "is_resource_exhausted", "kv_cache_is_quantized",
    "tree_bytes", "dtype_bytes", "fmt_bytes", "LEDGER_GAUGES",
]

# every key the ledger may publish as a `mem/<key>` gauge — the metric-
# catalog lint test enumerates these (they are set through one loop, so the
# literal-name scan cannot see them); growing this tuple means growing the
# docs/profiling.md catalog row
LEDGER_GAUGES = (
    "params_bytes", "kv_pool_bytes", "kv_pool_per_chip_bytes",
    "prefix_cached_bytes",
    "draft_params_bytes", "draft_pool_bytes",
    "master_bytes", "opt_state_bytes",
    "offload_staged_bytes", "offload_host_bytes",
    "moe_expert_params_bytes",
    "program_temp_bytes", "bytes_in_use", "peak_bytes", "capacity_bytes",
    "attributed_bytes", "unattributed_bytes", "headroom_frac",
)

# documented planner-vs-XLA validation tolerances (tests assert these)
SERVING_PLAN_TOLERANCE = 0.05
TRAIN_PLAN_TOLERANCE = 0.10


# ----------------------------------------------------------------------
# byte helpers
# ----------------------------------------------------------------------

_DTYPE_BYTES = {
    "float64": 8, "fp64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "fp32": 4, "float": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "bf16": 2, "float16": 2, "fp16": 2, "half": 2,
    "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
}


def dtype_bytes(dtype) -> int:
    """Itemsize of a dtype given as a string, numpy/jnp dtype, or a scalar
    TYPE object (jnp.float32, the engine's `compute_dtype` spelling) —
    without importing jax for the common string spellings (the CLI planner
    runs on machines with no accelerator stack at all)."""
    if isinstance(dtype, str):
        low = dtype.lower()
        if low in _DTYPE_BYTES:
            return _DTYPE_BYTES[low]
        import numpy as np
        return int(np.dtype(low).itemsize)
    name = getattr(dtype, "name", None)
    if isinstance(name, str) and name.lower() in _DTYPE_BYTES:
        return _DTYPE_BYTES[name.lower()]
    import numpy as np
    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:
        import jax.numpy as jnp                  # bfloat16 scalar types etc.
        return int(jnp.dtype(dtype).itemsize)


def tree_bytes(tree) -> int:
    """Total logical bytes of a pytree's array leaves (size x itemsize —
    sharding-agnostic: the GLOBAL footprint, which equals the per-device
    one for the replicated placements serving uses)."""
    if tree is None:
        return 0
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        size = getattr(leaf, "size", None)
        dt = getattr(leaf, "dtype", None)
        if size is None or dt is None:
            continue
        total += int(size) * dtype_bytes(dt)
    return total


def fmt_bytes(n) -> str:
    """Human-readable bytes (KiB/MiB/GiB); exact integers below 1 KiB."""
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    sign = "-" if n < 0 else ""
    n = abs(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            if unit == "B":
                return f"{sign}{n:.0f} B"
            return f"{sign}{n:.2f} {unit}"
        n /= 1024.0


def device_memory_stats() -> Dict[str, int]:
    """`device.memory_stats()` of local device 0, guarded: {} wherever the
    runtime exposes no allocator stats (the CPU harness returns None)."""
    try:
        from deepspeed_tpu.utils.memory import device_memory_stats as dms
        return dms() or {}
    except Exception:
        return {}


# ----------------------------------------------------------------------
# XLA memory analysis of compiled programs (the ledger's temp/peak source
# and the planner's validation oracle)
# ----------------------------------------------------------------------


def aot_memory_analysis(fn, *args) -> Dict[str, int]:
    """``memory_analysis()`` of `fn` compiled for the SHAPES of `args`.

    Goes through the AOT ``lower().compile()`` path with abstract
    `ShapeDtypeStruct`s (shardings preserved when the example carries
    them), so nothing executes, no buffer materializes, and — crucial for
    the serving compile contract — the jit CALL cache is untouched:
    ``compile_stats()`` reads the same before and after. `fn` may be the
    compile watchdog's `_WatchedProgram` wrapper (unwrapped here). Returns
    {} when the backend exposes no analysis. One extra XLA compile per
    distinct (fn, shapes) — callers cache the result.
    """
    import jax

    if not hasattr(fn, "lower"):
        fn = getattr(fn, "fn", fn)          # _WatchedProgram passthrough
    if not hasattr(fn, "lower"):
        return {}

    def sds(x):
        try:
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=getattr(x, "sharding", None))
        except Exception:
            import numpy as np
            a = np.asarray(x)
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

    try:
        abstract = jax.tree_util.tree_map(sds, args)
        ma = fn.lower(*abstract).compile().memory_analysis()
    except Exception as e:
        logger.warning(f"memscope: memory_analysis unavailable ({e})")
        return {}
    if ma is None:
        return {}

    def get(attr):
        return int(getattr(ma, attr, 0) or 0)

    return {"argument_bytes": get("argument_size_in_bytes"),
            "output_bytes": get("output_size_in_bytes"),
            "temp_bytes": get("temp_size_in_bytes"),
            "alias_bytes": get("alias_size_in_bytes"),
            "generated_code_bytes": get("generated_code_size_in_bytes")}


# ----------------------------------------------------------------------
# the pre-flight capacity planner
# ----------------------------------------------------------------------


class PredictedOOMError(RuntimeError):
    """The planner predicts this configuration cannot fit device memory
    (raised only under ``memscope_preflight: "refuse"`` or an explicit
    ``preflight_check(..., refuse=True)``)."""


@dataclasses.dataclass
class MemoryPlan:
    """A capacity prediction: per-category device/host bytes plus optional
    measured-or-margin temp and a capacity to judge against. `fits` is
    None when no capacity is known (the CPU harness has no HBM limit)."""
    kind: str                                   # "train" | "serving"
    device_bytes: Dict[str, int]
    host_bytes: Dict[str, int] = dataclasses.field(default_factory=dict)
    temp_bytes: int = 0
    capacity_bytes: int = 0
    notes: List[str] = dataclasses.field(default_factory=list)

    @property
    def total_device_bytes(self) -> int:
        return int(sum(self.device_bytes.values()))

    @property
    def total_host_bytes(self) -> int:
        return int(sum(self.host_bytes.values()))

    @property
    def predicted_peak_bytes(self) -> int:
        return self.total_device_bytes + int(self.temp_bytes)

    @property
    def headroom_bytes(self) -> Optional[int]:
        if not self.capacity_bytes:
            return None
        return int(self.capacity_bytes) - self.predicted_peak_bytes

    @property
    def headroom_frac(self) -> Optional[float]:
        hb = self.headroom_bytes
        if hb is None:
            return None
        return hb / float(self.capacity_bytes)

    @property
    def fits(self) -> Optional[bool]:
        hb = self.headroom_bytes
        return None if hb is None else hb >= 0

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind,
                "device_bytes": dict(self.device_bytes),
                "host_bytes": dict(self.host_bytes),
                "temp_bytes": int(self.temp_bytes),
                "capacity_bytes": int(self.capacity_bytes),
                "total_device_bytes": self.total_device_bytes,
                "total_host_bytes": self.total_host_bytes,
                "predicted_peak_bytes": self.predicted_peak_bytes,
                "headroom_bytes": self.headroom_bytes,
                "headroom_frac": self.headroom_frac,
                "fits": self.fits,
                "notes": list(self.notes)}

    def render(self) -> str:
        lines = [f"memory plan ({self.kind})"]
        for name, b in self.device_bytes.items():
            lines.append(f"  device {name:<18} {fmt_bytes(b)}")
        if self.temp_bytes:
            lines.append(f"  device {'program_temp':<18} "
                         f"{fmt_bytes(self.temp_bytes)}")
        lines.append(f"  device TOTAL (peak)       "
                     f"{fmt_bytes(self.predicted_peak_bytes)}")
        for name, b in self.host_bytes.items():
            lines.append(f"  host   {name:<18} {fmt_bytes(b)}")
        if self.capacity_bytes:
            verdict = "FITS" if self.fits else "PREDICTED OOM"
            lines.append(f"  capacity {fmt_bytes(self.capacity_bytes)} -> "
                         f"headroom {fmt_bytes(self.headroom_bytes)} "
                         f"({self.headroom_frac:.1%}) [{verdict}]")
        for n in self.notes:
            lines.append(f"  note: {n}")
        return "\n".join(lines)


def plan_training(n_params, *, zero_stage=0, dp=1, tp=1, dtype="bfloat16",
                  master_weights=True, optimizer_moments=2,
                  grad_accum_dtype=None, offload_optimizer=False,
                  offload_param=False, offload_param_bytes=None,
                  offload_staging_layers=0, offload_layer_bytes=0,
                  num_experts=0, ep_size=1, n_expert_params=0,
                  temp_bytes=0, capacity_bytes=0) -> MemoryPlan:
    """Model-state memory prediction per device — the ZeRO estimator.

    Mirrors the reference's `estimate_zero*_model_states_mem_needs` math on
    the TPU realization (`runtime/zero.py`): ZeRO stages are sharding
    denominators over the data domain — stage >= 1 shards optimizer state
    + fp32 master, stage >= 2 shards gradients, stage >= 3 shards the
    parameters themselves; TP divides everything. Offload flags move the
    corresponding states to the host column.

    Like the reference estimators this models MODEL STATES only:
    activations/workspace are XLA temporaries, covered by `temp_bytes`
    (pass a measured ``memory_analysis().temp_size_in_bytes`` when you have
    a compiled step, or a margin). Gradients here are also XLA temporaries
    inside the fused train step (they appear in temp, not as resident
    arguments) but are listed per the reference's convention — the
    planner-parity test compares `total - grads` against the compiled
    step's argument bytes.

    Exact offload pricing (the Infinity tier; `plan_training_from_infinity`
    fills these from the live engine): `offload_param_bytes` overrides the
    host params column with a LIVE store's measured bytes — the prediction
    is then byte-identical to the `LayerParamStore`, not an n·dtype
    estimate — and `offload_staging_layers` × `offload_layer_bytes` prices
    the device-side async staging window (lookahead+1 layers of weights in
    rotation) that the offloaded params still occupy.

    MoE pricing: `n_expert_params` (of the `n_params` total, summed over
    all `num_experts` experts) shards over the EXPERT axis — per-chip
    expert bytes are `n_expert_params/ep_size` on top of whatever the
    ZeRO/TP denominators already divide (expert leading dims carry
    `P(expert, …)` specs — `models/moe_gpt.py` `moe_gpt_param_specs`).
    The expert slice is listed as its own `moe_expert_params` device
    category so the plan shows the sparse-capacity headroom directly.
    """
    n = int(n_params)
    n_exp = min(int(n_expert_params), n)
    n -= n_exp                        # dense remainder below
    dp = max(1, int(dp))
    tp = max(1, int(tp))
    ep = max(1, int(ep_size))
    p_b = dtype_bytes(dtype)
    p_shard = tp * (dp if zero_stage >= 3 else 1)
    g_shard = tp * (dp if zero_stage >= 2 else 1)
    o_shard = tp * (dp if zero_stage >= 1 else 1)
    dev: Dict[str, int] = {}
    host: Dict[str, int] = {}
    notes: List[str] = []

    params = n * p_b // p_shard
    if offload_param:
        host["params"] = params if offload_param_bytes is None \
            else int(offload_param_bytes)
        dev["params"] = 0
        if offload_staging_layers and offload_layer_bytes:
            dev["param_staging"] = int(offload_staging_layers) * \
                int(offload_layer_bytes)
            notes.append(
                f"offload_param: async staging pool keeps "
                f"{int(offload_staging_layers)} layer(s) of weights "
                f"device-resident (lookahead+1 rotation)")
        notes.append("offload_param: bit16 params host-resident, "
                     "streamed/gathered through HBM per layer")
    else:
        dev["params"] = params

    g_b = dtype_bytes(grad_accum_dtype) if grad_accum_dtype else p_b
    dev["grads"] = n * g_b // g_shard

    master = n * 4 // o_shard if (master_weights and p_b < 4) else 0
    optim = n * 4 * max(0, int(optimizer_moments)) // o_shard

    if n_exp:
        # expert leaves shard their leading dim over the expert axis, on
        # top of the ZeRO/TP denominators (specs: P(expert, …))
        dev["moe_expert_params"] = n_exp * p_b // (p_shard * ep)
        dev["grads"] += n_exp * g_b // (g_shard * ep)
        if master_weights and p_b < 4:
            master += n_exp * 4 // (o_shard * ep)
        optim += n_exp * 4 * max(0, int(optimizer_moments)) // (o_shard * ep)
        notes.append(
            f"moe: {int(num_experts) or '?'} experts, "
            f"{fmt_bytes(n_exp * p_b)} of expert weights shard /ep_size="
            f"{ep} on the expert axis — per-chip expert params = "
            f"{fmt_bytes(n_exp * p_b // (p_shard * ep))}")
    if offload_optimizer:
        if master:
            host["master"] = master
        host["optim"] = optim
        dev["master"] = dev["optim"] = 0
        notes.append("offload_optimizer: fp32 master + moments host-"
                     "resident (streamed through HBM, or host-stepped)")
    else:
        if master:
            dev["master"] = master
        dev["optim"] = optim

    notes.append("model states only — activations/workspace live in "
                 "temp_bytes (measured or margin); grads are XLA "
                 "temporaries inside the fused step")
    return MemoryPlan("train", dev, host, int(temp_bytes),
                      int(capacity_bytes), notes)


def estimate_zero2_model_states_mem_needs(total_params, num_devices=1,
                                          cpu_offload=False,
                                          **kw) -> MemoryPlan:
    """Reference-API analog (`deepspeed.runtime.zero` estimators): ZeRO-2
    model-state needs for `total_params` over `num_devices`. Logs the
    verdict and returns the full `MemoryPlan`."""
    plan = plan_training(total_params, zero_stage=2, dp=num_devices,
                         offload_optimizer=cpu_offload, **kw)
    logger.info("estimate_zero2_model_states_mem_needs:\n" + plan.render())
    return plan


def estimate_zero3_model_states_mem_needs(total_params, num_devices=1,
                                          cpu_offload=False,
                                          cpu_offload_params=False,
                                          **kw) -> MemoryPlan:
    """Reference-API analog: ZeRO-3 model-state needs (optionally with
    optimizer and/or parameter offload)."""
    plan = plan_training(total_params, zero_stage=3, dp=num_devices,
                         offload_optimizer=cpu_offload,
                         offload_param=cpu_offload_params, **kw)
    logger.info("estimate_zero3_model_states_mem_needs:\n" + plan.render())
    return plan


def _expert_param_count(params, shardings) -> int:
    """Parameters (elements, not bytes) whose sharding spec names the
    `expert` axis — the slice `plan_training` prices per `ep_size`."""
    import jax
    import numpy as np
    try:
        leaves = jax.tree_util.tree_leaves(params)
        shards = jax.tree_util.tree_leaves(shardings)
        if len(leaves) != len(shards):
            return 0
    except Exception:
        return 0

    def mentions_expert(sh):
        spec = getattr(sh, "spec", None) or ()
        for entry in spec:
            names = entry if isinstance(entry, tuple) else (entry,)
            if "expert" in names:
                return True
        return False

    return sum(int(np.prod(p.shape)) for p, s in zip(leaves, shards)
               if mentions_expert(s))


def plan_training_from_engine(engine, capacity_bytes=0,
                              temp_bytes=0) -> MemoryPlan:
    """Build the training plan from a live engine's config + mesh — the
    preflight path and the OOM-dump "planner delta" source. Pass the
    measured train-step temp (`program_temp_bytes`) when available: in
    training the activations ARE the temp, the dominant OOM term."""
    from deepspeed_tpu.utils.tree import tree_num_params
    n = tree_num_params(engine.state.params)
    cfg = engine.config
    axes = dict(zip(engine.mesh.axis_names, engine.mesh.devices.shape))
    dp = int(axes.get("data", 1)) * int(axes.get("zero", 1))
    tp = int(axes.get("tensor", 1))
    ep = int(axes.get("expert", 1))
    n_exp = _expert_param_count(engine.state.params, engine.param_shardings)
    z = cfg.zero_optimization
    off_o = z.offload_optimizer is not None and \
        z.offload_optimizer.device in ("cpu", "nvme")
    off_p = z.offload_param is not None and \
        z.offload_param.device in ("cpu", "nvme")
    return plan_training(
        n, zero_stage=int(z.stage), dp=dp, tp=tp,
        dtype=getattr(engine, "compute_dtype", "float32"),
        master_weights=engine.state.master is not None,
        grad_accum_dtype=cfg.data_types.grad_accum_dtype,
        offload_optimizer=off_o, offload_param=off_p,
        ep_size=ep, n_expert_params=n_exp,
        temp_bytes=temp_bytes, capacity_bytes=capacity_bytes)


def plan_training_from_infinity(engine, capacity_bytes=0,
                                temp_bytes=0) -> MemoryPlan:
    """Training plan priced from a LIVE InfinityEngine — every model-state
    byte measured, none estimated:

      host   params  = the `LayerParamStore`'s exact bytes (layer_bytes ×
                       num_layers — the byte-identity the offload tests
                       assert);
      host   master  = the fp32 masters held by the per-layer +
                       resident `HostOffloadOptimizer`s;
      host   optim   = their moments (exp_avg / exp_avg_sq), whether
                       RAM-held or NVMe-swapped;
      device params          = the resident leaves (embed/norms/head);
      device param_staging   = the async staging window — lookahead+1
                               layers of bit16 weights in rotation
                               (`LayerStreamer.depth` × layer_bytes, the
                               streamer's peak_live_layers bound).

    Boundary activations ([L+1, B, T, D] — the dominant device term at
    large batch) live in `temp_bytes`, measured or margin, matching the
    reference estimators' model-states-only convention."""
    import numpy as np
    host: Dict[str, int] = {}
    dev: Dict[str, int] = {}
    store = engine.store
    host["params"] = int(store.host_bytes)
    masters = 0
    optim = 0
    for opt in list(engine.layer_opts) + [engine.resident_opt]:
        masters += sum(int(m.nbytes) for m in opt.master)
        for moments in (opt.exp_avg, opt.exp_avg_sq):
            if moments:
                optim += sum(int(m.nbytes) for m in moments)
        if getattr(opt, "nvme", None) is not None:
            # NVMe-swapped moments: priced from the swapper's metadata —
            # they stream through host RAM per step
            optim += sum(int(np.prod(s)) * np.dtype(d).itemsize
                         for s, d in opt.nvme.meta.values())
    host["master"] = masters
    host["optim"] = optim
    dev["params"] = tree_bytes(engine.resident)
    dev["param_staging"] = engine.streamer.depth * store.layer_bytes
    notes = [
        "priced from the live tier: host params are byte-identical to the "
        "LayerParamStore; param_staging is the lookahead+1 async staging "
        "window (peak_live_layers bound)",
        "boundary activations / vjp workspace live in temp_bytes "
        "(measured or margin)"]
    return MemoryPlan("train", dev, host, int(temp_bytes),
                      int(capacity_bytes), notes)


def kv_cache_is_quantized(kv_cache_dtype) -> bool:
    """True for the int8 quantized pool layout (payload + f32 group
    scales). Name-matched so the planner half stays jax-free; EXACT match
    — 'uint8' is a plain (scale-less) pool, not the quantized layout, and
    a substring test would make the planner price scales the scheduler
    never allocates."""
    name = kv_cache_dtype if isinstance(kv_cache_dtype, str) \
        else str(getattr(kv_cache_dtype, "name", kv_cache_dtype))
    return name.strip().lower() == "int8"


def serving_pool_bytes(*, n_layer, n_kv_head, head_dim, kv_block_size,
                       num_kv_blocks, kv_cache_dtype="bfloat16",
                       kv_group_size=0) -> int:
    """Bytes of a paged KV pool: K and V, each
    ``[L, num_blocks, Hkv, block, hd]`` (the `init_paged_pool` layout) at
    the pool dtype's itemsize. The int8 quantized pool additionally
    carries K and V scale leaves ``[L, N, Hkv, block, hd//g]`` f32
    (`kv_group_size` g, 0 = head_dim) — the scales-overhead term is what
    keeps the planner's byte identity with `init_paged_kv_pool` exact, and
    what caps the capacity win below a clean 2x (4/g extra bytes per
    element: g=128 -> 1.94x, g=head_dim=64 -> 1.88x)."""
    cells = (2 * int(n_layer) * int(num_kv_blocks) * int(n_kv_head)
             * int(kv_block_size))
    total = cells * int(head_dim) * dtype_bytes(kv_cache_dtype)
    if kv_cache_is_quantized(kv_cache_dtype):
        g = int(kv_group_size) or int(head_dim)
        total += cells * (int(head_dim) // g) * 4
    return total


def plan_serving(*, n_layer, n_kv_head, head_dim, kv_block_size,
                 num_kv_blocks, kv_cache_dtype="bfloat16", kv_group_size=0,
                 n_params=0, param_dtype="bfloat16", params_bytes=None,
                 tp=1, sequence_parallel=1, draft=None, temp_bytes=0,
                 capacity_bytes=0) -> MemoryPlan:
    """Serving-resident memory prediction: weights + the paged KV pool
    (+ the spec-decode draft mirror, which shares num_kv_blocks/block_size
    with the target by construction). `draft` is a dict with the draft
    model's `n_layer`/`n_kv_head`/`head_dim` and `n_params` (or
    `params_bytes`). `temp_bytes` carries the compiled-step temp (measured
    via `aot_memory_analysis`, or a margin) — decode/prefill temps are
    small next to the pool, but headroom claims should include them.

    `sequence_parallel` > 1 prices the SEQUENCE-SHARDED pool
    (`inference/sequence_span.py`): `num_kv_blocks` stays the GLOBAL block
    count, the pool's physical-block axis spans sp chips, so the per-chip
    kv_pool claim — the number this per-device plan judges against
    capacity — is total/sp. Weights replicate across the sequence axis
    (only tp divides them), so `params` is unchanged."""
    tp = max(1, int(tp))
    sp = max(1, int(sequence_parallel))
    dev: Dict[str, int] = {}
    notes: List[str] = []
    if params_bytes is None:
        params_bytes = int(n_params) * dtype_bytes(param_dtype)
    dev["params"] = int(params_bytes) // tp
    dev["kv_pool"] = serving_pool_bytes(
        n_layer=n_layer, n_kv_head=n_kv_head, head_dim=head_dim,
        kv_block_size=kv_block_size, num_kv_blocks=num_kv_blocks,
        kv_cache_dtype=kv_cache_dtype, kv_group_size=kv_group_size) // sp
    if sp > 1:
        notes.append(f"sequence-sharded pool (sequence_parallel={sp}): "
                     f"block tables span the `sequence` axis — per-chip "
                     f"KV bytes are 1/{sp} of the global pool")
    if kv_cache_is_quantized(kv_cache_dtype):
        notes.append("int8 KV pool: payload bytes + f32 per-group scales "
                     f"(group {int(kv_group_size) or int(head_dim)})")
    if draft:
        dpb = draft.get("params_bytes")
        if dpb is None:
            dpb = int(draft.get("n_params", 0)) * \
                dtype_bytes(draft.get("param_dtype", param_dtype))
        dev["draft_params"] = int(dpb) // tp
        dev["draft_pool"] = serving_pool_bytes(
            n_layer=draft["n_layer"], n_kv_head=draft["n_kv_head"],
            head_dim=draft["head_dim"], kv_block_size=kv_block_size,
            num_kv_blocks=num_kv_blocks,
            kv_cache_dtype=draft.get("kv_cache_dtype", kv_cache_dtype),
            kv_group_size=draft.get("kv_group_size", 0)) // sp
        notes.append("draft mirror shares the target's num_kv_blocks/"
                     "block_size (indexed by the same block tables)")
    notes.append("prefix-cached blocks live INSIDE kv_pool (a view, "
                 "not additive)")
    return MemoryPlan("serving", dev, {}, int(temp_bytes),
                      int(capacity_bytes), notes)


def max_kv_blocks(capacity_bytes, *, n_layer, n_kv_head, head_dim,
                  kv_block_size, kv_cache_dtype="bfloat16", kv_group_size=0,
                  params_bytes=0, temp_bytes=0, sequence_parallel=1,
                  draft=None) -> int:
    """The inverse question serving deployment actually asks: the largest
    `num_kv_blocks` that fits `capacity_bytes` next to the weights (and
    the draft mirror, whose pool grows block-for-block with the target's).
    An int8 `kv_cache_dtype` prices each block at payload + scales
    (`serving_pool_bytes`), so the same budget answers ~2x the blocks —
    2/(1 + 4/g) of bf16's, exactly.
    `sequence_parallel` > 1: `capacity_bytes` is PER CHIP but the answer
    stays the GLOBAL block count of the sequence-sharded pool. Shards hold
    WHOLE blocks (the pool is sp equal shard ranges), so the answer is
    (blocks-per-shard that fit one chip) × sp — exactly sp× the flat
    answer, never overfilling a shard with a fractional-block credit.
    Remember per-shard local block 0 is reserved as trash: usable capacity
    is the returned value minus `sequence_parallel` blocks."""
    sp = max(1, int(sequence_parallel))
    per_block = serving_pool_bytes(
        n_layer=n_layer, n_kv_head=n_kv_head, head_dim=head_dim,
        kv_block_size=kv_block_size, num_kv_blocks=1,
        kv_cache_dtype=kv_cache_dtype, kv_group_size=kv_group_size)
    fixed = int(params_bytes) + int(temp_bytes)
    if draft:
        dpb = draft.get("params_bytes")
        if dpb is None:
            dpb = int(draft.get("n_params", 0)) * \
                dtype_bytes(draft.get("param_dtype", kv_cache_dtype))
        fixed += int(dpb)
        per_block += serving_pool_bytes(
            n_layer=draft["n_layer"], n_kv_head=draft["n_kv_head"],
            head_dim=draft["head_dim"], kv_block_size=kv_block_size,
            num_kv_blocks=1,
            kv_cache_dtype=draft.get("kv_cache_dtype", kv_cache_dtype),
            kv_group_size=draft.get("kv_group_size", 0))
    # shards hold WHOLE blocks: one chip fits free//per_block of them, and
    # the global sequence-sharded pool is sp such shard ranges (sp=1: flat)
    free = int(capacity_bytes) - fixed
    return max(0, (free // max(1, per_block)) * sp)


def plan_serving_prealloc(spec, *, num_kv_blocks, kv_block_size,
                          kv_cache_dtype, kv_group_size=0, params=None,
                          draft_spec=None, param_dtype=None, temp_bytes=0,
                          capacity_bytes=0) -> MemoryPlan:
    """Serving plan BEFORE any pool allocation: pool bytes come from
    `jax.eval_shape` over the spec's `init_paged_pool` (no device memory
    is touched), so a predicted-OOM config can warn/refuse ahead of the
    `device_put` that would crash a real chip with a raw
    RESOURCE_EXHAUSTED. An int8 `kv_cache_dtype` threads `kv_group_size`
    through to the quantized-pool contract, so the scale leaves are in the
    shapes (and therefore in the prediction) too. `param_dtype` mirrors
    the drafter's cast (draft params are re-cast to the engine dtype when
    materialized)."""
    import jax
    import jax.numpy as jnp

    def pool_shape_bytes(s):
        if kv_cache_is_quantized(kv_cache_dtype):
            build = lambda: s.init_paged_pool(int(num_kv_blocks),
                                              int(kv_block_size),
                                              jnp.int8, int(kv_group_size))
        else:
            build = lambda: s.init_paged_pool(int(num_kv_blocks),
                                              int(kv_block_size),
                                              jnp.dtype(kv_cache_dtype))
        try:
            return tree_bytes(jax.eval_shape(build))
        except TypeError as e:
            # a 3-arg legacy init_paged_pool asked to build the int8 pool:
            # surface the contract instead of a bare arity error (the
            # scheduler raises the same pointer at real allocation time)
            raise ValueError(
                f"init_paged_pool of spec "
                f"'{getattr(s, 'name', '?')}' does not implement the "
                f"quantized-pool contract (4-arg form with kv_group_size; "
                f"init_paged_kv_pool in models/gpt.py is the reference): "
                f"{e}") from e

    dev = {"params": tree_bytes(params),
           "kv_pool": pool_shape_bytes(spec)}
    notes = ["pre-allocation plan: pool bytes via jax.eval_shape — no "
             "device memory touched"]
    if draft_spec is not None:
        dparams = getattr(draft_spec, "params", None)
        if dparams is not None and param_dtype is not None:
            from deepspeed_tpu.utils.tree import tree_cast
            dparams = jax.eval_shape(lambda: tree_cast(dparams, param_dtype))
        dev["draft_params"] = tree_bytes(dparams)
        dev["draft_pool"] = pool_shape_bytes(draft_spec)
        notes.append("draft mirror shares the target's num_kv_blocks/"
                     "block_size (indexed by the same block tables)")
    notes.append("prefix-cached blocks live INSIDE kv_pool (a view, "
                 "not additive)")
    return MemoryPlan("serving", dev, {}, int(temp_bytes),
                      int(capacity_bytes), notes)


def preflight_check(plan: MemoryPlan, refuse=False) -> MemoryPlan:
    """Judge a plan against its capacity: logs a warning on predicted OOM,
    or raises `PredictedOOMError` with the full plan table when `refuse`.
    A plan without a known capacity passes silently (nothing to judge)."""
    if plan.fits is False:
        msg = (f"memscope preflight: predicted OOM — "
               f"{fmt_bytes(plan.predicted_peak_bytes)} predicted vs "
               f"{fmt_bytes(plan.capacity_bytes)} capacity\n{plan.render()}")
        if refuse:
            raise PredictedOOMError(msg)
        logger.warning(msg)
    return plan


# ----------------------------------------------------------------------
# OOM detection
# ----------------------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Resource exhausted",
                "Out of memory", "out of memory",
                "Failed to allocate")


def is_resource_exhausted(exc) -> bool:
    """True when `exc` (or anything on its cause/context chain) looks like
    a device allocator failure. String-matched on purpose: the concrete
    exception type varies across jaxlib versions and backends
    (XlaRuntimeError today), but every runtime spells the status code."""
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        text = f"{type(exc).__name__}: {exc}"
        if any(m in text for m in _OOM_MARKERS):
            return True
        exc = exc.__cause__ or exc.__context__
    return False


# ----------------------------------------------------------------------
# the live ledger
# ----------------------------------------------------------------------


class _MemScopeBase:
    """Shared ledger machinery: category attribution, lazy per-program
    `memory_analysis`, gauge publishing, preflight, and the OOM dump."""

    subsystem = "?"

    def __init__(self, telemetry, flightrec_fn=None):
        self.telemetry = telemetry
        cfg = getattr(telemetry, "config", None)
        self.capacity_override = int(
            getattr(cfg, "memscope_capacity_bytes", 0) or 0)
        self.analyze_programs = bool(getattr(cfg, "memscope_programs", True))
        self._out_dir = str(getattr(cfg, "output_path", "telemetry")
                            or "telemetry")
        self._flightrec_fn = flightrec_fn or (lambda: None)
        self._programs: Optional[Dict[str, Dict[str, int]]] = None
        self.last_plan: Optional[MemoryPlan] = None
        self.oom_dumps = 0

    # -- subclass surface ----------------------------------------------

    def _categories(self) -> Tuple[Dict[str, int], Dict[str, int]]:
        """(attributed, informational) category dicts; informational
        entries (e.g. prefix_cached_bytes — a view of the pool) appear in
        the snapshot but never in the attribution sum."""
        raise NotImplementedError

    def _program_args(self) -> Iterable[Tuple[str, Any, tuple]]:
        """(name, jitted_fn, example_args) per persistent program."""
        return ()

    def plan(self) -> MemoryPlan:
        raise NotImplementedError

    # -- programs -------------------------------------------------------

    def program_memory(self) -> Dict[str, Dict[str, int]]:
        """Per-program `memory_analysis` numbers, computed lazily ONCE
        (one AOT compile per program — jit call caches untouched)."""
        if self._programs is None:
            out = {}
            if self.analyze_programs:
                for name, fn, args in self._program_args():
                    ma = aot_memory_analysis(fn, *args)
                    if ma:
                        out[name] = ma
            self._programs = out
        return self._programs

    def program_temp_bytes(self) -> int:
        """The live-at-once workspace claim: programs run one at a time,
        so the MAX temp across them is what must fit next to residents."""
        progs = self._programs if self._programs is not None else {}
        return max((p.get("temp_bytes", 0) for p in progs.values()),
                   default=0)

    # -- the ledger -----------------------------------------------------

    def capacity_bytes(self) -> int:
        if self.capacity_override:
            return self.capacity_override
        return int(device_memory_stats().get("bytes_limit", 0) or 0)

    def snapshot(self, programs: Optional[bool] = None) -> Dict[str, Any]:
        """The ledger: attributed categories, program temp, allocator
        watermarks, capacity, and the unattributed residual. `programs`
        overrides the lazy `memory_analysis` pass (False inside failure
        paths — never compile while dying)."""
        if programs is None:
            programs = self.analyze_programs
        if programs:
            self.program_memory()
        cats, info = self._categories()
        temp = self.program_temp_bytes()
        stats = device_memory_stats()
        in_use = int(stats.get("bytes_in_use", 0) or 0)
        peak = int(stats.get("peak_bytes_in_use", 0) or 0)
        cap = self.capacity_override or \
            int(stats.get("bytes_limit", 0) or 0)
        attributed = int(sum(cats.values())) + temp
        out: Dict[str, Any] = {"subsystem": self.subsystem}
        out.update(cats)
        out.update(info)
        out["program_temp_bytes"] = temp
        out["bytes_in_use"] = in_use
        out["peak_bytes"] = peak
        out["capacity_bytes"] = cap
        out["attributed_bytes"] = attributed
        # honest residual: what the allocator holds that the ledger cannot
        # name (only computable where allocator stats exist)
        out["unattributed_bytes"] = max(0, in_use - attributed) if in_use \
            else 0
        if cap:
            resident = in_use if in_use else attributed
            out["headroom_frac"] = max(0.0, 1.0 - resident / cap)
        return out

    def headroom_frac(self) -> Optional[float]:
        """Fraction of capacity still free — the PressureController's
        optional signal. None when no capacity is known (signal omitted,
        the ladder falls back to its other signals). Derived from
        `snapshot()` so the resident/headroom formula lives in one place;
        `programs=False` keeps the signal path compile-free."""
        return self.snapshot(programs=False).get("headroom_frac")

    def publish(self):
        """Set the `mem/*` gauges from a fresh snapshot (names enumerated
        in LEDGER_GAUGES for the catalog lint)."""
        t = self.telemetry
        if t is None or not getattr(t, "enabled", False):
            return
        for k, v in self.snapshot().items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            t.set_gauge(f"mem/{k}", v)

    # -- preflight ------------------------------------------------------

    def preflight(self, mode="warn") -> Optional[MemoryPlan]:
        """Run the planner against this subsystem's live configuration.
        `mode`: "off" | "warn" | "refuse" (the `memscope_preflight`
        knob)."""
        if mode == "off":
            return None
        try:
            plan = dataclasses.replace(self.plan(),
                                       capacity_bytes=self.capacity_bytes())
        except Exception as e:
            logger.warning(f"memscope preflight unavailable: {e}")
            return None
        self.last_plan = plan
        return preflight_check(plan, refuse=(mode == "refuse"))

    # -- OOM forensics --------------------------------------------------

    def on_step_error(self, exc) -> Optional[str]:
        """Dispatch-boundary hook: dump forensics iff `exc` is a device
        allocator failure. Returns the dump path (None otherwise). Never
        raises — this runs inside an exception handler that must re-raise
        the ORIGINAL error."""
        try:
            if is_resource_exhausted(exc):
                return self.oom_dump(exc)
        except Exception:
            pass
        return None

    def oom_dump(self, exc) -> Optional[str]:
        """The OOM black box: ledger + planner delta + flight-recorder
        ring to `<out>/<subsystem>.memscope.oom.NNN.json`. Also fires the
        flight recorder's own dump when it is enabled, so the standard
        PR 8 post-mortem artifact exists alongside."""
        try:
            snap = self.snapshot(programs=False)    # no compiles while dying
            try:
                # a fresh plan carries the measured program temp (when the
                # lazy analysis already ran) — tighter than the pre-flight
                # plan, whose temp was necessarily 0
                plan = dataclasses.replace(
                    self.plan(), capacity_bytes=self.capacity_bytes())
            except Exception:
                plan = self.last_plan
            delta = None
            if plan is not None:
                # the line that says whether this OOM was FORESEEABLE:
                # bytes the allocator holds beyond what the plan predicted
                observed = snap["bytes_in_use"] or snap["attributed_bytes"]
                delta = {"predicted_peak_bytes": plan.predicted_peak_bytes,
                         "observed_bytes": observed,
                         "unpredicted_bytes":
                             observed - plan.predicted_peak_bytes,
                         "fits_predicted": plan.fits}
            rec = self._flightrec_fn()
            events = rec.events() if rec is not None and \
                getattr(rec, "enabled", False) else []
            os.makedirs(self._out_dir, exist_ok=True)
            prefix = f"{self.subsystem}.memscope.oom."
            n = self.oom_dumps
            for name in os.listdir(self._out_dir):
                if name.startswith(prefix) and name.endswith(".json"):
                    try:
                        n = max(n, int(name[len(prefix):-5]) + 1)
                    except ValueError:
                        continue
            path = os.path.join(self._out_dir, f"{prefix}{n:03d}.json")
            self.oom_dumps = n + 1
            with open(path, "w") as f:
                json.dump({"reason": f"{type(exc).__name__}: {exc}",
                           "time": time.time(),
                           "subsystem": self.subsystem,
                           "ledger": snap,
                           "plan": plan.to_dict() if plan else None,
                           "plan_delta": delta,
                           "flight_events": events}, f, indent=1,
                          default=str)
            if rec is not None and getattr(rec, "enabled", False):
                rec.dump(f"RESOURCE_EXHAUSTED: {exc}",
                         state={"ledger": snap,
                                "plan_delta": delta})
            logger.warning(f"memscope: OOM forensics dumped to {path}")
            return path
        except Exception as e:
            logger.warning(f"memscope: OOM dump failed ({e})")
            return None


class ServingMemScope(_MemScopeBase):
    """The serving engine's ledger: weights, paged KV pool, prefix-cached
    carve-out, draft mirror, and the three persistent programs' temps."""

    subsystem = "serving"

    def __init__(self, serving):
        super().__init__(serving.telemetry,
                         flightrec_fn=lambda: serving.flightrec)
        self.serving = serving
        # static footprints, measured once from the live trees
        self.params_bytes = tree_bytes(serving.engine.params)
        self.pool_bytes = tree_bytes(serving.pool)
        # sequence-spanning pools shard the physical-block axis over
        # `span_shards` chips; an engine built over a SpanKVPool mirrors
        # the pool's span_shards attr here (the ledger wire —
        # inference/sequence_span.py SpanKVPool docstring); 1 = flat pool
        self.span_shards = max(1, int(getattr(serving, "span_shards", 1)))
        self.block_bytes = self.pool_bytes // max(1,
                                                  serving.allocator.num_blocks)
        dr = serving.drafter
        self.draft_params_bytes = tree_bytes(getattr(dr, "params", None)) \
            if dr is not None else 0
        self.draft_pool_bytes = tree_bytes(getattr(dr, "pool", None)) \
            if dr is not None else 0
        # streamed (offloaded-weights) mode: params_bytes above priced only
        # the RESIDENT tree (engine.params); the staged layer window is a
        # live device claim of its own, the host store an informational one
        self._streamed_engine = serving.engine \
            if getattr(serving, "streamed", False) else None

    def _categories(self):
        cats = {"params_bytes": self.params_bytes,
                "kv_pool_bytes": self.pool_bytes}
        if self.draft_params_bytes or self.draft_pool_bytes:
            cats["draft_params_bytes"] = self.draft_params_bytes
            cats["draft_pool_bytes"] = self.draft_pool_bytes
        eng = self._streamed_engine
        if eng is not None:
            cats["offload_staged_bytes"] = \
                len(eng.streamer._live) * eng.store.layer_bytes
        info = {
            # per-sequence-shard residency: equals kv_pool_bytes for the
            # flat pool; 1/sp of it when the pool spans the sequence axis —
            # the live-ledger counterpart of plan_serving's
            # sequence_parallel pricing. Informational (a per-chip VIEW of
            # kv_pool_bytes, never added to the attribution sum).
            "kv_pool_per_chip_bytes": self.pool_bytes // self.span_shards,
        }
        if eng is not None:
            # host/disk residency of the streamed weights — informational
            # (not device memory), the live counterpart of the planner's
            # host column
            info["offload_host_bytes"] = eng.store.host_bytes
        pc = self.serving.prefix_cache
        if pc is not None:
            # a VIEW of kv_pool (blocks the cache holds matchable), never
            # added to the attribution sum
            info["prefix_cached_bytes"] = int(pc.num_cached) * \
                self.block_bytes
        return cats, info

    def _program_args(self):
        import numpy as np
        s = self.serving
        if getattr(s, "streamed", False):
            # streamed (offloaded-weights) mode: the step "programs" are
            # host loops over per-layer jits — no single whole-step
            # executable exists to memory_analyze; the pool + resident
            # categories (and the staging window, priced by the planner)
            # still cover the residents
            return
        params, pool, rng = s.engine.params, s.pool, s._rng
        S, chunk = s.max_slots, s.chunk

        def i32(shape):
            return np.zeros(shape, np.int32)

        yield "decode_step", s._decode_step, \
            (params, i32((S,)), i32((S,)), pool, np.asarray(s.tables), rng)
        yield "prefill_step", s._prefill_step, \
            (params, i32((1, chunk)), i32((1,)), i32((1,)), pool,
             np.asarray(s.tables[:1]), rng)
        if s._verify_step is not None:
            yield "verify_step", s._verify_step, \
                (params, i32((S, s.draft_k + 1)), i32((S,)), pool,
                 np.asarray(s.tables), rng)

    @staticmethod
    def _pool_geometry(pool):
        """(payload leaf, kv_group_size) of a pool tree: the k payload is
        ``[L, N, Hkv, block, hd]`` by the `init_paged_pool` contract, and
        the int8 layout's `k_scale` leaf reveals the scale group."""
        import jax
        leaf = pool["k"] if isinstance(pool, dict) and "k" in pool \
            else jax.tree_util.tree_leaves(pool)[0]
        g = 0
        if isinstance(pool, dict) and "k_scale" in pool:
            g = int(leaf.shape[-1]) // int(pool["k_scale"].shape[-1])
        return leaf, g

    def plan(self) -> MemoryPlan:
        """Reconstruct the pre-flight prediction from the live pool
        geometry (payload + scale-group, see `_pool_geometry`) — the OOM
        dump's planner-delta source."""
        leaf, g = self._pool_geometry(self.serving.pool)
        L, N, Hkv, B, hd = leaf.shape
        draft = None
        if self.serving.drafter is not None and self.draft_pool_bytes:
            dleaf, dg = self._pool_geometry(self.serving.drafter.pool)
            draft = {"n_layer": dleaf.shape[0], "n_kv_head": dleaf.shape[2],
                     "head_dim": dleaf.shape[4],
                     "params_bytes": self.draft_params_bytes,
                     "kv_cache_dtype": dleaf.dtype, "kv_group_size": dg}
        params_bytes = self.params_bytes
        eng = self._streamed_engine
        if eng is not None:
            # streamed weights: the device claim is resident leaves + the
            # staging window (lookahead+1 layers), byte-identical to the
            # live LayerParamStore's layer_bytes
            params_bytes += eng.streamer.depth * eng.store.layer_bytes
        return plan_serving(
            n_layer=L, n_kv_head=Hkv, head_dim=hd, kv_block_size=B,
            num_kv_blocks=N, kv_cache_dtype=leaf.dtype, kv_group_size=g,
            params_bytes=params_bytes, draft=draft,
            temp_bytes=self.program_temp_bytes(),
            capacity_bytes=self.capacity_bytes())


class TrainMemScope(_MemScopeBase):
    """The training engine's ledger: compute params, fp32 master,
    optimizer state, and the compiled train step's temp (the activations'
    true home — measured once a batch shape is known)."""

    subsystem = "train"

    def __init__(self, engine):
        super().__init__(engine.telemetry,
                         flightrec_fn=lambda: engine.telemetry.flightrec)
        self.engine = engine
        self._batch_example = None     # abstract shapes only — holding a
                                       # real batch would pin its memory

    def _categories(self):
        st = self.engine.state
        info = {}
        if isinstance(st.params, dict) and "moe" in st.params:
            # a VIEW of params_bytes (the expert-weights slice the planner
            # prices per ep_size), never added to the attribution sum
            info["moe_expert_params_bytes"] = tree_bytes(st.params["moe"])
        return ({"params_bytes": tree_bytes(st.params),
                 "master_bytes": tree_bytes(st.master),
                 "opt_state_bytes": tree_bytes(st.opt_state)}, info)

    def _program_args(self):
        if self._batch_example is None or \
                getattr(self.engine, "_train_step", None) is None:
            return
        yield "train_step", self.engine._train_step, \
            (self.engine.state, self._batch_example)

    def publish(self, placed=None):
        if placed is not None and self._batch_example is None:
            import jax
            self._batch_example = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
                placed)
        super().publish()

    def plan(self) -> MemoryPlan:
        return plan_training_from_engine(self.engine,
                                         capacity_bytes=self.capacity_bytes(),
                                         temp_bytes=self.program_temp_bytes())


# ----------------------------------------------------------------------
# CLI: bin/dstpu_memscope
# ----------------------------------------------------------------------


def _parse_size(s) -> int:
    """'16G'/'16GiB'/'512M'/'512B'/'1.5e9'/'4096' -> bytes."""
    s = str(s).strip()
    units = {"k": 2**10, "m": 2**20, "g": 2**30, "t": 2**40}
    low = s.lower()
    for suffix in ("ib", "b", ""):
        for u, mult in units.items():
            if low.endswith(u + suffix) and low[:-len(u + suffix) or None]:
                try:
                    return int(float(low[:-(len(u + suffix))]) * mult)
                except ValueError:
                    pass
    # a bare byte suffix ('512B') has no unit prefix to match above
    if low.endswith("b") and low[:-1]:
        low = low[:-1]
    return int(float(low))


def _render_live(record, mem_only=True) -> str:
    metrics = record.get("metrics", {})
    rows = [(name, m) for name, m in sorted(metrics.items())
            if name.startswith("mem/") or not mem_only]
    lines = [f"memory ledger @ step {record.get('step')}"]
    if not rows:
        lines.append("  (no mem/* gauges in this snapshot — was "
                     "telemetry.memscope enabled?)")
    for name, m in rows:
        val = m.get("value", 0)
        if name.endswith("_frac"):
            lines.append(f"  {name:<28} {val:.3f}")
        else:
            lines.append(f"  {name:<28} {fmt_bytes(val)}")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="dstpu_memscope",
        description="HBM memory ledger viewer + pre-flight capacity "
                    "planner (deepspeed_tpu/telemetry/memscope.py).")
    ap.add_argument("path", nargs="?", default="telemetry",
                    help="telemetry dir or metrics .jsonl (live-ledger "
                         "mode; default ./telemetry)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--plan", choices=["train", "serving"],
                    help="run the pre-flight planner instead of reading "
                         "a live ledger")
    # shared planner knobs
    ap.add_argument("--params", type=float, default=0,
                    help="parameter count (e.g. 1.3e9)")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--capacity", default="0",
                    help="per-device HBM (e.g. 16G); 0 = just report bytes")
    ap.add_argument("--tp", type=int, default=1)
    # train planner
    ap.add_argument("--zero", type=int, default=0)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--no-master", action="store_true")
    ap.add_argument("--offload-optimizer", action="store_true")
    ap.add_argument("--offload-param", action="store_true")
    ap.add_argument("--offload-param-bytes", type=float, default=0,
                    help="exact host bytes of a live LayerParamStore "
                         "(overrides the n-params estimate for the host "
                         "params column — byte-identical planning)")
    ap.add_argument("--staging-layers", type=int, default=0,
                    help="offload staging-pool depth (lookahead+1): prices "
                         "the device-resident weight window next to the "
                         "host column")
    ap.add_argument("--layer-bytes", type=float, default=0,
                    help="bit16 bytes of ONE layer's weights (with "
                         "--staging-layers: the staging window's unit)")
    ap.add_argument("--num-experts", type=int, default=0,
                    help="MoE: total expert count (informational in the "
                         "plan notes; pair with --expert-params/--ep-size)")
    ap.add_argument("--ep-size", type=int, default=1,
                    help="MoE: expert-parallel axis size — expert weights "
                         "shard /ep_size per chip on top of the ZeRO/TP "
                         "denominators")
    ap.add_argument("--expert-params", type=float, default=0,
                    help="MoE: parameter count of ALL expert weights "
                         "(a slice of --params; e.g. 8 experts x 50e6)")
    # serving planner
    ap.add_argument("--layers", type=int, default=0)
    ap.add_argument("--kv-heads", type=int, default=0)
    ap.add_argument("--head-dim", type=int, default=0)
    ap.add_argument("--block-size", type=int, default=512)
    ap.add_argument("--blocks", type=int, default=0,
                    help="num_kv_blocks (serving plan)")
    ap.add_argument("--kv-dtype", default="bfloat16")
    ap.add_argument("--kv-group", type=int, default=0,
                    help="int8 pool scale-group size (0 = head_dim); "
                         "prices the f32 scales next to the payload")
    ap.add_argument("--fit", action="store_true",
                    help="serving: report the LARGEST num_kv_blocks that "
                         "fits --capacity instead of judging --blocks")
    ap.add_argument("--sp", type=int, default=1,
                    help="serving: sequence_parallel — price the sequence-"
                         "sharded pool (inference/sequence_span.py): "
                         "per-chip KV bytes are 1/sp of the global pool; "
                         "--fit answers the GLOBAL block count ~sp times "
                         "a single chip's")
    args = ap.parse_args(argv)
    try:
        capacity = _parse_size(args.capacity)
    except ValueError:
        print(f"dstpu_memscope: cannot parse --capacity {args.capacity!r} "
              f"(try '16G', '512MiB', or plain bytes)", file=sys.stderr)
        return 1

    if args.plan == "train":
        plan = plan_training(int(args.params), zero_stage=args.zero,
                             dp=args.dp, tp=args.tp, dtype=args.dtype,
                             master_weights=not args.no_master,
                             offload_optimizer=args.offload_optimizer,
                             offload_param=args.offload_param,
                             offload_param_bytes=(int(args.offload_param_bytes)
                                                  or None),
                             offload_staging_layers=args.staging_layers,
                             offload_layer_bytes=int(args.layer_bytes),
                             num_experts=args.num_experts,
                             ep_size=args.ep_size,
                             n_expert_params=int(args.expert_params),
                             capacity_bytes=capacity)
        print(json.dumps(plan.to_dict()) if args.json else plan.render())
        return 0 if plan.fits is not False else 2

    if args.plan == "serving":
        if not (args.layers and args.kv_heads and args.head_dim):
            print("dstpu_memscope: --plan serving needs --layers, "
                  "--kv-heads, --head-dim", file=sys.stderr)
            return 1
        if not args.fit and args.blocks <= 0:
            # without this a forgotten --blocks plans a zero-byte pool and
            # exits 0 with a FITS verdict — a trap for scripted gates
            print("dstpu_memscope: --plan serving needs --blocks "
                  "(num_kv_blocks), or --fit to solve for it",
                  file=sys.stderr)
            return 1
        params_bytes = int(args.params * dtype_bytes(args.dtype))
        if args.fit:
            if not capacity:
                print("dstpu_memscope: --fit needs --capacity",
                      file=sys.stderr)
                return 1
            per_dev_params = params_bytes // max(1, args.tp)
            blocks = max_kv_blocks(
                capacity, n_layer=args.layers, n_kv_head=args.kv_heads,
                head_dim=args.head_dim, kv_block_size=args.block_size,
                kv_cache_dtype=args.kv_dtype, kv_group_size=args.kv_group,
                params_bytes=per_dev_params,
                sequence_parallel=args.sp)
            # one trash block is reserved PER SHARD (the flat pool's
            # block 0; every sequence shard's local block 0 under --sp)
            sp = max(1, args.sp)
            usable = max(0, blocks - sp)
            out = {"max_kv_blocks": blocks,
                   "usable_blocks": usable,
                   "capacity_bytes": capacity,
                   "params_bytes": per_dev_params}
            print(json.dumps(out) if args.json else
                  f"largest num_kv_blocks that fits "
                  f"{fmt_bytes(capacity)}: {blocks} "
                  f"({usable} usable past the trash "
                  f"block{'s' if sp > 1 else ''})")
            return 0
        plan = plan_serving(
            n_layer=args.layers, n_kv_head=args.kv_heads,
            head_dim=args.head_dim, kv_block_size=args.block_size,
            num_kv_blocks=args.blocks, kv_cache_dtype=args.kv_dtype,
            kv_group_size=args.kv_group,
            params_bytes=params_bytes, tp=args.tp,
            sequence_parallel=args.sp, capacity_bytes=capacity)
        print(json.dumps(plan.to_dict()) if args.json else plan.render())
        return 0 if plan.fits is not False else 2

    # live-ledger mode: latest snapshot from the telemetry JSONL log
    from deepspeed_tpu.telemetry.cli import load_latest
    record = load_latest(args.path)
    if record is None:
        print(f"dstpu_memscope: no metrics log at {args.path!r}",
              file=sys.stderr)
        return 1
    if args.json:
        mem = {k: v for k, v in record.get("metrics", {}).items()
               if k.startswith("mem/")}
        print(json.dumps({"step": record.get("step"),
                          "time": record.get("time"), "metrics": mem}))
    else:
        print(_render_live(record))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
