"""Diffusion (stable-diffusion-style) model family — UNet / VAE / CLIP text.

Reference: `module_inject/containers/{clip,unet,vae}.py` + `csrc/spatial/`
(channels-last bias-add and fused groupnorm CUDA kernels) — DeepSpeed's
diffusers acceleration swaps HF modules for fused attention and channels-last
spatial kernels. The TPU-native counterpart:

  * NHWC layout throughout — channels-last IS the TPU-native conv layout, so
    the whole `csrc/spatial` kernel family collapses into XLA's fused
    conv+bias+activation emission;
  * attention (spatial self- and text cross-attention) is the same einsum
    formulation as the LLM zoo — one fused softmax program, bf16-friendly;
  * the denoise loop is a single `lax.scan` over timesteps: scheduler math,
    UNet, and classifier-free guidance compile into ONE XLA program (the
    reference replays per-step Python with cuda-graph capture to approximate
    this).

Blocks mirror the diffusers UNet2DConditionModel essentials: timestep
sinusoidal embedding + MLP, ResnetBlock2D, Transformer2D (self + cross +
geglu ff), down/upsample ladder with skips, mid block; VAE decoder ladder;
CLIP text encoder reusing the GPT block machinery (quick-gelu, causal).
"""

import dataclasses
import math
from functools import partial
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ----------------------------------------------------------------------
# primitives (NHWC)
# ----------------------------------------------------------------------


def conv2d(x, w, b=None, stride=1, padding=1):
    """x: [B,H,W,C_in], w: [kh,kw,C_in,C_out] (HWIO — TPU-native)."""
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if b is not None:
        out = out + b
    return out


def group_norm(x, scale, bias, groups=32, eps=1e-5):
    """NHWC group norm with fp32 statistics (the `csrc/spatial` fused-GN
    role — XLA fuses the normalize+affine+activation chain)."""
    B, H, W, C = x.shape
    g = min(groups, C)
    xg = x.reshape(B, H, W, g, C // g).astype(jnp.float32)
    mu = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    xg = (xg - mu) * jax.lax.rsqrt(var + eps)
    out = xg.reshape(B, H, W, C).astype(x.dtype)
    return out * scale + bias


def timestep_embedding(t, dim, max_period=10000.0):
    """Sinusoidal timestep embedding (diffusers get_timestep_embedding)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) *
                    jnp.arange(half, dtype=jnp.float32) / half)
    ang = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)


def _attn(q, k, v, heads):
    """[B, Nq, C] x [B, Nk, C] multi-head attention, fp32 softmax."""
    B, Nq, C = q.shape
    Nk = k.shape[1]
    hd = C // heads
    q = q.reshape(B, Nq, heads, hd)
    k = k.reshape(B, Nk, heads, hd)
    v = v.reshape(B, Nk, heads, hd)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits / math.sqrt(hd)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return out.reshape(B, Nq, C)


# ----------------------------------------------------------------------
# UNet blocks
# ----------------------------------------------------------------------


def resnet_block(x, temb, p, groups=32):
    """ResnetBlock2D: GN-silu-conv, +time proj, GN-silu-conv, skip."""
    h = group_norm(x, p["gn1_s"], p["gn1_b"], groups)
    h = conv2d(jax.nn.silu(h), p["conv1_w"], p["conv1_b"])
    if temb is not None:
        h = h + (jax.nn.silu(temb) @ p["temb_w"] + p["temb_b"])[:, None, None, :]
    h = group_norm(h, p["gn2_s"], p["gn2_b"], groups)
    h = conv2d(jax.nn.silu(h), p["conv2_w"], p["conv2_b"])
    if "skip_w" in p:
        x = conv2d(x, p["skip_w"], p["skip_b"], padding=0)
    return x + h


def transformer2d(x, context, p, heads, groups=32):
    """Spatial transformer: GN + proj_in, self-attn, cross-attn(context),
    geglu ff, proj_out + residual (diffusers BasicTransformerBlock)."""
    B, H, W, C = x.shape
    res = x
    h = group_norm(x, p["gn_s"], p["gn_b"], groups)
    h = (h.reshape(B, H * W, C) @ p["proj_in_w"]) + p["proj_in_b"]

    # self attention
    hn = _layer_norm(h, p["ln1_s"], p["ln1_b"])
    q = hn @ p["sa_q"]
    k = hn @ p["sa_k"]
    v = hn @ p["sa_v"]
    h = h + _attn(q, k, v, heads) @ p["sa_o_w"] + p["sa_o_b"]

    # cross attention over the text context [B, T, C_ctx]
    hn = _layer_norm(h, p["ln2_s"], p["ln2_b"])
    q = hn @ p["ca_q"]
    k = context @ p["ca_k"]
    v = context @ p["ca_v"]
    h = h + _attn(q, k, v, heads) @ p["ca_o_w"] + p["ca_o_b"]

    # geglu feed-forward
    hn = _layer_norm(h, p["ln3_s"], p["ln3_b"])
    up = hn @ p["ff_in_w"] + p["ff_in_b"]
    a, g = jnp.split(up, 2, axis=-1)
    h = h + (a * jax.nn.gelu(g)) @ p["ff_out_w"] + p["ff_out_b"]

    h = h @ p["proj_out_w"] + p["proj_out_b"]
    return res + h.reshape(B, H, W, C)


def _layer_norm(x, s, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * s + b


def downsample(x, p):
    return conv2d(x, p["w"], p["b"], stride=2)


def upsample(x, p):
    B, H, W, C = x.shape
    x = jax.image.resize(x, (B, 2 * H, 2 * W, C), method="nearest")
    return conv2d(x, p["w"], p["b"])


# ----------------------------------------------------------------------
# UNet2DCondition
# ----------------------------------------------------------------------


@dataclasses.dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_channels: Tuple[int, ...] = (64, 128)   # per resolution level
    layers_per_block: int = 1
    attn_levels: Tuple[int, ...] = (1,)           # levels with cross-attn
    heads: int = 4
    context_dim: int = 256                        # text-encoder width
    groups: int = 16
    dtype: Any = jnp.float32


def init_unet_params(cfg: UNetConfig, seed=0):
    rng = np.random.default_rng(seed)
    dt = cfg.dtype

    def nrm(*s, scale=0.05):
        return jnp.asarray(rng.normal(0, scale, s), dt)

    def zeros(*s):
        return jnp.zeros(s, dt)

    def ones(*s):
        return jnp.ones(s, dt)

    def resnet(cin, cout, tdim):
        p = {"gn1_s": ones(cin), "gn1_b": zeros(cin),
             "conv1_w": nrm(3, 3, cin, cout), "conv1_b": zeros(cout),
             "temb_w": nrm(tdim, cout), "temb_b": zeros(cout),
             "gn2_s": ones(cout), "gn2_b": zeros(cout),
             "conv2_w": nrm(3, 3, cout, cout), "conv2_b": zeros(cout)}
        if cin != cout:
            p["skip_w"] = nrm(1, 1, cin, cout)
            p["skip_b"] = zeros(cout)
        return p

    def xformer(c):
        ff = 4 * c
        return {"gn_s": ones(c), "gn_b": zeros(c),
                "proj_in_w": nrm(c, c), "proj_in_b": zeros(c),
                "ln1_s": ones(c), "ln1_b": zeros(c),
                "sa_q": nrm(c, c), "sa_k": nrm(c, c), "sa_v": nrm(c, c),
                "sa_o_w": nrm(c, c), "sa_o_b": zeros(c),
                "ln2_s": ones(c), "ln2_b": zeros(c),
                "ca_q": nrm(c, c), "ca_k": nrm(cfg.context_dim, c),
                "ca_v": nrm(cfg.context_dim, c),
                "ca_o_w": nrm(c, c), "ca_o_b": zeros(c),
                "ln3_s": ones(c), "ln3_b": zeros(c),
                "ff_in_w": nrm(c, 2 * ff), "ff_in_b": zeros(2 * ff),
                "ff_out_w": nrm(ff, c), "ff_out_b": zeros(c),
                "proj_out_w": nrm(c, c), "proj_out_b": zeros(c)}

    ch = cfg.block_channels
    tdim = 4 * ch[0]
    params = {
        "temb_w1": nrm(ch[0], tdim), "temb_b1": zeros(tdim),
        "temb_w2": nrm(tdim, tdim), "temb_b2": zeros(tdim),
        "conv_in_w": nrm(3, 3, cfg.in_channels, ch[0]),
        "conv_in_b": zeros(ch[0]),
        "down": [], "up": [],
        "gn_out_s": ones(ch[0]), "gn_out_b": zeros(ch[0]),
        "conv_out_w": nrm(3, 3, ch[0], cfg.out_channels),
        "conv_out_b": zeros(cfg.out_channels),
    }
    # down ladder
    cin = ch[0]
    for lvl, c in enumerate(ch):
        blocks = []
        for _ in range(cfg.layers_per_block):
            blk = {"res": resnet(cin, c, tdim)}
            if lvl in cfg.attn_levels:
                blk["attn"] = xformer(c)
            blocks.append(blk)
            cin = c
        level = {"blocks": blocks}
        if lvl < len(ch) - 1:
            level["down"] = {"w": nrm(3, 3, c, c), "b": zeros(c)}
        params["down"].append(level)
    # mid
    cm = ch[-1]
    params["mid"] = {"res1": resnet(cm, cm, tdim), "attn": xformer(cm),
                     "res2": resnet(cm, cm, tdim)}
    # up ladder (reverse, with skip concat channels)
    for lvl in reversed(range(len(ch))):
        c = ch[lvl]
        blocks = []
        for i in range(cfg.layers_per_block + 1):
            skip_c = ch[lvl] if i < cfg.layers_per_block else \
                ch[max(lvl - 1, 0)]
            blk = {"res": resnet(cin + skip_c, c, tdim)}
            if lvl in cfg.attn_levels:
                blk["attn"] = xformer(c)
            blocks.append(blk)
            cin = c
        level = {"blocks": blocks}
        if lvl > 0:
            level["up"] = {"w": nrm(3, 3, c, c), "b": zeros(c)}
        params["up"].append(level)
    return params


def unet_forward(params, x, t, context, cfg: UNetConfig):
    """x: [B,H,W,C_in] noisy latents, t: [B] timesteps, context: [B,T,ctx].
    Returns predicted noise [B,H,W,C_out]."""
    temb = timestep_embedding(t, cfg.block_channels[0]).astype(x.dtype)
    temb = jax.nn.silu(temb @ params["temb_w1"] + params["temb_b1"])
    temb = temb @ params["temb_w2"] + params["temb_b2"]

    h = conv2d(x, params["conv_in_w"], params["conv_in_b"])
    skips = [h]
    for lvl, level in enumerate(params["down"]):
        for blk in level["blocks"]:
            h = resnet_block(h, temb, blk["res"], cfg.groups)
            if "attn" in blk:
                h = transformer2d(h, context, blk["attn"], cfg.heads, cfg.groups)
            skips.append(h)
        if "down" in level:
            h = downsample(h, level["down"])
            skips.append(h)

    m = params["mid"]
    h = resnet_block(h, temb, m["res1"], cfg.groups)
    h = transformer2d(h, context, m["attn"], cfg.heads, cfg.groups)
    h = resnet_block(h, temb, m["res2"], cfg.groups)

    for i, level in enumerate(params["up"]):
        for blk in level["blocks"]:
            h = jnp.concatenate([h, skips.pop()], axis=-1)
            h = resnet_block(h, temb, blk["res"], cfg.groups)
            if "attn" in blk:
                h = transformer2d(h, context, blk["attn"], cfg.heads, cfg.groups)
        if "up" in level:
            h = upsample(h, level["up"])

    h = group_norm(h, params["gn_out_s"], params["gn_out_b"], cfg.groups)
    return conv2d(jax.nn.silu(h), params["conv_out_w"], params["conv_out_b"])


# ----------------------------------------------------------------------
# VAE decoder
# ----------------------------------------------------------------------


@dataclasses.dataclass
class VAEDecoderConfig:
    latent_channels: int = 4
    out_channels: int = 3
    block_channels: Tuple[int, ...] = (128, 64)   # high→low res order
    layers_per_block: int = 1
    groups: int = 16
    scaling_factor: float = 0.18215               # SD latent scale
    dtype: Any = jnp.float32


def init_vae_decoder_params(cfg: VAEDecoderConfig, seed=0):
    rng = np.random.default_rng(seed)
    dt = cfg.dtype
    nrm = lambda *s: jnp.asarray(rng.normal(0, 0.05, s), dt)
    zeros = lambda *s: jnp.zeros(s, dt)
    ones = lambda *s: jnp.ones(s, dt)

    def resnet(cin, cout):
        p = {"gn1_s": ones(cin), "gn1_b": zeros(cin),
             "conv1_w": nrm(3, 3, cin, cout), "conv1_b": zeros(cout),
             "gn2_s": ones(cout), "gn2_b": zeros(cout),
             "conv2_w": nrm(3, 3, cout, cout), "conv2_b": zeros(cout)}
        if cin != cout:
            p["skip_w"] = nrm(1, 1, cin, cout)
            p["skip_b"] = zeros(cout)
        return p

    ch = cfg.block_channels
    params = {"conv_in_w": nrm(3, 3, cfg.latent_channels, ch[0]),
              "conv_in_b": zeros(ch[0]),
              "mid": {"res1": resnet(ch[0], ch[0]), "res2": resnet(ch[0], ch[0])},
              "up": [],
              "gn_out_s": ones(ch[-1]), "gn_out_b": zeros(ch[-1]),
              "conv_out_w": nrm(3, 3, ch[-1], cfg.out_channels),
              "conv_out_b": zeros(cfg.out_channels)}
    cin = ch[0]
    for lvl, c in enumerate(ch):
        level = {"blocks": [resnet(cin if i == 0 else c, c)
                            for i in range(cfg.layers_per_block)]}
        cin = c
        if lvl < len(ch) - 1:
            level["upsample"] = {"w": nrm(3, 3, c, c), "b": zeros(c)}
        params["up"].append(level)
    return params


def vae_decode(params, z, cfg: VAEDecoderConfig):
    """z: [B,h,w,latent] → image [B,H,W,3] in [-1, 1]."""
    h = conv2d(z / cfg.scaling_factor, params["conv_in_w"], params["conv_in_b"])
    h = resnet_block(h, None, params["mid"]["res1"], cfg.groups)
    h = resnet_block(h, None, params["mid"]["res2"], cfg.groups)
    for level in params["up"]:
        for p in level["blocks"]:
            h = resnet_block(h, None, p, cfg.groups)
        if "upsample" in level:
            h = upsample(h, level["upsample"])
    h = group_norm(h, params["gn_out_s"], params["gn_out_b"], cfg.groups)
    return jnp.tanh(conv2d(jax.nn.silu(h), params["conv_out_w"],
                           params["conv_out_b"]))


# ----------------------------------------------------------------------
# CLIP text encoder — the GPT block machinery with quick-gelu
# (reference `containers/clip.py` maps CLIPEncoderLayer onto the fused GPT
# inference block; here the mapping is a GPTConfig)
# ----------------------------------------------------------------------


def clip_text_config(vocab_size=1000, width=256, layers=2, heads=4,
                     max_len=77, dtype=jnp.float32):
    from deepspeed_tpu.models.gpt import GPTConfig
    return GPTConfig(vocab_size=vocab_size, n_layer=layers, n_head=heads,
                     d_model=width, d_ff=4 * width, max_seq_len=max_len,
                     activation="quick_gelu", tie_embeddings=True,
                     dtype=dtype, remat=False)


def clip_text_encode(params, tokens, cfg):
    """CLIP text transformer: causal blocks + final LN; returns
    (hidden [B,T,D], pooled [B,D]) with pooling at the last token
    (CLIP pools at the EOS position; callers pass eos-terminated prompts)."""
    from deepspeed_tpu.models.gpt import _embed, _block, _norm
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    x = _embed(params, tokens, positions, cfg)

    def body(x, lp):
        return _block(x, lp, cfg=cfg, positions=positions), None

    x, _ = jax.lax.scan(body, x, params["blocks"])
    x = _norm(x, params["lnf_scale"], params.get("lnf_bias"),
              cfg.use_rmsnorm, cfg.norm_eps)
    return x, x[:, -1, :]


# ----------------------------------------------------------------------
# DDIM scheduler + txt2img pipeline (one compiled scan)
# ----------------------------------------------------------------------


@dataclasses.dataclass
class DDIMSchedule:
    num_train_timesteps: int = 1000
    beta_start: float = 0.00085
    beta_end: float = 0.012

    def alphas_cumprod(self):
        betas = jnp.linspace(self.beta_start**0.5, self.beta_end**0.5,
                             self.num_train_timesteps) ** 2
        return jnp.cumprod(1.0 - betas)


def ddim_step(eps, x, alpha_t, alpha_prev):
    """Deterministic DDIM update (eta=0)."""
    x0 = (x - jnp.sqrt(1 - alpha_t) * eps) / jnp.sqrt(alpha_t)
    return jnp.sqrt(alpha_prev) * x0 + jnp.sqrt(1 - alpha_prev) * eps


def make_txt2img(unet_params, unet_cfg: UNetConfig,
                 vae_params, vae_cfg: VAEDecoderConfig,
                 text_params, text_cfg,
                 schedule: DDIMSchedule = None, steps: int = 20,
                 guidance_scale: float = 7.5, latent_hw: int = 16):
    """Build a jitted (prompt_tokens, uncond_tokens, rng) -> images function.

    Classifier-free guidance batches cond+uncond through one UNet call; the
    whole denoise loop is a single lax.scan — scheduler constants are baked
    into the compiled program."""
    schedule = schedule or DDIMSchedule()
    acp = schedule.alphas_cumprod()
    ts = jnp.linspace(schedule.num_train_timesteps - 1, 0, steps).astype(jnp.int32)
    alphas = acp[ts]
    alphas_prev = jnp.concatenate([acp[ts[1:]], jnp.ones((1,))])

    def txt2img(prompt_tokens, uncond_tokens, rng):
        B = prompt_tokens.shape[0]
        ctx_c, _ = clip_text_encode(text_params, prompt_tokens, text_cfg)
        ctx_u, _ = clip_text_encode(text_params, uncond_tokens, text_cfg)
        context = jnp.concatenate([ctx_u, ctx_c], axis=0)   # [2B, T, D]
        x = jax.random.normal(
            rng, (B, latent_hw, latent_hw, unet_cfg.in_channels),
            unet_cfg.dtype)

        def body(x, sched):
            t, a_t, a_prev = sched
            xx = jnp.concatenate([x, x], axis=0)
            tt = jnp.full((2 * B,), t, jnp.int32)
            eps = unet_forward(unet_params, xx, tt, context, unet_cfg)
            eps_u, eps_c = jnp.split(eps, 2, axis=0)
            eps = eps_u + guidance_scale * (eps_c - eps_u)
            return ddim_step(eps, x, a_t, a_prev), None

        x, _ = jax.lax.scan(body, x, (ts, alphas, alphas_prev))
        return vae_decode(vae_params, x, vae_cfg)

    return jax.jit(txt2img)
