"""MoE-GPT — GPT with Mixture-of-Experts MLPs, training AND inference.

Reference: training MoE via `deepspeed/moe/layer.py:16` placed inside client
transformer MLPs, and MoE *inference* via the expert-parallel containers
(`ops/transformer/inference/moe_inference.py`, `inference/engine.py:260`
`_create_ep_parallel_group`).

TPU-native formulation: every `moe_freq`-th block's MLP is a GShard-style
expert layer. Training routes with masked static-capacity top-1 gating —
through the comm facade's instrumented all_to_all inside `shard_map` when a
mesh with expert parallelism is active (`parallel/moe.py`'s
`expert_parallel_moe`; dispatch bytes land in `comm/all_to_all_bytes`), and
through the dispatch-einsum + sharding-constraint fallback otherwise (XLA
emits the all-to-all pair). Capacity overflow masks tokens (no dynamic
shapes); drop/overflow counts surface as `moe/*` telemetry via the loss aux.

Inference routes **capacity-free**: every token goes to its argmax expert
with a one-hot combine (`_moe_mlp_nodrop`). That choice is deliberate — the
routing decision depends only on the token itself, never on batch
composition or chunk boundaries, which is exactly the invariance the paged
serving path needs for token-identical continuous batching (a prompt chunked
3 ways routes identically to the same prompt in one pass). Capacity is a
training-throughput construct; at serving granularity it only creates drops.
"""

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.mesh import (BATCH_AXES, EXPERT_AXIS, SEQ_AXIS,
                                     TENSOR_AXIS, get_mesh, has_mesh,
                                     shard_constraint)
from deepspeed_tpu.models.gpt import (GPTConfig, _attn_half, _block,
                                      _block_decode, _decode_attn_half, _embed,
                                      _lm_head, _norm, _paged_attn_half,
                                      _residual_mlp, gpt_cache_identity,
                                      init_gpt_params, init_kv_cache,
                                      init_paged_kv_pool, gpt_param_specs)
from deepspeed_tpu.parallel.moe import (can_use_expert_shard_map,
                                        expert_parallel_moe,
                                        gating_drop_stats, top1_gating)
from deepspeed_tpu.runtime.engine import ModelSpec


@dataclasses.dataclass
class MoEGPTConfig(GPTConfig):
    num_experts: int = 8
    moe_freq: int = 2                 # every moe_freq-th block is MoE (from block 1)
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    moe_aux_weight: float = 0.01
    moe_dispatch_wire: str = "none"   # WireTransform on the facade a2a pair

    def moe_layer_ids(self):
        return [i for i in range(self.n_layer) if i % self.moe_freq == 1]


def init_moe_gpt_params(cfg: MoEGPTConfig, seed: int = 0, dtype=jnp.float32):
    """Dense skeleton (stacked blocks, gpt.py layout) + per-MoE-layer expert
    weights {layer_id: {gate_w, w_up [E,D,F], w_down [E,F,D]}}."""
    params = init_gpt_params(cfg, seed=seed, dtype=dtype)
    rng = np.random.default_rng(seed + 7)
    D, F, E = cfg.d_model, cfg.d_ff, cfg.num_experts
    moe = {}
    for lid in cfg.moe_layer_ids():
        moe[str(lid)] = {
            "gate_w": jnp.asarray(rng.normal(0, 0.02, (D, E)), dtype),
            "w_up": jnp.asarray(rng.normal(0, 0.02, (E, D, F)), dtype),
            "b_up": jnp.zeros((E, F), dtype),
            "w_down": jnp.asarray(rng.normal(0, 0.02 / np.sqrt(2 * cfg.n_layer),
                                             (E, F, D)), dtype),
            "b_down": jnp.zeros((E, D), dtype),
        }
    params["moe"] = moe
    return params


def moe_gpt_param_specs(cfg: MoEGPTConfig):
    specs = gpt_param_specs(cfg)
    e, t = EXPERT_AXIS, TENSOR_AXIS
    moe_spec = {
        "gate_w": P(None, None),
        "w_up": P(e, None, t),
        "b_up": P(e, t),
        "w_down": P(e, t, None),
        "b_down": P(e, None),
    }
    specs["moe"] = {str(lid): dict(moe_spec) for lid in cfg.moe_layer_ids()}
    return specs


def _expert_ffn(xe, mp, cfg, constrain=True):
    """xe: [E, C, D] tokens per expert → [E, C, D]; batched expert FFN on the
    expert mesh axis. `constrain=False` for shard_map bodies (manual sharding
    forbids constraints — the expert dim is already local there)."""
    h = jnp.einsum("ecd,edf->ecf", xe, mp["w_up"]) + mp["b_up"][:, None, :]
    h = jax.nn.gelu(h) if cfg.activation == "gelu" else jax.nn.relu(h)
    if constrain:
        h = shard_constraint(h, EXPERT_AXIS, None, TENSOR_AXIS)
    return jnp.einsum("ecf,efd->ecd", h, mp["w_down"]) + mp["b_down"][:, None, :]


def _moe_mlp(x, mp, cfg: MoEGPTConfig, training=True, mesh=None):
    """x: [B, T, D] → (out, l_aux, drop_stats). Static-capacity top-1 routing.

    With a mesh that `can_use_expert_shard_map` accepts, dispatch/combine run
    inside shard_map with the facade's all_to_all pair (per-shard gating,
    local capacity); otherwise the GShard dispatch/combine einsums + expert
    sharding constraint (XLA inserts the a2a — invisible to facade stats).
    """
    B, T, D = x.shape
    E = cfg.num_experts
    cf = cfg.capacity_factor if training else cfg.eval_capacity_factor
    xf = x.reshape(B * T, D)

    if mesh is None and has_mesh():
        # lazy resolution: the engine builds the mesh after the ModelSpec, so
        # a loss traced under an active expert mesh picks up facade dispatch
        # automatically; can_use_expert_shard_map rejects unsuitable meshes
        mesh = get_mesh()
    if can_use_expert_shard_map(mesh, E, B * T):
        eparams = {k: mp[k] for k in ("w_up", "b_up", "w_down", "b_down")}
        out, l_aux, _counts, stats = expert_parallel_moe(
            xf, mp["gate_w"], eparams,
            lambda xe, p: _expert_ffn(xe, p, cfg, constrain=False), mesh,
            num_experts=E, capacity_factor=cf, min_capacity=cfg.min_capacity,
            dispatch_wire=cfg.moe_dispatch_wire)
        return out.reshape(B, T, D), l_aux, stats

    logits = (xf @ mp["gate_w"]).astype(jnp.float32)
    l_aux, dispatch, combine, counts = top1_gating(
        logits, capacity_factor=cf, min_capacity=cfg.min_capacity)
    stats = gating_drop_stats(dispatch, counts)
    # dispatch: [N, E, C] — einsum routes tokens to expert slots; the sharding
    # constraint on the expert dim makes XLA emit the a2a (reference _AllToAll)
    xe = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), xf)
    xe = shard_constraint(xe, EXPERT_AXIS, None, None)
    ye = _expert_ffn(xe, mp, cfg)
    out = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), ye)
    return out.reshape(B, T, D), l_aux, stats


def _moe_mlp_nodrop(x, mp, cfg: MoEGPTConfig):
    """Capacity-free inference routing: x [B, T, D] → (out, l_aux).

    Every token goes to its argmax expert, weighted by the gate probability —
    routing depends only on the token, so any batching/chunking of the same
    tokens produces identical outputs (the paged-serving parity invariant).
    Dispatches every token to all experts' rows and masks (E× FFN flops for
    static shapes; decode is bandwidth-bound, prefill chunks are short).
    The me·ce aux loss is still reported (eval-time routing balance).
    """
    B, T, D = x.shape
    E = cfg.num_experts
    xf = x.reshape(B * T, D)
    logits = (xf @ mp["gate_w"]).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top = jnp.argmax(probs, axis=-1)                       # [N]
    gate = jnp.max(probs, axis=-1).astype(x.dtype)         # [N]
    onehot = jax.nn.one_hot(top, E, dtype=x.dtype)         # [N, E]
    l_aux = jnp.sum(jnp.mean(probs, axis=0)
                    * jnp.mean(onehot.astype(jnp.float32), axis=0)) * E
    xe = jnp.einsum("ne,nd->end", onehot, xf)              # [E, N, D]
    ye = _expert_ffn(xe, mp, cfg)                          # [E, N, D]
    out = jnp.einsum("ne,end->nd", onehot, ye) * gate[:, None]
    return out.reshape(B, T, D), l_aux


def _zero_drop_stats():
    z = jnp.asarray(0.0, jnp.float32)
    return {"routed": z, "kept": z, "overflow_tokens": z, "dropped_frac": z}


def _sum_drop_stats(acc, s):
    acc = {k: acc[k] + s[k] for k in ("routed", "kept", "overflow_tokens")}
    acc["dropped_frac"] = acc["overflow_tokens"] / jnp.maximum(acc["routed"], 1.0)
    return acc


def moe_gpt_forward(params, tokens, cfg: MoEGPTConfig, training=True, rng=None,
                    mesh=None, return_stats=False):
    """[B, T] → (logits, total_l_aux[, drop_stats]). Python loop over layers
    (MoE layers break the homogeneous scan; L is moderate for MoE models)."""
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    x = _embed(params, tokens, positions, cfg)
    x = shard_constraint(x, BATCH_AXES, SEQ_AXIS, None)

    l_aux_total = jnp.asarray(0.0, jnp.float32)
    stats_total = _zero_drop_stats()
    moe_ids = set(cfg.moe_layer_ids())
    for lid in range(cfg.n_layer):
        p = jax.tree_util.tree_map(lambda a: a[lid], params["blocks"])
        if lid in moe_ids:
            # attention half from the dense block, MLP half replaced by MoE
            x, l_aux, stats = _moe_block(x, p, params["moe"][str(lid)], cfg,
                                         positions, training, mesh)
            l_aux_total = l_aux_total + l_aux
            stats_total = _sum_drop_stats(stats_total, stats)
        else:
            x = _block(x, p, cfg, positions)

    x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), cfg.use_rmsnorm,
              cfg.norm_eps)
    head = params["lm_head"] if not cfg.tie_embeddings else params["wte"]
    logits = jnp.einsum("btd,vd->btv", x, head.astype(x.dtype))
    if return_stats:
        return logits, l_aux_total, stats_total
    return logits, l_aux_total


def _moe_block(x, p, mp, cfg, positions, training, mesh=None):
    """Transformer block with MoE MLP (attention half shared with gpt._block,
    so alibi/sliding-window/parallel-residual behave identically)."""
    aux = []

    def moe_fn(h):
        if training:
            out, l_aux, stats = _moe_mlp(h, mp, cfg, training=True, mesh=mesh)
        else:
            out, l_aux = _moe_mlp_nodrop(h, mp, cfg)
            stats = _zero_drop_stats()
        aux.append((l_aux, stats))
        return out

    attn_out, _, _ = _attn_half(x, p, cfg, positions)
    x = _residual_mlp(x, attn_out, p, cfg, mlp_fn=moe_fn)
    l_aux, stats = aux[0]
    return shard_constraint(x, BATCH_AXES, SEQ_AXIS, None), l_aux, stats


def moe_gpt_loss(params, batch, rng, cfg: MoEGPTConfig, mesh=None):
    tokens = batch.get("tokens", batch.get("input_ids"))
    labels = batch.get("labels")
    if labels is None:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
    else:
        inputs = tokens
    logits, l_aux, stats = moe_gpt_forward(params, inputs, cfg, training=True,
                                           rng=rng, mesh=mesh,
                                           return_stats=True)
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    safe = jnp.maximum(labels, 0)
    gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    mask = (labels >= 0).astype(jnp.float32)
    nll = ((logz - gold) * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    # slash-keyed entries flow to telemetry gauges (runtime/engine.py threads
    # them through the grad path into `moe/*` — docs/profiling.md catalog)
    aux = {"lm_loss": nll, "l_aux": l_aux,
           "moe/aux_loss": l_aux,
           "moe/overflow_tokens": stats["overflow_tokens"],
           "moe/dropped_frac": stats["dropped_frac"]}
    return nll + cfg.moe_aux_weight * l_aux, aux


def make_moe_gpt_model(cfg: MoEGPTConfig, name="moe-gpt", seed=0,
                       mesh=None) -> ModelSpec:
    """Pass ``mesh=`` to route expert dispatch through the comm facade's
    all_to_all (shard_map over the expert axis) instead of the einsum path."""
    params = init_moe_gpt_params(cfg, seed=seed)
    return ModelSpec(loss_fn=partial(moe_gpt_loss, cfg=cfg, mesh=mesh),
                     params=params,
                     param_specs=moe_gpt_param_specs(cfg), has_aux=True,
                     apply_fn=partial(moe_gpt_forward, cfg=cfg, training=False),
                     name=name)


# ----------------------------------------------------------------------
# inference (expert-parallel decode — reference moe_inference.py)
# ----------------------------------------------------------------------


def _moe_mlp_decode(x, mp, cfg):
    """Single-token routing (kept for the contiguous decode path): the
    [B, 1, D] special case of `_moe_mlp_nodrop`."""
    out, _ = _moe_mlp_nodrop(x, mp, cfg)
    return out


def moe_cache_identity(cfg: MoEGPTConfig, name: str = "") -> str:
    """`gpt_cache_identity` plus the MoE fields that change KV VALUES: expert
    count and placement change every MoE layer's output, hence every later
    layer's K/V. Capacity knobs are absent on purpose — inference routing is
    capacity-free, so they cannot change cached bytes."""
    return (f"moe:{cfg.num_experts}|{cfg.moe_freq}|"
            + gpt_cache_identity(cfg, name))


def make_moe_gpt_decode_model(cfg: MoEGPTConfig, params=None, name="moe-gpt", seed=0):
    from deepspeed_tpu.inference.engine import DecodeModelSpec
    if params is None:
        params = init_moe_gpt_params(cfg, seed=seed)
    moe_ids = set(cfg.moe_layer_ids())

    def prefill_fn(params, tokens, cache, pad_mask):
        B, T = tokens.shape
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
        x = _embed(params, tokens, positions, cfg)
        ks, vs = [], []
        for lid in range(cfg.n_layer):
            p = jax.tree_util.tree_map(lambda a: a[lid], params["blocks"])
            attn_out, k, v = _attn_half(x, p, cfg, positions)
            ks.append(jnp.moveaxis(k, 1, 2))
            vs.append(jnp.moveaxis(v, 1, 2))
            if lid in moe_ids:
                mp = params["moe"][str(lid)]
                moe_fn = lambda h, mp=mp: _moe_mlp_nodrop(h, mp, cfg)[0]
                x = _residual_mlp(x, attn_out, p, cfg, mlp_fn=moe_fn)
            else:
                x = _residual_mlp(x, attn_out, p, cfg)
        x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), cfg.use_rmsnorm,
                  cfg.norm_eps)
        head = params["lm_head"] if not cfg.tie_embeddings else params["wte"]
        logits = jnp.einsum("btd,vd->btv", x, head.astype(x.dtype))
        new_cache = {
            "k": cache["k"].at[:, :, :, :T].set(jnp.stack(ks, 0).astype(cache["k"].dtype)),
            "v": cache["v"].at[:, :, :, :T].set(jnp.stack(vs, 0).astype(cache["v"].dtype)),
            "length": jnp.full((B,), T, jnp.int32),
        }
        return logits, new_cache

    def decode_fn(params, token, pos, cache):
        B = token.shape[0]
        x = _embed(params, token[:, None], pos[:, None], cfg)
        new_k, new_v = [], []
        for lid in range(cfg.n_layer):
            p = jax.tree_util.tree_map(lambda a: a[lid], params["blocks"])
            if lid in moe_ids:
                x, ck, cv = _moe_block_decode(x, p, params["moe"][str(lid)],
                                              cache["k"][lid], cache["v"][lid],
                                              pos, cfg)
            else:
                x, ck, cv = _block_decode(x, p, cache["k"][lid], cache["v"][lid],
                                          pos, cfg)
            new_k.append(ck)
            new_v.append(cv)
        x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), cfg.use_rmsnorm,
                  cfg.norm_eps)
        head = params["lm_head"] if not cfg.tie_embeddings else params["wte"]
        logits = jnp.einsum("bod,vd->bov", x, head.astype(x.dtype))[:, 0]
        cache_out = {"k": jnp.stack(new_k, 0), "v": jnp.stack(new_v, 0),
                     "length": cache["length"] + 1}
        return logits, cache_out

    def init_cache(batch_size, max_len, dtype=jnp.bfloat16):
        return init_kv_cache(cfg, batch_size, max_len, dtype)

    # paged-pool serving contract (see DecodeModelSpec): same pool layout and
    # attention machinery as gpt.py's paged path, but the stacked-layer scan
    # becomes a Python loop — MoE layers are heterogeneous (per-layer expert
    # trees), and the capacity-free routing keeps every chunking of a prompt
    # token-identical, which is what continuous batching relies on.

    def _loop_paged(params, x, pool, block_tables, positions, phase=None):
        slices = []
        for lid in range(cfg.n_layer):
            p = jax.tree_util.tree_map(lambda a: a[lid], params["blocks"])
            pool_l = {k: v[lid] for k, v in pool.items()}
            attn_out, pool_l = _paged_attn_half(x, p, pool_l, positions,
                                                block_tables, cfg, phase=phase)
            if lid in moe_ids:
                mp = params["moe"][str(lid)]
                moe_fn = lambda h, mp=mp: _moe_mlp_nodrop(h, mp, cfg)[0]
                x = _residual_mlp(x, attn_out, p, cfg, constrain=False,
                                  mlp_fn=moe_fn)
            else:
                x = _residual_mlp(x, attn_out, p, cfg, constrain=False)
            slices.append(pool_l)
        pool = {k: jnp.stack([s[k] for s in slices], 0) for k in pool}
        return x, pool

    def prefill_paged_fn(params, tokens, start_pos, last_idx, pool,
                         block_tables):
        B, C = tokens.shape
        positions = start_pos[:, None] + jnp.arange(C, dtype=jnp.int32)[None]
        x = _embed(params, tokens, positions, cfg)
        x, pool = _loop_paged(params, x, pool, block_tables, positions)
        last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)
        logits = _lm_head(params, last, cfg)[:, 0]
        return logits, pool

    def decode_paged_fn(params, token, pos, pool, block_tables):
        x = _embed(params, token[:, None], pos[:, None], cfg)
        x, pool = _loop_paged(params, x, pool, block_tables, pos[:, None])
        logits = _lm_head(params, x, cfg)[:, 0]
        return logits, pool

    def verify_paged_fn(params, tokens, pos, pool, block_tables):
        B, C = tokens.shape
        positions = pos[:, None] + jnp.arange(C, dtype=jnp.int32)[None]
        x = _embed(params, tokens, positions, cfg)
        x, pool = _loop_paged(params, x, pool, block_tables, positions,
                              phase="verify")
        logits = _lm_head(params, x, cfg)
        return logits, pool

    def init_paged_pool(num_blocks, block_size, dtype=jnp.bfloat16,
                        kv_group_size=0):
        return init_paged_kv_pool(cfg, num_blocks, block_size, dtype,
                                  kv_group_size)

    return DecodeModelSpec(prefill_fn=prefill_fn, decode_fn=decode_fn,
                           init_cache=init_cache, params=params,
                           param_specs=moe_gpt_param_specs(cfg), name=name,
                           prefill_paged_fn=prefill_paged_fn,
                           decode_paged_fn=decode_paged_fn,
                           verify_paged_fn=verify_paged_fn,
                           init_paged_pool=init_paged_pool,
                           cache_fingerprint=moe_cache_identity(cfg, name))


def _moe_block_decode(x, p, mp, cache_k, cache_v, pos, cfg):
    """_block_decode with the MLP replaced by single-token MoE routing."""
    attn_out, cache_k, cache_v = _decode_attn_half(x, p, cache_k, cache_v, pos, cfg)
    x = _residual_mlp(x, attn_out, p, cfg, constrain=False,
                      mlp_fn=lambda h: _moe_mlp_decode(h, mp, cfg))
    return x, cache_k, cache_v


def moe_expert_store(params, layer_id):
    """One MoE layer's stacked expert tree as a `LayerParamStore` — experts
    play the role of layers, so `LayerStreamer(..., cyclic=True)` stages
    expert weights through a small HBM window exactly like PR 15's layer
    streaming (expert weights are the ideal streamed tier: each token's
    forward touches one expert, the rest are cold).

    Returns (store, expert_tree) — `store.layer_params(e)`-style access comes
    from the streamer; `expert_tree` is the [E, ...] source for parity checks.
    """
    from deepspeed_tpu.runtime.param_swap import LayerParamStore
    mp = params["moe"][str(layer_id)]
    expert_tree = {k: v for k, v in mp.items() if k != "gate_w"}
    return LayerParamStore(expert_tree), expert_tree
