"""MoE-GPT — GPT with Mixture-of-Experts MLPs, training AND inference.

Reference: training MoE via `deepspeed/moe/layer.py:16` placed inside client
transformer MLPs, and MoE *inference* via the expert-parallel containers
(`ops/transformer/inference/moe_inference.py`, `inference/engine.py:260`
`_create_ep_parallel_group`).

TPU-native formulation: every `moe_freq`-th block's MLP is a GShard-style
expert layer — gate → top-1 dispatch einsum constrained onto the `expert` mesh
axis (XLA inserts the all-to-all pair) → expert FFN batched over the expert
dim → combine einsum. Static capacity, masked overflow (no dynamic shapes).
Inference gating drops jitter/aux-loss and keeps argmax routing; the decode
path routes single tokens with a plain one-hot combine (capacity is irrelevant
at batch-per-step granularity).
"""

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.mesh import (BATCH_AXES, EXPERT_AXIS, SEQ_AXIS,
                                     TENSOR_AXIS, shard_constraint)
from deepspeed_tpu.models.gpt import (GPTConfig, _attn_half, _block,
                                      _block_decode, _decode_attn_half, _embed,
                                      _norm, _residual_mlp,
                                      init_gpt_params, gpt_param_specs,
                                      init_kv_cache)
from deepspeed_tpu.parallel.moe import top1_gating
from deepspeed_tpu.runtime.engine import ModelSpec


@dataclasses.dataclass
class MoEGPTConfig(GPTConfig):
    num_experts: int = 8
    moe_freq: int = 2                 # every moe_freq-th block is MoE (from block 1)
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    moe_aux_weight: float = 0.01

    def moe_layer_ids(self):
        return [i for i in range(self.n_layer) if i % self.moe_freq == 1]


def init_moe_gpt_params(cfg: MoEGPTConfig, seed: int = 0, dtype=jnp.float32):
    """Dense skeleton (stacked blocks, gpt.py layout) + per-MoE-layer expert
    weights {layer_id: {gate_w, w_up [E,D,F], w_down [E,F,D]}}."""
    params = init_gpt_params(cfg, seed=seed, dtype=dtype)
    rng = np.random.default_rng(seed + 7)
    D, F, E = cfg.d_model, cfg.d_ff, cfg.num_experts
    moe = {}
    for lid in cfg.moe_layer_ids():
        moe[str(lid)] = {
            "gate_w": jnp.asarray(rng.normal(0, 0.02, (D, E)), dtype),
            "w_up": jnp.asarray(rng.normal(0, 0.02, (E, D, F)), dtype),
            "b_up": jnp.zeros((E, F), dtype),
            "w_down": jnp.asarray(rng.normal(0, 0.02 / np.sqrt(2 * cfg.n_layer),
                                             (E, F, D)), dtype),
            "b_down": jnp.zeros((E, D), dtype),
        }
    params["moe"] = moe
    return params


def moe_gpt_param_specs(cfg: MoEGPTConfig):
    specs = gpt_param_specs(cfg)
    e, t = EXPERT_AXIS, TENSOR_AXIS
    moe_spec = {
        "gate_w": P(None, None),
        "w_up": P(e, None, t),
        "b_up": P(e, t),
        "w_down": P(e, t, None),
        "b_down": P(e, None),
    }
    specs["moe"] = {str(lid): dict(moe_spec) for lid in cfg.moe_layer_ids()}
    return specs


def _expert_ffn(xe, mp, cfg):
    """xe: [E, C, D] tokens per expert → [E, C, D]; batched expert FFN on the
    expert mesh axis."""
    h = jnp.einsum("ecd,edf->ecf", xe, mp["w_up"]) + mp["b_up"][:, None, :]
    h = jax.nn.gelu(h) if cfg.activation == "gelu" else jax.nn.relu(h)
    h = shard_constraint(h, EXPERT_AXIS, None, TENSOR_AXIS)
    return jnp.einsum("ecf,efd->ecd", h, mp["w_down"]) + mp["b_down"][:, None, :]


def _moe_mlp(x, mp, cfg: MoEGPTConfig, training=True):
    """x: [B, T, D] → (out, l_aux). GShard dispatch/combine einsums."""
    B, T, D = x.shape
    xf = x.reshape(B * T, D)
    logits = (xf @ mp["gate_w"]).astype(jnp.float32)
    cf = cfg.capacity_factor if training else cfg.eval_capacity_factor
    l_aux, dispatch, combine, _counts = top1_gating(
        logits, capacity_factor=cf, min_capacity=cfg.min_capacity)
    # dispatch: [N, E, C] — einsum routes tokens to expert slots; the sharding
    # constraint on the expert dim makes XLA emit the a2a (reference _AllToAll)
    xe = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), xf)
    xe = shard_constraint(xe, EXPERT_AXIS, None, None)
    ye = _expert_ffn(xe, mp, cfg)
    out = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), ye)
    return out.reshape(B, T, D), l_aux


def moe_gpt_forward(params, tokens, cfg: MoEGPTConfig, training=True, rng=None):
    """[B, T] → (logits, total_l_aux). Python loop over layers (MoE layers break
    the homogeneous scan; L is moderate for MoE models)."""
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    x = _embed(params, tokens, positions, cfg)
    x = shard_constraint(x, BATCH_AXES, SEQ_AXIS, None)

    l_aux_total = jnp.asarray(0.0, jnp.float32)
    moe_ids = set(cfg.moe_layer_ids())
    for lid in range(cfg.n_layer):
        p = jax.tree_util.tree_map(lambda a: a[lid], params["blocks"])
        if lid in moe_ids:
            # attention half from the dense block, MLP half replaced by MoE
            x = _moe_block(x, p, params["moe"][str(lid)], cfg, positions, training)
            x, l_aux = x
            l_aux_total = l_aux_total + l_aux
        else:
            x = _block(x, p, cfg, positions)

    x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), cfg.use_rmsnorm,
              cfg.norm_eps)
    head = params["lm_head"] if not cfg.tie_embeddings else params["wte"]
    logits = jnp.einsum("btd,vd->btv", x, head.astype(x.dtype))
    return logits, l_aux_total


def _moe_block(x, p, mp, cfg, positions, training):
    """Transformer block with MoE MLP (attention half shared with gpt._block,
    so alibi/sliding-window/parallel-residual behave identically)."""
    aux = []

    def moe_fn(h):
        out, l_aux = _moe_mlp(h, mp, cfg, training)
        aux.append(l_aux)
        return out

    attn_out, _, _ = _attn_half(x, p, cfg, positions)
    x = _residual_mlp(x, attn_out, p, cfg, mlp_fn=moe_fn)
    return shard_constraint(x, BATCH_AXES, SEQ_AXIS, None), aux[0]


def moe_gpt_loss(params, batch, rng, cfg: MoEGPTConfig):
    tokens = batch.get("tokens", batch.get("input_ids"))
    labels = batch.get("labels")
    if labels is None:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
    else:
        inputs = tokens
    logits, l_aux = moe_gpt_forward(params, inputs, cfg, training=True, rng=rng)
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    safe = jnp.maximum(labels, 0)
    gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    mask = (labels >= 0).astype(jnp.float32)
    nll = ((logz - gold) * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return nll + cfg.moe_aux_weight * l_aux, {"lm_loss": nll, "l_aux": l_aux}


def make_moe_gpt_model(cfg: MoEGPTConfig, name="moe-gpt", seed=0) -> ModelSpec:
    params = init_moe_gpt_params(cfg, seed=seed)
    return ModelSpec(loss_fn=partial(moe_gpt_loss, cfg=cfg), params=params,
                     param_specs=moe_gpt_param_specs(cfg), has_aux=True,
                     apply_fn=partial(moe_gpt_forward, cfg=cfg, training=False),
                     name=name)


# ----------------------------------------------------------------------
# inference (expert-parallel decode — reference moe_inference.py)
# ----------------------------------------------------------------------


def _moe_mlp_decode(x, mp, cfg):
    """Single-token routing: x [B, 1, D]; every token goes to its argmax expert
    (capacity-free — one token per step cannot overflow)."""
    B, _, D = x.shape
    xf = x.reshape(B, D)
    logits = (xf @ mp["gate_w"]).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top = jnp.argmax(probs, axis=-1)                       # [B]
    gate = jnp.max(probs, axis=-1).astype(x.dtype)         # [B]
    onehot = jax.nn.one_hot(top, cfg.num_experts, dtype=x.dtype)  # [B, E]
    # dispatch every token to all experts' slots, mask by routing (E is small;
    # trades E× FFN flops for static shapes — decode is bandwidth-bound anyway)
    xe = jnp.einsum("be,bd->ebd", onehot, xf)              # [E, B, D]
    ye = _expert_ffn(xe, mp, cfg)                          # [E, B, D]
    out = jnp.einsum("be,ebd->bd", onehot, ye) * gate[:, None]
    return out.reshape(B, 1, D)


def make_moe_gpt_decode_model(cfg: MoEGPTConfig, params=None, name="moe-gpt", seed=0):
    from deepspeed_tpu.inference.engine import DecodeModelSpec
    if params is None:
        params = init_moe_gpt_params(cfg, seed=seed)
    moe_ids = set(cfg.moe_layer_ids())

    def prefill_fn(params, tokens, cache, pad_mask):
        B, T = tokens.shape
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
        x = _embed(params, tokens, positions, cfg)
        ks, vs = [], []
        for lid in range(cfg.n_layer):
            p = jax.tree_util.tree_map(lambda a: a[lid], params["blocks"])
            attn_out, k, v = _attn_half(x, p, cfg, positions)
            ks.append(jnp.moveaxis(k, 1, 2))
            vs.append(jnp.moveaxis(v, 1, 2))
            if lid in moe_ids:
                mp = params["moe"][str(lid)]
                moe_fn = lambda h, mp=mp: _moe_mlp(h, mp, cfg, training=False)[0]
                x = _residual_mlp(x, attn_out, p, cfg, mlp_fn=moe_fn)
            else:
                x = _residual_mlp(x, attn_out, p, cfg)
        x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), cfg.use_rmsnorm,
                  cfg.norm_eps)
        head = params["lm_head"] if not cfg.tie_embeddings else params["wte"]
        logits = jnp.einsum("btd,vd->btv", x, head.astype(x.dtype))
        new_cache = {
            "k": cache["k"].at[:, :, :, :T].set(jnp.stack(ks, 0).astype(cache["k"].dtype)),
            "v": cache["v"].at[:, :, :, :T].set(jnp.stack(vs, 0).astype(cache["v"].dtype)),
            "length": jnp.full((B,), T, jnp.int32),
        }
        return logits, new_cache

    def decode_fn(params, token, pos, cache):
        B = token.shape[0]
        x = _embed(params, token[:, None], pos[:, None], cfg)
        new_k, new_v = [], []
        for lid in range(cfg.n_layer):
            p = jax.tree_util.tree_map(lambda a: a[lid], params["blocks"])
            if lid in moe_ids:
                x, ck, cv = _moe_block_decode(x, p, params["moe"][str(lid)],
                                              cache["k"][lid], cache["v"][lid],
                                              pos, cfg)
            else:
                x, ck, cv = _block_decode(x, p, cache["k"][lid], cache["v"][lid],
                                          pos, cfg)
            new_k.append(ck)
            new_v.append(cv)
        x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), cfg.use_rmsnorm,
                  cfg.norm_eps)
        head = params["lm_head"] if not cfg.tie_embeddings else params["wte"]
        logits = jnp.einsum("bod,vd->bov", x, head.astype(x.dtype))[:, 0]
        cache_out = {"k": jnp.stack(new_k, 0), "v": jnp.stack(new_v, 0),
                     "length": cache["length"] + 1}
        return logits, cache_out

    def init_cache(batch_size, max_len, dtype=jnp.bfloat16):
        return init_kv_cache(cfg, batch_size, max_len, dtype)

    return DecodeModelSpec(prefill_fn=prefill_fn, decode_fn=decode_fn,
                           init_cache=init_cache, params=params,
                           param_specs=moe_gpt_param_specs(cfg), name=name)


def _moe_block_decode(x, p, mp, cache_k, cache_v, pos, cfg):
    """_block_decode with the MLP replaced by single-token MoE routing."""
    attn_out, cache_k, cache_v = _decode_attn_half(x, p, cache_k, cache_v, pos, cfg)
    x = _residual_mlp(x, attn_out, p, cfg, constrain=False,
                      mlp_fn=lambda h: _moe_mlp_decode(h, mp, cfg))
    return x, cache_k, cache_v
