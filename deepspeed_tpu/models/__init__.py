from deepspeed_tpu.models.gpt import (
    GPTConfig,
    init_gpt_params,
    gpt_forward,
    make_gpt_model,
    make_gpt_decode_model,
    GPT2_CONFIGS,
)
