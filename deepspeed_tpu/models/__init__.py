from deepspeed_tpu.models.gpt import (
    GPTConfig,
    init_gpt_params,
    gpt_forward,
    make_gpt_model,
    make_gpt_decode_model,
    GPT2_CONFIGS,
)
from deepspeed_tpu.models.llama import (
    LLAMA_CONFIGS,
    llama_config,
    make_llama_model,
    make_llama_decode_model,
)
from deepspeed_tpu.models.bert import (
    BertConfig,
    BERT_CONFIGS,
    make_bert_model,
    bert_encode,
)
