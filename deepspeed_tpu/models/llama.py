"""LLaMA family — rotary + SwiGLU + RMSNorm + grouped-query attention.

The reference serves LLaMA through injection containers
(`module_inject/containers/llama.py`, `llama2.py` — policy classes mapping HF
modules onto fused CUDA blocks). Here LLaMA is a first-class zoo member built on
the shared GPT core (models/gpt.py): one compiled block program scanned over
layers, TP PartitionSpecs, remat policy, and a static-shape KV-cache decode path.
GQA (llama2-70b, llama3) contracts grouped query heads against unreplicated k/v.

HF checkpoint import lives in inference/adapters.py (the containers' weight-layout
role).
"""

import jax.numpy as jnp

from deepspeed_tpu.models.gpt import (GPTConfig, init_gpt_params, gpt_forward,
                                      gpt_loss, gpt_param_specs, make_gpt_model,
                                      make_gpt_decode_model)


def llama_config(**kw) -> GPTConfig:
    base = dict(use_rotary=True, use_swiglu=True, use_rmsnorm=True,
                tie_embeddings=False, dtype=jnp.bfloat16)
    base.update(kw)
    return GPTConfig(**base)


LLAMA_CONFIGS = {
    # tiny config for tests / dryruns
    "llama-tiny": llama_config(n_layer=2, n_head=4, n_kv_head=2, d_model=128,
                               d_ff=256, max_seq_len=256, vocab_size=1024),
    "llama2-7b": llama_config(n_layer=32, n_head=32, d_model=4096, d_ff=11008,
                              max_seq_len=4096, vocab_size=32000),
    "llama2-13b": llama_config(n_layer=40, n_head=40, d_model=5120, d_ff=13824,
                               max_seq_len=4096, vocab_size=32000),
    "llama2-70b": llama_config(n_layer=80, n_head=64, n_kv_head=8, d_model=8192,
                               d_ff=28672, max_seq_len=4096, vocab_size=32000),
    "llama3-8b": llama_config(n_layer=32, n_head=32, n_kv_head=8, d_model=4096,
                              d_ff=14336, max_seq_len=8192, vocab_size=128256,
                              rope_theta=500000.0),
    "llama3-70b": llama_config(n_layer=80, n_head=64, n_kv_head=8, d_model=8192,
                               d_ff=28672, max_seq_len=8192, vocab_size=128256,
                               rope_theta=500000.0),
}


def make_llama_model(cfg: GPTConfig = None, name="llama2-7b", seed=0, attn_fn=None):
    """Training ModelSpec (shares the GPT core — same scan/remat/TP treatment)."""
    cfg = cfg or LLAMA_CONFIGS[name]
    return make_gpt_model(cfg=cfg, name=name, seed=seed, attn_fn=attn_fn)


def make_llama_decode_model(cfg: GPTConfig = None, name="llama2-7b", params=None, seed=0):
    """DecodeModelSpec for the inference engine."""
    cfg = cfg or LLAMA_CONFIGS[name]
    return make_gpt_decode_model(cfg=cfg, name=name, params=params, seed=seed)


__all__ = ["LLAMA_CONFIGS", "llama_config", "make_llama_model",
           "make_llama_decode_model", "init_gpt_params", "gpt_forward",
           "gpt_loss", "gpt_param_specs"]
