"""GPT family — the flagship model, TPU-first.

The reference trains GPT through client Megatron models and serves it through
injected containers (`module_inject/containers/gpt2.py`, `megatron_gpt.py`); its
flagship benchmark is GPT ZeRO-3 (BASELINE.md). Here the model itself is part of
the framework's zoo, written the TPU way:

  * stacked block parameters + `lax.scan` over layers — one compiled block program,
    O(1) compile time in depth;
  * logical sharding via PartitionSpecs: batch on `data`, heads/ffn on `tensor`
    (Megatron TP), sequence on `sequence` (Ulysses — see parallel/ulysses.py);
  * `jax.checkpoint` (remat) policy per block for activation-memory control
    (analog of `runtime/activation_checkpointing/`);
  * bf16 activations, fp32 softmax/layernorm accumulation;
  * a static-shape KV-cache decode path for the inference engine.

Architecture: pre-LN GPT-2 (learned positions) with optional GPT-NeoX/LLaMA-style
rotary embeddings and (Sw)iGLU — enough surface to cover the reference's
gpt2/gptj/neox/llama containers with one implementation.
"""

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.mesh import BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, shard_constraint
from deepspeed_tpu.ops import attention_dispatch as attn_dispatch
from deepspeed_tpu.runtime.engine import ModelSpec


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304          # padded to 128 multiple (MXU-friendly)
    n_layer: int = 12
    n_head: int = 12
    n_kv_head: Optional[int] = None  # grouped-query attention; None = n_head (MHA)
    d_model: int = 768
    d_ff: Optional[int] = None       # default 4*d_model (or 8/3 for swiglu)
    max_seq_len: int = 1024
    dropout: float = 0.0
    use_rotary: bool = False         # False: learned positions (GPT-2); True: RoPE
    rotary_pct: float = 1.0
    rope_theta: float = 10000.0      # RoPE base (LLaMA-3 uses 500000)
    norm_eps: float = 1e-5           # LayerNorm/RMSNorm epsilon (HF LLaMA: 1e-6)
    use_swiglu: bool = False         # LLaMA-style gated MLP
    use_rmsnorm: bool = False        # LLaMA-style RMSNorm
    activation: str = "gelu"         # "gelu" (tanh approx = HF gelu_new), "relu" (OPT)
    use_alibi: bool = False          # BLOOM attention bias instead of positions
    use_emb_ln: bool = False         # BLOOM LayerNorm after word embedding
    parallel_residual: bool = False  # NeoX/GPT-J: x + attn(ln1 x) + mlp(ln2 x)
    sliding_window: Optional[int] = None  # Mistral local attention window
    attn_layer_types: Optional[tuple] = None  # GPT-Neo per-layer ("global",
                                     # "local", ...): "local" layers apply the
                                     # sliding_window mask, "global" full causal
    scale_attn: bool = True          # GPT-Neo scores are NOT scaled by 1/sqrt(hd)
    tie_embeddings: bool = True
    remat: bool = True               # jax.checkpoint each block
    remat_policy: str = "nothing_saveable"  # jax.checkpoint_policies name, or
                                     # "save_matmuls": save every big matmul
                                     # output (named checkpoints) so backward
                                     # recomputes only norms/softmax/elementwise.
                                     # Measured on v5e: full remat WINS anyway —
                                     # recompute is cheaper than reloading the
                                     # saved ~150MB/layer from HBM; kept as an
                                     # option for bandwidth-rich parts
    use_flash_attention: Optional[bool] = None  # None = AUTO by sequence
                                     # length: the Pallas kernel engages at
                                     # T >= FLASH_MIN_SEQ (measured r4, bf16
                                     # dots + 512-blocks: XLA wins <=512
                                     # (0.78 vs 1.22ms), flash wins 1.6x at
                                     # 1k, 2.3x at 2k, 3.4x at 4k fwd+bwd)
                                     # and, since it streams K/V from HBM,
                                     # carries EVERY longer T (no VMEM cap).
                                     # True/False force the choice. The
                                     # DECODE kernel auto-engages from
                                     # M >= DECODE_KERNEL_MIN_CTX: at short
                                     # contexts XLA's einsum sits at the
                                     # bandwidth floor (r5: 174-204us vs
                                     # kernel 189us vs floor 164us at ctx
                                     # 8k), but the blocked kernel reads
                                     # only the live prefix of the cache
                                     # while XLA always reads all M — at
                                     # serving-scale caches that asymmetry,
                                     # not the matmul, decides; see
                                     # docs/kernels.md
    attention_backend: Optional[str] = None  # explicit attention-program
                                     # request for the dispatch layer
                                     # (ops/attention_dispatch.py): "ring" /
                                     # "ring_ulysses" engage context
                                     # parallelism over the `sequence` mesh
                                     # axis (K/V shards rotate via ppermute;
                                     # the hybrid adds the Ulysses head
                                     # all-to-all, sp = ulysses x ring).
                                     # None = auto (flash/chunked/dense by
                                     # the measured crossovers). Ignored
                                     # when no `sequence` axis > 1 is
                                     # installed — the request falls through
                                     # to the auto programs
    chunked_attn_min_seq: Optional[int] = None  # remat/memory escape hatch:
                                     # T >= this routes to the q-chunked
                                     # rematerialized XLA path
                                     # (ops/chunked_attention.py) instead of
                                     # the flash kernel. None (default) =
                                     # never — the streaming kernel has no
                                     # sequence cap, so only an HBM squeeze
                                     # (activation residuals at extreme T)
                                     # justifies the ~2.8x-slower fallback
    act_quant: Any = None            # ActQuantGate (compression/pruners.py):
                                     # when .active, each block linear's INPUT
                                     # is fake-quantized to .bits with STE
                                     # (reference basic_layer QuantAct role)
    loss_chunks: int = 0             # >0: chunked-vocab CE (ops/chunked_ce.py)
                                     # — never materializes [B,T,V] logits;
                                     # frees ~1.2G peak HBM at 50k vocab for
                                     # one extra head-matmul pass in the bwd
    softmax_dtype: Any = jnp.float32  # attention softmax accumulation dtype;
                                     # bf16 halves the dominant HBM traffic of
                                     # materialized attention (max-subtracted,
                                     # exp still in fp32) — the bench uses it
    scan_unroll: int = 1             # lax.scan unroll over layers (measured r4:
                                     # unroll=2 LOSES 14% at the bench shape —
                                     # bigger program, no slice saved; keep 1)
    remat_prevent_cse: bool = False  # jax.checkpoint prevent_cse. False is the
                                     # documented-efficient form inside scan
                                     # (the scan boundary already stops the CSE
                                     # that prevent_cse guards against) and
                                     # measured +6.4%/+6.7% MFU on the
                                     # 760m/1.3b bench lanes (0.597->0.633,
                                     # 0.588->0.628 at gas 8)
    dtype: Any = jnp.bfloat16        # activation dtype

    def __post_init__(self):
        if self.d_ff is None:
            self.d_ff = int(8 * self.d_model / 3) if self.use_swiglu else 4 * self.d_model
        if self.n_kv_head is None:
            self.n_kv_head = self.n_head
        assert self.d_model % self.n_head == 0
        assert self.n_head % self.n_kv_head == 0

    @property
    def head_dim(self):
        return self.d_model // self.n_head

    @property
    def qkv_dim(self):
        """Fused qkv output width: H*hd for q + 2*Hkv*hd for k,v (GQA-aware)."""
        return (self.n_head + 2 * self.n_kv_head) * self.head_dim

    def num_params(self):
        wpe = 0 if self.use_rotary else self.max_seq_len * self.d_model
        per_block = (self.d_model * (self.qkv_dim + self.d_model)  # qkv + proj
                     + (3 if self.use_swiglu else 2) * self.d_model * self.d_ff
                     + 4 * self.d_model)                       # norms/biases approx
        emb = self.vocab_size * self.d_model
        head = 0 if self.tie_embeddings else self.vocab_size * self.d_model
        return emb + head + wpe + self.n_layer * per_block


# Reference model sizes used in the baseline ladder (BASELINE.md). Head counts
# for the training-bench sizes are chosen so head_dim == 128, the MXU lane
# width (head_dim 64/96 leaves 25-50% of every attention dot's lanes padded —
# measured +14% MFU on the 1.3B lane, +3.5% on 760m). Param count is
# head-count invariant, and the reference's own ZeRO tutorial picks 16 heads
# for its 1.5B GPT-2 (`docs/_tutorials/zero.md:35`); HF-checkpoint adapters
# (`inference/adapters.py`) carry each checkpoint's true head count instead.
GPT2_CONFIGS = {
    "gpt2-tiny": GPTConfig(n_layer=2, n_head=4, d_model=128, max_seq_len=256, vocab_size=1024),
    "gpt2-125m": GPTConfig(n_layer=12, n_head=12, d_model=768, max_seq_len=1024),
    "gpt2-350m": GPTConfig(n_layer=24, n_head=8, d_model=1024, max_seq_len=1024),
    "gpt2-760m": GPTConfig(n_layer=24, n_head=12, d_model=1536, max_seq_len=1024),
    "gpt2-1.3b": GPTConfig(n_layer=24, n_head=16, d_model=2048, max_seq_len=1024),
    "gpt2-2.7b": GPTConfig(n_layer=32, n_head=20, d_model=2560, max_seq_len=1024),
    "gpt2-6.7b": GPTConfig(n_layer=32, n_head=32, d_model=4096, max_seq_len=1024),
}


# ----------------------------------------------------------------------
# init
# ----------------------------------------------------------------------


def init_gpt_params(cfg: GPTConfig, seed: int = 0, dtype=jnp.float32):
    """Stacked-block parameter pytree. Block leaves have leading dim n_layer."""
    rng = np.random.default_rng(seed)
    D, F, L, H = cfg.d_model, cfg.d_ff, cfg.n_layer, cfg.n_head

    def norm(*shape, scale=0.02):
        return jnp.asarray(rng.normal(0.0, scale, shape), dtype)

    def zeros(*shape):
        return jnp.zeros(shape, dtype)

    def ones(*shape):
        return jnp.ones(shape, dtype)

    proj_scale = 0.02 / math.sqrt(2 * L)  # GPT-2 residual-proj init
    QKV = cfg.qkv_dim
    block = {
        "ln1_scale": ones(L, D),
        "ln2_scale": ones(L, D),
        "attn_qkv_w": norm(L, D, QKV),
        "attn_qkv_b": zeros(L, QKV),
        "attn_out_w": jnp.asarray(rng.normal(0.0, proj_scale, (L, D, D)), dtype),
        "attn_out_b": zeros(L, D),
        "mlp_out_b": zeros(L, D),
    }
    if not cfg.use_rmsnorm:
        block["ln1_bias"] = zeros(L, D)
        block["ln2_bias"] = zeros(L, D)
    if cfg.use_swiglu:
        block["mlp_gate_w"] = norm(L, D, F)
        block["mlp_up_w"] = norm(L, D, F)
        block["mlp_down_w"] = jnp.asarray(rng.normal(0.0, proj_scale, (L, F, D)), dtype)
    else:
        block["mlp_up_w"] = norm(L, D, F)
        block["mlp_up_b"] = zeros(L, F)
        block["mlp_down_w"] = jnp.asarray(rng.normal(0.0, proj_scale, (L, F, D)), dtype)

    params = {
        "wte": norm(cfg.vocab_size, D, scale=0.02),
        "blocks": block,
        "lnf_scale": ones(D),
    }
    if not cfg.use_rmsnorm:
        params["lnf_bias"] = zeros(D)
    if not cfg.use_rotary and not cfg.use_alibi:
        params["wpe"] = norm(cfg.max_seq_len, D, scale=0.01)
    if cfg.use_emb_ln:
        params["emb_ln_scale"] = ones(D)
        params["emb_ln_bias"] = zeros(D)
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(cfg.vocab_size, D, scale=0.02)
    return params


def gpt_init_fn(cfg: GPTConfig, dtype=jnp.float32):
    """jax-traceable initializer (rng -> params) mirroring `init_gpt_params`.

    For the engine's zero.Init path (ModelSpec.init_fn): the returned function
    runs under jit with stage-3 out_shardings, so each leaf is created directly
    in its shard and a model larger than host RAM / one-chip HBM never
    materializes whole (reference `zero/partition_parameters.py:723`)."""
    D, F, L = cfg.d_model, cfg.d_ff, cfg.n_layer
    proj_scale = 0.02 / math.sqrt(2 * L)
    QKV = cfg.qkv_dim

    def init(rng):
        keys = iter(jax.random.split(rng, 16))
        norm = lambda *shape, scale=0.02: (
            jax.random.normal(next(keys), shape, dtype) * scale)
        zeros = lambda *shape: jnp.zeros(shape, dtype)
        ones = lambda *shape: jnp.ones(shape, dtype)
        block = {
            "ln1_scale": ones(L, D),
            "ln2_scale": ones(L, D),
            "attn_qkv_w": norm(L, D, QKV),
            "attn_qkv_b": zeros(L, QKV),
            "attn_out_w": norm(L, D, D, scale=proj_scale),
            "attn_out_b": zeros(L, D),
            "mlp_out_b": zeros(L, D),
        }
        if not cfg.use_rmsnorm:
            block["ln1_bias"] = zeros(L, D)
            block["ln2_bias"] = zeros(L, D)
        if cfg.use_swiglu:
            block["mlp_gate_w"] = norm(L, D, F)
            block["mlp_up_w"] = norm(L, D, F)
            block["mlp_down_w"] = norm(L, F, D, scale=proj_scale)
        else:
            block["mlp_up_w"] = norm(L, D, F)
            block["mlp_up_b"] = zeros(L, F)
            block["mlp_down_w"] = norm(L, F, D, scale=proj_scale)
        params = {
            "wte": norm(cfg.vocab_size, D, scale=0.02),
            "blocks": block,
            "lnf_scale": ones(D),
        }
        if not cfg.use_rmsnorm:
            params["lnf_bias"] = zeros(D)
        if not cfg.use_rotary and not cfg.use_alibi:
            params["wpe"] = norm(cfg.max_seq_len, D, scale=0.01)
        if cfg.use_emb_ln:
            params["emb_ln_scale"] = ones(D)
            params["emb_ln_bias"] = zeros(D)
        if not cfg.tie_embeddings:
            params["lm_head"] = norm(cfg.vocab_size, D, scale=0.02)
        return params

    return init


def gpt_param_specs(cfg: GPTConfig):
    """Megatron-style TP PartitionSpecs (reference: AutoTP's shard plan,
    `module_inject/auto_tp.py` — column-parallel qkv/up, row-parallel out/down).
    ZeRO adds its axes orthogonally (runtime/zero.py)."""
    t = TENSOR_AXIS
    block = {
        "ln1_scale": P(None, None),
        "ln2_scale": P(None, None),
        "attn_qkv_w": P(None, None, t),      # column parallel
        "attn_qkv_b": P(None, t),
        "attn_out_w": P(None, t, None),      # row parallel
        "attn_out_b": P(None, None),
        "mlp_out_b": P(None, None),
    }
    if not cfg.use_rmsnorm:
        block["ln1_bias"] = P(None, None)
        block["ln2_bias"] = P(None, None)
    if cfg.use_swiglu:
        block["mlp_gate_w"] = P(None, None, t)
        block["mlp_up_w"] = P(None, None, t)
        block["mlp_down_w"] = P(None, t, None)
    else:
        block["mlp_up_w"] = P(None, None, t)
        block["mlp_up_b"] = P(None, t)
        block["mlp_down_w"] = P(None, t, None)
    specs = {
        "wte": P(t, None),                   # vocab-parallel embedding
        "blocks": block,
        "lnf_scale": P(None),
    }
    if not cfg.use_rmsnorm:
        specs["lnf_bias"] = P(None)
    if not cfg.use_rotary and not cfg.use_alibi:
        specs["wpe"] = P(None, None)
    if cfg.use_emb_ln:
        specs["emb_ln_scale"] = P(None)
        specs["emb_ln_bias"] = P(None)
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(t, None)
    return specs


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------


def _norm(x, scale, bias, use_rms, eps=1e-5):
    xf = x.astype(jnp.float32)
    if use_rms:
        xf = xf * jax.lax.rsqrt(jnp.mean(jnp.square(xf), axis=-1, keepdims=True) + eps)
        return (xf * scale.astype(jnp.float32)).astype(x.dtype)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (xf * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


def _act(x, cfg):
    if cfg.activation == "relu":
        return jax.nn.relu(x)
    if cfg.activation == "quick_gelu":  # CLIP text encoder (x * sigmoid(1.702x))
        return x * jax.nn.sigmoid(1.702 * x)
    return jax.nn.gelu(x)


def _alibi_slopes(n_heads):
    """BLOOM/press-et-al alibi head slopes (geometric in 2^(-8/n); odd head
    counts get the interleaved extension)."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(n_heads).is_integer():
        slopes = pow2_slopes(n_heads)
    else:
        base = 2 ** math.floor(math.log2(n_heads))
        slopes = pow2_slopes(base)
        extra = pow2_slopes(2 * base)[0::2][: n_heads - base]
        slopes += extra
    return jnp.asarray(slopes, jnp.float32)


def _alibi_bias(cfg, q_positions, k_positions):
    """[H, Tq, S] additive attention bias: -slope_h * (t - s)."""
    dist = (q_positions[:, None] - k_positions[None, :]).astype(jnp.float32)
    return -_alibi_slopes(cfg.n_head)[:, None, None] * dist


def _window_mask(q_positions, k_positions, window):
    """Sliding-window validity [Tq, S]: key within `window` of the query."""
    dist = q_positions[:, None] - k_positions[None, :]
    return dist < window


def _rope(x, positions, rotary_dims, theta=10000.0):
    """Rotary position embedding over the first `rotary_dims` of the head dim.
    x: [B, T, H, hd]; positions: [B, T]."""
    hd = x.shape[-1]
    rd = rotary_dims
    freqs = 1.0 / (theta**(jnp.arange(0, rd, 2, dtype=jnp.float32) / rd))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,T,rd/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x_rot, x_pass = x[..., :rd], x[..., rd:]
    x1, x2 = x_rot[..., ::2], x_rot[..., 1::2]
    out1 = x1 * cos - x2 * sin
    out2 = x1 * sin + x2 * cos
    rotated = jnp.stack([out1, out2], axis=-1).reshape(x_rot.shape)
    return jnp.concatenate([rotated, x_pass], axis=-1).astype(x.dtype) if rd < hd \
        else rotated.astype(x.dtype)


SAVE_MATMULS_NAMES = ("qkv_proj", "attn_out", "mlp_up", "mlp_down")


def _ckpt_name(x, name):
    """Tag a tensor for the "save_matmuls" selective-remat policy."""
    from jax.ad_checkpoint import checkpoint_name
    return checkpoint_name(x, name)


def resolve_remat_policy(name):
    """remat_policy string → jax.checkpoint policy. "save_matmuls" keeps every
    tagged matmul output (the MXU-heavy tensors) so the backward recomputes
    only norms/softmax/elementwise — the cheap fraction of a block.
    "save_matmuls_probs" additionally keeps the [B,H,T,S] softmax probs, so
    the backward skips the attention-score recompute entirely — the fastest
    policy when HBM has room for ~B*H*T*S*2 bytes per layer (bf16 softmax)."""
    if name == "save_matmuls":
        return jax.checkpoint_policies.save_only_these_names(*SAVE_MATMULS_NAMES)
    if name == "save_matmuls_probs":
        return jax.checkpoint_policies.save_only_these_names(
            *SAVE_MATMULS_NAMES, "attn_probs")
    return getattr(jax.checkpoint_policies, name, None)


# Dispatch crossovers live in ops/attention_dispatch.py (ONE home for the
# predicates every attention call site shares); re-exported here for the
# callers that read the constants (tests, bench).
FLASH_MIN_SEQ = attn_dispatch.FLASH_MIN_SEQ
DECODE_KERNEL_MIN_CTX = attn_dispatch.DECODE_KERNEL_MIN_CTX


def _train_attn_site(cfg, T, S, has_bias, attn_fn):
    """Dispatch key for the training/prefill attention call sites."""
    return attn_dispatch.AttnSite(
        phase="train", q_len=T, kv_len=S, causal=True,
        has_bias=has_bias, has_window=bool(cfg.sliding_window),
        scale_attn=cfg.scale_attn,
        mesh_axes=attn_dispatch.active_mesh_axes(),
        force_flash=cfg.use_flash_attention,
        chunk_min=getattr(cfg, "chunked_attn_min_seq", None),
        backend=getattr(cfg, "attention_backend", None),
        external_fn=attn_fn is not None)


def _attention(q, k, v, causal_mask, cfg, attn_fn=None, bias=None):
    """q: [B, T, H, hd]; k,v: [B, S, Hkv, hd] → [B, T, H, hd]. fp32 softmax.

    GQA (Hkv < H): query heads are grouped per kv head and contracted without
    materializing repeated k/v (reference serves GQA models like llama2-70b via
    `module_inject/containers/llama2.py`). `bias`: additive [H, T, S] (alibi).

    Program selection goes through the unified dispatch layer
    (`ops/attention_dispatch.py`): flash at the measured crossover, the
    chunked escape hatch, ring / ring∘Ulysses context parallelism on
    request (`GPTConfig.attention_backend`), dense XLA otherwise — every
    registered program's runner is invoked through the same matched-heads
    external-fn path, so a new variant plugs in at the registry, not here."""
    program = attn_dispatch.select(
        _train_attn_site(cfg, q.shape[1], k.shape[1], bias is not None,
                         attn_fn))
    if program not in ("dense", "external"):
        runner = attn_dispatch.get_program(program).runner
        attn_fn = partial(runner, causal=True,
                          sm_scale=None if cfg.scale_attn else 1.0)
    if attn_fn is not None:
        if k.shape[2] != q.shape[2]:  # external kernels expect matched heads
            rep = q.shape[2] // k.shape[2]
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        return attn_fn(q, k, v)
    scale = 1.0 / math.sqrt(q.shape[-1]) if cfg.scale_attn else 1.0
    B, T, H, hd = q.shape
    Hkv = k.shape[2]
    G = H // Hkv  # grouped einsum; G == 1 is plain MHA
    sm_dtype = jnp.dtype(getattr(cfg, "softmax_dtype", jnp.float32))
    qg = q.reshape(B, T, Hkv, G, hd)
    logits = jnp.einsum("btkgd,bskd->bkgts", qg, k).astype(sm_dtype) * scale
    if bias is not None:
        S = k.shape[1]
        logits = logits + bias.reshape(Hkv, G, T, S)[None].astype(sm_dtype)
    neg = jnp.asarray(-1e30 if sm_dtype == jnp.float32 else -3e38, sm_dtype)
    logits = jnp.where(causal_mask[:, None], logits, neg)
    if sm_dtype == jnp.float32:
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    else:
        # reduced-precision softmax: the [T,S] score tensor stays bf16 (the
        # HBM-traffic hot spot); max-subtraction keeps exp well-conditioned
        # and the exp itself runs in fp32 before narrowing back
        m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
        e = jnp.exp((logits - m).astype(jnp.float32)).astype(q.dtype)
        denom = jnp.sum(e, axis=-1, keepdims=True, dtype=jnp.float32)
        probs = (e.astype(jnp.float32) / denom).astype(q.dtype)
    probs = _ckpt_name(probs, "attn_probs")
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(B, T, H, hd)


def _act_quant(x, cfg):
    """Activation fake-quant at a linear input, gated by the compression
    schedule (trace-time read; the engine retraces when the gate flips)."""
    gate = getattr(cfg, "act_quant", None)
    if gate is None or not gate.active:
        return x
    from deepspeed_tpu.compression.basic_layer import quantize_activation
    return quantize_activation(x, gate.bits, symmetric=gate.symmetric)


def _mlp(h, p, cfg, constrain=True):
    """MLP half-block: gated (swiglu) or plain with configurable activation.
    `constrain=False` on the decode path ([B, 1, F] can't shard on sequence)."""
    h = _act_quant(h, cfg)
    if cfg.use_swiglu:
        up = jax.nn.silu(h @ p["mlp_gate_w"]) * (h @ p["mlp_up_w"])
    else:
        up = _act(h @ p["mlp_up_w"] + p["mlp_up_b"], cfg)
    up = _ckpt_name(up, "mlp_up")
    if constrain:
        up = shard_constraint(up, BATCH_AXES, SEQ_AXIS, TENSOR_AXIS)
    up = _act_quant(up, cfg)
    return _ckpt_name(up @ p["mlp_down_w"] + p["mlp_out_b"], "mlp_down")


def _layer_local_flags(cfg: GPTConfig):
    """attn_layer_types → bool[L] scan data (None when uniform attention)."""
    if cfg.attn_layer_types is None:
        return None
    assert cfg.sliding_window, "attn_layer_types needs sliding_window set"
    return jnp.asarray([t == "local" for t in cfg.attn_layer_types], bool)


def _attn_half(x, p, cfg: GPTConfig, positions, attn_fn=None, constrain=True,
               local_flag=None):
    """Attention half-block: ln1 → qkv → rope → masked attention → out-proj.

    Returns (attn_out, k, v) with k/v [B, T, Hkv, hd] so decode-model prefill
    can write them into the KV cache. Every architecture flag (rotary, alibi,
    sliding window, GQA) is honored here, in ONE place, for the training
    forward, the MoE blocks, and the inference prefill alike."""
    B, T, D = x.shape
    H, Hkv, hd = cfg.n_head, cfg.n_kv_head, cfg.head_dim

    h = _norm(x, p["ln1_scale"], p.get("ln1_bias"), cfg.use_rmsnorm, cfg.norm_eps)
    h = _act_quant(h, cfg)
    qkv = _ckpt_name(h @ p["attn_qkv_w"] + p["attn_qkv_b"], "qkv_proj")
    q, k, v = jnp.split(qkv, [H * hd, (H + Hkv) * hd], axis=-1)
    q = q.reshape(B, T, H, hd)
    k = k.reshape(B, T, Hkv, hd)
    v = v.reshape(B, T, Hkv, hd)
    if constrain:
        # activations: heads on tensor axis (Megatron), seq on sequence axis
        q = shard_constraint(q, BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, None)
        k = shard_constraint(k, BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, None)
        v = shard_constraint(v, BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, None)
    if cfg.use_rotary:
        rd = int(cfg.rotary_pct * hd) // 2 * 2
        q = _rope(q, positions, rd, cfg.rope_theta)
        k = _rope(k, positions, rd, cfg.rope_theta)
    t_pos = jnp.arange(T, dtype=jnp.int32)
    causal = jnp.tril(jnp.ones((T, T), bool))
    if cfg.sliding_window:
        win = causal & _window_mask(t_pos, t_pos, cfg.sliding_window)
        if local_flag is None:
            causal = win
        else:  # GPT-Neo alternating global/local: flag is per-layer scan data
            causal = jnp.where(local_flag, win, causal)
    causal = causal[None, None, :, :]
    # alibi uses in-sequence distances (standard unpadded formulation)
    bias = _alibi_bias(cfg, t_pos, t_pos) if cfg.use_alibi else None
    attn = _attention(q, k, v, causal, cfg, attn_fn=attn_fn, bias=bias)
    attn_flat = _act_quant(attn.reshape(B, T, D), cfg)
    attn_out = _ckpt_name(
        attn_flat @ p["attn_out_w"] + p["attn_out_b"], "attn_out")
    return attn_out, k, v


def _residual_mlp(x, attn_out, p, cfg: GPTConfig, constrain=True, mlp_fn=None):
    """Residual second half of a block; `mlp_fn` lets MoE swap the dense MLP."""
    if mlp_fn is None:
        mlp_fn = lambda h: _mlp(h, p, cfg, constrain)
    use_rms = cfg.use_rmsnorm
    if cfg.parallel_residual:
        # NeoX/GPT-J: both halves read the block INPUT (GPT-J ties ln2 == ln1)
        h2 = _norm(x, p["ln2_scale"], p.get("ln2_bias"), use_rms, cfg.norm_eps)
        return x + attn_out + mlp_fn(h2)
    x = x + attn_out
    h2 = _norm(x, p["ln2_scale"], p.get("ln2_bias"), use_rms, cfg.norm_eps)
    return x + mlp_fn(h2)


def _head_table(params, cfg: GPTConfig):
    """The (tied) LM-head weight table [V, D] — single source of truth."""
    return params["lm_head"] if not cfg.tie_embeddings else params["wte"]


def _head_logits(params, x, cfg: GPTConfig):
    """LM-head matmul (+ GPT-J's tied bias). x: [B, T, D] -> [B, T, V]."""
    logits = jnp.einsum("btd,vd->btv", x, _head_table(params, cfg).astype(x.dtype))
    if "lm_head_bias" in params:  # GPT-J ties a bias to the LM head
        logits = logits + params["lm_head_bias"].astype(logits.dtype)
    return logits


def _lm_head(params, x, cfg: GPTConfig):
    """Final norm + (tied) LM head. x: [B, T, D] -> logits [B, T, V]."""
    x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), cfg.use_rmsnorm,
              cfg.norm_eps)
    return _head_logits(params, x, cfg)


def _embed(params, tokens, positions, cfg: GPTConfig):
    """Token embedding + (absolute) position embedding + BLOOM emb LayerNorm.

    The tables are constrained to their gathered (TP-only) layout before the
    lookup: under ZeRO-3 the policy shards their feature dim over the zero
    domain, and XLA cannot reshard a gather whose operand is feature-sharded
    without a full replicate-then-partition of the output (SPMD partitioner
    warning). Constraining the *table* instead makes the all-gather explicit —
    exactly ZeRO-3's gather-before-use (reference
    `zero/partitioned_param_coordinator.py:256`) — after which the output
    transition to batch/seq sharding is a cheap slice."""
    wte = shard_constraint(params["wte"], TENSOR_AXIS, None)
    x = jnp.take(wte, tokens, axis=0).astype(cfg.dtype)
    if not cfg.use_rotary and not cfg.use_alibi:
        wpe = shard_constraint(params["wpe"], None, None)
        x = x + jnp.take(wpe, positions, axis=0).astype(cfg.dtype)
    if cfg.use_emb_ln:  # BLOOM word-embedding LayerNorm
        x = _norm(x, params["emb_ln_scale"], params.get("emb_ln_bias"),
                  use_rms=False, eps=cfg.norm_eps)
    return x


def _block(x, p, cfg: GPTConfig, positions, dropout_rng=None, attn_fn=None,
           local_flag=None):
    """One transformer block. x: [B, T, D]."""
    attn_out, _, _ = _attn_half(x, p, cfg, positions, attn_fn=attn_fn,
                                local_flag=local_flag)
    x = _residual_mlp(x, attn_out, p, cfg)
    return shard_constraint(x, BATCH_AXES, SEQ_AXIS, None)


def gpt_hidden(params, tokens, cfg: GPTConfig, positions=None, attn_fn=None,
               pld=None, ltd=None):
    """tokens: [B, T] int32 → final-norm'd hidden states [B, T, D].

    `pld`: (keep_idx [n_keep] int32, theta scalar) — progressive layer drop
    (reference `runtime/progressive_layer_drop.py`): only the kept layers'
    params are gathered and scanned (real flop savings — one compiled
    program per kept count), each kept layer's residual delta rescaled by
    1/theta (inverted stochastic depth, expectation-preserving).

    `ltd`: (start_layer int, keep_idx [B, n_ltd, K] int32) — random-LTD
    (reference `data_routing/basic_layer.py`): layers [start, start+n_ltd)
    process only each sample's K kept token positions (gather → block →
    scatter); dropped tokens bypass those layers unchanged. Attention inside
    the subset stays causal in ORIGINAL positions (indices arrive sorted);
    rotary embeddings read the true positions.
    """
    B, T = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    x = _embed(params, tokens, positions, cfg)
    x = shard_constraint(x, BATCH_AXES, SEQ_AXIS, None)

    flags = _layer_local_flags(cfg)
    if flags is None:
        block_fn = partial(_block, cfg=cfg, positions=positions, attn_fn=attn_fn)
    else:
        def block_fn(x, layer_params, flag):
            return _block(x, layer_params, cfg=cfg, positions=positions,
                          attn_fn=attn_fn, local_flag=flag)
    if cfg.remat:
        block_fn = jax.checkpoint(block_fn, policy=resolve_remat_policy(cfg.remat_policy),
                                  prevent_cse=cfg.remat_prevent_cse)

    if pld is not None:
        assert flags is None and ltd is None, \
            "progressive_layer_drop composes with neither per-layer attention "\
            "flags nor random-LTD"
        keep_idx, theta = pld
        kept = jax.tree_util.tree_map(
            lambda l: jnp.take(l, keep_idx, axis=0), params["blocks"])
        inv = (1.0 / jnp.maximum(theta, 1e-6)).astype(x.dtype)

        def pld_body(x, layer_params):
            return x + (block_fn(x, layer_params) - x) * inv, None

        x, _ = jax.lax.scan(pld_body, x, kept, unroll=cfg.scan_unroll)
    elif ltd is not None:
        assert flags is None, "random-LTD needs uniform attention layers"
        assert attn_fn is None, \
            "random-LTD gathers token subsets; a custom attn_fn with a " \
            "T-static layout cannot run on them"
        assert not cfg.use_alibi and not cfg.sliding_window, \
            "random-LTD subset attention does not carry alibi/window masks yet"
        start, kidx = ltd
        n_ltd = kidx.shape[1]
        blocks = params["blocks"]
        pre = jax.tree_util.tree_map(lambda l: l[:start], blocks)
        mid = jax.tree_util.tree_map(lambda l: l[start:start + n_ltd], blocks)
        post = jax.tree_util.tree_map(lambda l: l[start + n_ltd:], blocks)

        def sub_block(sx, lp, pos):
            return _block(sx, lp, cfg=cfg, positions=pos, attn_fn=None)
        if cfg.remat:
            sub_block = jax.checkpoint(
                sub_block, policy=resolve_remat_policy(cfg.remat_policy),
                prevent_cse=cfg.remat_prevent_cse)

        def plain_body(x, layer_params):
            return block_fn(x, layer_params), None

        def mid_body(carry, inp):
            lp, kx = inp                                  # kx: [B, K]
            sub = jnp.take_along_axis(carry, kx[..., None], axis=1)
            sub_out = sub_block(sub, lp, kx)
            carry = carry.at[jnp.arange(carry.shape[0])[:, None], kx].set(
                sub_out.astype(carry.dtype))
            return carry, None

        x, _ = jax.lax.scan(plain_body, x, pre, unroll=cfg.scan_unroll)
        x, _ = jax.lax.scan(mid_body, x, (mid, jnp.moveaxis(kidx, 0, 1)),
                            unroll=cfg.scan_unroll)
        x, _ = jax.lax.scan(plain_body, x, post, unroll=cfg.scan_unroll)
    elif flags is None:
        def scan_body(x, layer_params):
            return block_fn(x, layer_params), None
        x, _ = jax.lax.scan(scan_body, x, params["blocks"],
                            unroll=cfg.scan_unroll)
    else:
        def scan_body(x, inputs):
            layer_params, flag = inputs
            return block_fn(x, layer_params, flag), None
        x, _ = jax.lax.scan(scan_body, x, (params["blocks"], flags),
                            unroll=cfg.scan_unroll)

    return _norm(x, params["lnf_scale"], params.get("lnf_bias"), cfg.use_rmsnorm,
                 cfg.norm_eps)


def gpt_forward(params, tokens, cfg: GPTConfig, positions=None, attn_fn=None):
    """tokens: [B, T] int32 → logits [B, T, vocab]."""
    x = gpt_hidden(params, tokens, cfg, positions=positions, attn_fn=attn_fn)
    return _head_logits(params, x, cfg)


def gpt_loss(params, batch, rng, cfg: GPTConfig, attn_fn=None):
    """Causal-LM cross entropy. batch: {"tokens": [B,T]} or {"input_ids", "labels"}."""
    tokens = batch.get("tokens", batch.get("input_ids"))
    labels = batch.get("labels")
    if labels is None:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
    else:
        inputs = tokens
    # engine-injected routing directives (engine._inject_routing_directives):
    # broadcast over the batch dim; counts ride in the SHAPES (static)
    pld = ltd = None
    if "pld_keep_idx" in batch:
        pld = (batch["pld_keep_idx"][0], batch["pld_theta"][0])
    if "ltd_keep_idx" in batch:
        ltd = (batch["ltd_start"].shape[1], batch["ltd_keep_idx"])
    if cfg.loss_chunks:
        from deepspeed_tpu.ops.chunked_ce import chunked_softmax_xent
        B, T = inputs.shape
        x = gpt_hidden(params, inputs, cfg, attn_fn=attn_fn, pld=pld, ltd=ltd)
        assert "lm_head_bias" not in params, \
            "chunked CE does not support a tied LM-head bias"
        head = _head_table(params, cfg)
        nll = chunked_softmax_xent(x.reshape(B * T, -1), head.astype(x.dtype),
                                   labels.reshape(B * T), cfg.loss_chunks)
        mask = (labels.reshape(B * T) >= 0).astype(jnp.float32)
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    x = gpt_hidden(params, inputs, cfg, attn_fn=attn_fn, pld=pld, ltd=ltd)
    logits = _head_logits(params, x, cfg)
    # cross entropy WITHOUT materializing an fp32 [B,T,V] buffer (1.65G at
    # mbs16/seq512/50k vocab): logits stay in compute dtype, the exp/sum runs
    # with an fp32 accumulator fused into the reduction, and only [B,T]
    # tensors ever exist in fp32.
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    sumexp = jnp.sum(jnp.exp((logits - m).astype(jnp.float32)), axis=-1)
    logz = m[..., 0].astype(jnp.float32) + jnp.log(sumexp)
    safe_labels = jnp.maximum(labels, 0)  # ignore-index (<0) must not wrap
    gold = jnp.take_along_axis(logits, safe_labels[..., None],
                               axis=-1)[..., 0].astype(jnp.float32)
    mask = (labels >= 0).astype(jnp.float32)
    nll = (logz - gold) * mask
    return nll.sum() / jnp.maximum(mask.sum(), 1.0)


def make_gpt_model(cfg: GPTConfig = None, name="gpt2-125m", seed=0, attn_fn=None,
                   abstract=False) -> ModelSpec:
    """ModelSpec for the training engine.

    `abstract=True` returns a spec with init_fn instead of concrete params —
    the engine then materializes each leaf directly into its ZeRO/TP shard
    (zero.Init, `zero/partition_parameters.py:723`)."""
    cfg = cfg or GPT2_CONFIGS[name]
    return ModelSpec(
        loss_fn=partial(gpt_loss, cfg=cfg, attn_fn=attn_fn),
        params=None if abstract else init_gpt_params(cfg, seed=seed),
        init_fn=gpt_init_fn(cfg) if abstract else None,
        arch_cfg=cfg,
        # same attention on the eval/inference forward as in training (a
        # sparse/custom attn_fn must not silently fall back to dense here)
        apply_fn=partial(gpt_forward, cfg=cfg, attn_fn=attn_fn),
        param_specs=gpt_param_specs(cfg),
        name=name,
    )


# ----------------------------------------------------------------------
# decode path (KV cache) — for the inference engine
# ----------------------------------------------------------------------


def init_kv_cache(cfg: GPTConfig, batch_size, max_len, dtype=jnp.bfloat16):
    """[L, B, Hkv, max_len, hd] stacked cache (reference: InferenceContext
    workspace, `csrc/transformer/inference/includes/inference_context.h:49`).
    Head-major layout so the decode kernel streams one head's K/V contiguously.

    Blocked layout: when max_len is a whole number of KV blocks the
    streaming decode kernel addresses the contiguous M axis as
    [num_blocks, block, hd] tiles (a free reshape). The inference engine
    rounds max_len up via `TpuInferenceConfig.kv_block_size`
    (`InferenceEngine._cache_len`) so decode steps never pay a runtime
    pad-to-block copy of the whole cache."""
    shape = (cfg.n_layer, batch_size, cfg.n_kv_head, max_len, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
            "length": jnp.zeros((batch_size,), jnp.int32)}


def _decode_qkv(x, p, positions, cfg: GPTConfig):
    """Shared decode-path preamble: ln1 -> fused qkv -> split/reshape ->
    rope at absolute positions. One definition for the contiguous-cache
    half AND the paged half — a rope/GQA change cannot diverge them.
    x: [B, C, D]; positions: [B, C]. Returns q [B,C,H,hd], k/v [B,C,Hkv,hd].
    (The training `_attn_half` stays separate: it additionally threads
    act-quant gates, remat checkpoint names, and shard constraints.)"""
    B, C, _ = x.shape
    H, Hkv, hd = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    h = _norm(x, p["ln1_scale"], p.get("ln1_bias"), cfg.use_rmsnorm,
              cfg.norm_eps)
    qkv = h @ p["attn_qkv_w"] + p["attn_qkv_b"]
    q, k, v = jnp.split(qkv, [H * hd, (H + Hkv) * hd], axis=-1)
    q = q.reshape(B, C, H, hd)
    k = k.reshape(B, C, Hkv, hd)
    v = v.reshape(B, C, Hkv, hd)
    if cfg.use_rotary:
        rd = int(cfg.rotary_pct * hd) // 2 * 2
        q = _rope(q, positions, rd, cfg.rope_theta)
        k = _rope(k, positions, rd, cfg.rope_theta)
    return q, k, v


def _decode_attn_site(cfg: GPTConfig, phase, C, M, kv_dtype="bfloat16",
                      block_size=0):
    """Dispatch key for the decode/paged call sites. The engage rule itself
    (`attn_dispatch.decode_kernel_wanted`) has ONE definition shared by the
    contiguous path (M = allocated cache length) and the paged path
    (M = table_width * block = the effective context)."""
    return attn_dispatch.AttnSite(
        phase=phase, q_len=C, kv_len=M, causal=True,
        has_bias=cfg.use_alibi, has_window=bool(cfg.sliding_window),
        scale_attn=cfg.scale_attn, kv_dtype=kv_dtype, block_size=block_size,
        mesh_axes=attn_dispatch.active_mesh_axes(),
        force_flash=cfg.use_flash_attention)


def _decode_attn_half(x, p, cache_k, cache_v, pos, cfg: GPTConfig,
                      local_flag=None):
    """Single-token attention half: writes k/v at `pos` into the head-major
    cache and attends over it. x: [B, 1, D]; cache_[kv]: [B, Hkv, M, hd];
    pos: [B]. Returns (attn_out, cache_k, cache_v)."""
    B, _, D = x.shape
    H, Hkv, hd = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    M = cache_k.shape[2]
    q, k, v = _decode_qkv(x, p, pos[:, None], cfg)

    # write k,v at pos via one-hot masked rewrite. Counterintuitive but
    # measured: streaming the whole [B,Hkv,M,hd] cache through fused
    # elementwise ops beats a batched scatter inside the decode scan on TPU
    # (3.2 vs 3.9 ms/token, gpt2-125m bs8 M=576 — scatter breaks the carry's
    # in-place update); revisit if XLA's scatter lowering improves
    # cache dtype wins (mirrors prefill's .astype(ck.dtype)): without the
    # casts, a model whose compute dtype is wider than kv_cache_dtype (e.g.
    # fp32-adapted HF weights + bf16 cache) promotes the rewrite to fp32 and
    # the decode scan carry dtype flips
    onehot = jax.nn.one_hot(pos, M, dtype=cache_k.dtype)      # [B, M]
    k_new = jnp.moveaxis(k, 1, 2).astype(cache_k.dtype)       # [B, Hkv, 1, hd]
    v_new = jnp.moveaxis(v, 1, 2).astype(cache_v.dtype)
    cache_k = cache_k * (1 - onehot)[:, None, :, None] + onehot[:, None, :, None] * k_new
    cache_v = cache_v * (1 - onehot)[:, None, :, None] + onehot[:, None, :, None] * v_new

    # decode kernel: explicit True forces it; auto engages from
    # DECODE_KERNEL_MIN_CTX — the blocked streaming kernel reads only the
    # live prefix of the cache while the XLA einsum reads the whole
    # allocated M every step (at short contexts XLA already sits at the
    # bandwidth floor: r5 174-204us vs kernel 189us at ctx 8k)
    # auto additionally requires a block-tileable M (128-multiple): an
    # unrounded cache would otherwise pay a whole-cache pad-to-block copy
    # INSIDE every jitted decode step (the engine's kv_block_size rounding
    # guarantees this; direct callers with odd M stay on XLA). Alibi/window
    # archs disqualify the kernel — all through the dispatch registry.
    program = attn_dispatch.select(_decode_attn_site(cfg, "decode", 1, M))
    if program not in ("decode_kernel", "decode_dense"):
        # the decode/paged sites dispatch BY NAME (their call signatures
        # carry cache state the train-phase runner protocol doesn't):
        # an unknown registered program must fail loudly here, not fall
        # into a numerically-different path
        raise NotImplementedError(
            f"attention program {program!r} selected for the contiguous "
            f"decode site has no handler in models/gpt.py — non-train "
            f"phases dispatch by name; add a branch for it here")
    if program == "decode_kernel":
        from deepspeed_tpu.ops.pallas.decode_attention import decode_attention
        attn = decode_attention(
            q[:, 0], cache_k, cache_v, pos,
            # honor scale_attn=False (GPT-Neo): the kernel defaults to
            # 1/sqrt(hd) when sm_scale is None
            sm_scale=None if cfg.scale_attn else 1.0).reshape(B, 1, D)
    else:
        scale = 1.0 / math.sqrt(hd) if cfg.scale_attn else 1.0
        m_pos = jnp.arange(M)
        valid = (m_pos[None, :] <= pos[:, None])              # [B, M]
        if cfg.sliding_window:
            win = valid & (pos[:, None] - m_pos[None, :] < cfg.sliding_window)
            valid = win if local_flag is None else jnp.where(local_flag, win, valid)
        G = H // Hkv  # grouped einsum; G == 1 is plain MHA
        qg = q.reshape(B, Hkv, G, hd)
        logits = jnp.einsum("bkgd,bkmd->bkgm", qg, cache_k).astype(jnp.float32) * scale
        if cfg.use_alibi:
            dist = (pos[:, None] - m_pos[None, :]).astype(jnp.float32)  # [B, M]
            bias = -_alibi_slopes(H).reshape(Hkv, G)[None, :, :, None] * \
                dist[:, None, None, :]
            logits = logits + bias
        logits = jnp.where(valid[:, None, None, :], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        attn = jnp.einsum("bkgm,bkmd->bkgd", probs, cache_v).reshape(B, 1, D)
    attn_out = attn @ p["attn_out_w"] + p["attn_out_b"]
    return attn_out, cache_k, cache_v


def _block_decode(x, p, cache_k, cache_v, pos, cfg: GPTConfig, local_flag=None):
    """Single-token decode for one block."""
    attn_out, cache_k, cache_v = _decode_attn_half(x, p, cache_k, cache_v, pos,
                                                   cfg, local_flag=local_flag)
    x = _residual_mlp(x, attn_out, p, cfg, constrain=False)
    return x, cache_k, cache_v


def gpt_cache_identity(cfg: GPTConfig, name: str = "") -> str:
    """Cache-identity fingerprint for the prefix cache's hash chain
    (`DecodeModelSpec.cache_fingerprint`): every arch field that changes the
    KV VALUES a prompt writes into the paged pool — layer/head geometry,
    position encoding (learned wpe vs rotary incl. theta/pct, alibi),
    normalization, embedding LayerNorm — plus the spec name. Two configs
    differing in any of these can never serve each other's cached blocks
    even on identical token streams. Weights are engine-local (the cache
    lives inside one ServingEngine), so parameters are deliberately not
    hashed."""
    fields = (name, cfg.vocab_size, cfg.n_layer, cfg.n_head, cfg.n_kv_head,
              cfg.d_model, cfg.d_ff, cfg.max_seq_len, cfg.use_rotary,
              cfg.rotary_pct, cfg.rope_theta, cfg.use_alibi, cfg.use_emb_ln,
              cfg.use_rmsnorm, cfg.norm_eps, cfg.sliding_window,
              cfg.attn_layer_types, cfg.scale_attn, cfg.parallel_residual,
              cfg.use_swiglu, cfg.activation, jnp.dtype(cfg.dtype).name,
              jnp.dtype(cfg.softmax_dtype).name)
    return "gpt:" + "|".join(map(str, fields))


def make_gpt_decode_model(cfg: GPTConfig = None, name="gpt2-125m", params=None, seed=0):
    """DecodeModelSpec for the inference engine (prefill + per-token decode)."""
    from deepspeed_tpu.inference.engine import DecodeModelSpec
    cfg = cfg or GPT2_CONFIGS[name]
    if params is None:
        params = init_gpt_params(cfg, seed=seed)

    def prefill_fn(params, tokens, cache, pad_mask):
        B, T = tokens.shape
        # single pass: compute activations AND populate the KV cache in one scan
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
        x = _embed(params, tokens, positions, cfg)

        flags = _layer_local_flags(cfg)

        def body(x, inputs, flag=None):
            p, ck, cv = inputs
            attn_out, k, v = _attn_half(x, p, cfg, positions, local_flag=flag)
            ck = ck.at[:, :, :T].set(jnp.moveaxis(k, 1, 2).astype(ck.dtype))
            cv = cv.at[:, :, :T].set(jnp.moveaxis(v, 1, 2).astype(cv.dtype))
            x = _residual_mlp(x, attn_out, p, cfg)
            return x, (ck, cv)

        layers = (params["blocks"], cache["k"], cache["v"])
        if flags is None:
            x, (ks, vs) = jax.lax.scan(body, x, layers)
        else:
            x, (ks, vs) = jax.lax.scan(
                lambda c, inp: body(c, inp[0], flag=inp[1]), x, (layers, flags))
        logits = _lm_head(params, x, cfg)
        cache = {"k": ks, "v": vs, "length": jnp.full((B,), T, jnp.int32)}
        return logits, cache

    def decode_fn(params, token, pos, cache):
        B = token.shape[0]
        x = _embed(params, token[:, None], pos[:, None], cfg)

        flags = _layer_local_flags(cfg)

        def body(x, inputs, flag=None):
            p, ck, cv = inputs
            x, ck, cv = _block_decode(x, p, ck, cv, pos, cfg, local_flag=flag)
            return x, (ck, cv)

        layers = (params["blocks"], cache["k"], cache["v"])
        if flags is None:
            x, (ks, vs) = jax.lax.scan(body, x, layers)
        else:
            x, (ks, vs) = jax.lax.scan(
                lambda c, inp: body(c, inp[0], flag=inp[1]), x, (layers, flags))
        logits = _lm_head(params, x, cfg)[:, 0]
        cache = {"k": ks, "v": vs, "length": cache["length"] + 1}
        return logits, cache

    def init_cache(batch_size, max_len, dtype=jnp.bfloat16):
        return init_kv_cache(cfg, batch_size, max_len, dtype)

    # paged-pool serving contract (see DecodeModelSpec): both fns scan the
    # stacked blocks with the pool's layer axis as scan data, exactly like
    # the contiguous cache path, so layer count stays out of compile time

    def _scan_paged(params, x, pool, block_tables, positions, phase=None):
        # the pool rides the scan as a PYTREE of [L, ...] leaves (k/v, plus
        # the int8 pool's k_scale/v_scale), so the quantized and fp layouts
        # share one scan body — the per-layer slice arrives as a dict.
        # `phase` labels the dispatch site ("verify" for the spec-decode
        # chunk; None = derive decode/prefill from the chunk width)
        flags = _layer_local_flags(cfg)

        def body(x, inputs, flag=None):
            p, pool_l = inputs
            x, pool_l = _block_paged(x, p, pool_l, positions, block_tables,
                                     cfg, local_flag=flag, phase=phase)
            return x, pool_l

        layers = (params["blocks"], pool)
        if flags is None:
            x, pool = jax.lax.scan(body, x, layers)
        else:
            x, pool = jax.lax.scan(
                lambda c, inp: body(c, inp[0], flag=inp[1]), x, (layers, flags))
        return x, pool

    def prefill_paged_fn(params, tokens, start_pos, last_idx, pool,
                         block_tables):
        B, C = tokens.shape
        positions = start_pos[:, None] + jnp.arange(C, dtype=jnp.int32)[None]
        x = _embed(params, tokens, positions, cfg)
        x, pool = _scan_paged(params, x, pool, block_tables, positions)
        last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)
        logits = _lm_head(params, last, cfg)[:, 0]
        return logits, pool

    def decode_paged_fn(params, token, pos, pool, block_tables):
        x = _embed(params, token[:, None], pos[:, None], cfg)
        x, pool = _scan_paged(params, x, pool, block_tables, pos[:, None])
        logits = _lm_head(params, x, cfg)[:, 0]
        return logits, pool

    def verify_paged_fn(params, tokens, pos, pool, block_tables):
        """Speculative-decoding verify: score C tokens per row in ONE pass
        at an arbitrary cursor. Identical machinery to a prefill chunk —
        `_paged_attend`'s absolute-position causal mask already lets row b's
        positions start anywhere — but the logits of EVERY position come
        back, not just the last: row i's argmax is the greedy ground truth
        for draft i+1 and the bonus token at the first disagreement."""
        B, C = tokens.shape
        positions = pos[:, None] + jnp.arange(C, dtype=jnp.int32)[None]
        x = _embed(params, tokens, positions, cfg)
        x, pool = _scan_paged(params, x, pool, block_tables, positions,
                              phase="verify")
        logits = _lm_head(params, x, cfg)
        return logits, pool

    def init_paged_pool(num_blocks, block_size, dtype=jnp.bfloat16,
                        kv_group_size=0):
        return init_paged_kv_pool(cfg, num_blocks, block_size, dtype,
                                  kv_group_size)

    return DecodeModelSpec(prefill_fn=prefill_fn, decode_fn=decode_fn,
                           init_cache=init_cache, params=params, name=name,
                           prefill_paged_fn=prefill_paged_fn,
                           decode_paged_fn=decode_paged_fn,
                           verify_paged_fn=verify_paged_fn,
                           init_paged_pool=init_paged_pool,
                           cache_fingerprint=gpt_cache_identity(cfg, name))


# ----------------------------------------------------------------------
# paged decode path — for the continuous-batching serving engine
# (inference/scheduler.py): KV lives in a shared pool of physical blocks,
# each slot addresses it through a block table
# ----------------------------------------------------------------------


def init_paged_kv_pool(cfg: GPTConfig, num_blocks, block_size,
                       dtype=jnp.bfloat16, kv_group_size=0):
    """[L, num_blocks, Hkv, block, hd] physical-block pool, allocated ONCE at
    serving-engine init (vLLM's PagedAttention layout on the blocked cache
    unit). Block 0 is the trash block (inference/kv_cache.py): inactive
    slots' writes land there so the fixed-shape decode step never branches
    on liveness.

    `dtype=int8` selects the QUANTIZED pool (`ServingConfig.quantization.
    kv_cache_dtype`): the k/v payload is symmetric per-group int8 and the
    pool grows `k_scale`/`v_scale` f32 leaves [L, N, Hkv, block, hd//g]
    (`kv_group_size` g, 0 = head_dim — one scale per written K/V vector per
    head). Scales share the physical-block axis with the payload, so every
    block-indexed operation — transplant handoff, the prefix cache's
    content-immutable sharing, the pool auditor — carries a block's scales
    with its bytes automatically. Zero-init is safe: a trash-block read
    dequantizes to exact zeros, garbage rows callers already ignore."""
    shape = (cfg.n_layer, num_blocks, cfg.n_kv_head, block_size, cfg.head_dim)
    pool = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if jnp.dtype(dtype) == jnp.int8:
        g = int(kv_group_size) or cfg.head_dim
        if g < 1 or cfg.head_dim % g != 0:
            raise ValueError(
                f"init_paged_kv_pool: kv_group_size {g} does not tile "
                f"head_dim {cfg.head_dim} (one scale per {g}-element group "
                f"of each K/V vector)")
        sshape = shape[:-1] + (cfg.head_dim // g,)
        pool["k_scale"] = jnp.zeros(sshape, jnp.float32)
        pool["v_scale"] = jnp.zeros(sshape, jnp.float32)
    return pool


def _paged_attend(q, k_ctx, v_ctx, q_pos, cfg: GPTConfig, local_flag=None):
    """Attend q over table-gathered KV with ABSOLUTE positions.

    q: [B, C, H, hd] (C = 1 for decode, = chunk length for chunked prefill);
    k_ctx/v_ctx: [B, Hkv, S, hd] in logical order (S = nb * block — gathered
    rows ARE position order, so k index == absolute position); q_pos: [B, C].
    Causal/window masks and alibi bias are built from absolute positions
    per row — unlike the training path, two rows of a serving batch sit at
    different positions. Returns [B, C, H*hd]; fp32 softmax."""
    B, C, H, hd = q.shape
    Hkv, S = k_ctx.shape[1], k_ctx.shape[2]
    G = H // Hkv
    scale = 1.0 / math.sqrt(hd) if cfg.scale_attn else 1.0
    k_pos = jnp.arange(S, dtype=jnp.int32)
    valid = k_pos[None, None, :] <= q_pos[:, :, None]          # [B, C, S]
    if cfg.sliding_window:
        win = valid & (q_pos[:, :, None] - k_pos[None, None, :]
                       < cfg.sliding_window)
        valid = win if local_flag is None else jnp.where(local_flag, win, valid)
    qg = q.reshape(B, C, Hkv, G, hd)
    logits = jnp.einsum("bckgd,bksd->bkgcs", qg,
                        k_ctx).astype(jnp.float32) * scale
    if cfg.use_alibi:
        dist = (q_pos[:, :, None] - k_pos[None, None, :]).astype(jnp.float32)
        logits = logits - (_alibi_slopes(H).reshape(Hkv, G)[None, :, :, None, None]
                           * dist[:, None, None, :, :])
    logits = jnp.where(valid[:, None, None, :, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgcs,bksd->bckgd", probs, v_ctx)
    return out.reshape(B, C, H * hd)


def _paged_attn_half(x, p, pool_l, positions, block_tables,
                     cfg: GPTConfig, local_flag=None, phase=None):
    """Attention half-block against one layer's paged pool.

    x: [B, C, D]; pool_l: one layer's pool slice — ``k``/``v``
    [N, Hkv, block, hd] plus, for the int8 quantized pool,
    ``k_scale``/``v_scale`` [N, Hkv, block, hd//g]; positions: [B, C]
    absolute; block_tables: [B, nb]. Writes the C new tokens' k/v into each
    row's blocks (logical position -> table -> physical block scatter), then
    attends over the row's whole table. Returns (attn_out, pool_l).

    Quantized pool: K/V are quantized AT CACHE-WRITE TIME (symmetric
    per-group int8 + f32 scales, `quantization.quantize_kv` — the same
    scheme as `ops/pallas/quant.py`), so fp K/V for the cached prefix never
    materializes in HBM. Reads dequantize on the fly: the single-token
    kernel path dequantizes each streamed tile inside the Pallas KV-grid
    walk (`paged_decode_attention_quant`), and the gather path (chunked
    prefill, the spec-decode verify chunk, CPU/arch-flag fallbacks) runs
    the dequantizing gather oracle — one shared numeric definition, so the
    two are parity-testable tile for tile.
    """
    from deepspeed_tpu.inference.kv_cache import (gather_block_kv,
                                                  gather_block_kv_dequant)

    B, C, D = x.shape
    bs = pool_l["k"].shape[2]
    nb = block_tables.shape[1]
    quantized = "k_scale" in pool_l

    q, k, v = _decode_qkv(x, p, positions, cfg)

    # scatter the new k/v through the table: logical block = pos // bs,
    # physical block = table[row, logical], offset = pos % bs. Rows of
    # inactive slots (all-trash tables, pos 0) collide in the trash block —
    # duplicate-index scatter order is unspecified there and irrelevant.
    blk = jnp.take_along_axis(block_tables, positions // bs, axis=1)  # [B, C]
    off = positions % bs
    pool_l = dict(pool_l)
    if quantized:
        from deepspeed_tpu.inference.quantization import quantize_kv
        g = cfg.head_dim // pool_l["k_scale"].shape[-1]
        qk, sk = quantize_kv(k, g)
        qv, sv = quantize_kv(v, g)
        pool_l["k"] = pool_l["k"].at[blk, :, off, :].set(qk)
        pool_l["v"] = pool_l["v"].at[blk, :, off, :].set(qv)
        pool_l["k_scale"] = pool_l["k_scale"].at[blk, :, off, :].set(sk)
        pool_l["v_scale"] = pool_l["v_scale"].at[blk, :, off, :].set(sv)
    else:
        pool_l["k"] = pool_l["k"].at[blk, :, off, :].set(
            k.astype(pool_l["k"].dtype))
        pool_l["v"] = pool_l["v"].at[blk, :, off, :].set(
            v.astype(pool_l["v"].dtype))

    # single-token steps ride the paged Pallas kernel when it is worth it:
    # same engage rule as the contiguous decode path (forced, or auto at
    # serving-scale effective context nb*bs), PLUS the paged-only
    # constraints: the kernel's no-bias/no-window contract, a lane-aligned
    # pool block (it cannot pad physical blocks the way the contiguous
    # kernel pads a whole cache), and C == 1 — chunked prefill and the
    # spec-decode verify chunk always take the gather path (matmul-bound,
    # not gather-bound). The int8-pool kernel is an ordinary REGISTERED
    # program keyed on kv_dtype, not a special case here.
    program = attn_dispatch.select(_decode_attn_site(
        cfg,
        phase or ("paged_decode" if C == 1 else "prefill_chunk"), C, nb * bs,
        kv_dtype="int8" if quantized else str(jnp.dtype(pool_l["k"].dtype)),
        block_size=bs))
    if program == "paged_kernel_quant":
        from deepspeed_tpu.ops.pallas.decode_attention import \
            paged_decode_attention_quant
        attn = paged_decode_attention_quant(
            q[:, 0], pool_l["k"], pool_l["v"], pool_l["k_scale"],
            pool_l["v_scale"], block_tables, positions[:, 0],
            sm_scale=None if cfg.scale_attn else 1.0).reshape(B, 1, D)
    elif program == "paged_kernel":
        from deepspeed_tpu.ops.pallas.decode_attention import \
            paged_decode_attention
        attn = paged_decode_attention(
            q[:, 0], pool_l["k"], pool_l["v"], block_tables,
            positions[:, 0],
            sm_scale=None if cfg.scale_attn else 1.0).reshape(B, 1, D)
    elif program in ("paged_gather_quant", "paged_gather"):
        if program == "paged_gather_quant":
            k_ctx, v_ctx = gather_block_kv_dequant(pool_l, block_tables,
                                                   x.dtype)
        else:
            k_ctx, v_ctx = gather_block_kv(pool_l["k"], pool_l["v"],
                                           block_tables)
        attn = _paged_attend(q, k_ctx, v_ctx, positions, cfg,
                             local_flag=local_flag)
    else:
        # see the contiguous decode site: by-name dispatch, loud failure
        # for programs without a handler (an unknown name silently taking
        # the fp gather would read int8 payload as K/V on quantized pools)
        raise NotImplementedError(
            f"attention program {program!r} selected for the paged site "
            f"has no handler in models/gpt.py — non-train phases dispatch "
            f"by name; add a branch for it here")
    attn_out = attn @ p["attn_out_w"] + p["attn_out_b"]
    return attn_out, pool_l


def _block_paged(x, p, pool_l, positions, block_tables,
                 cfg: GPTConfig, local_flag=None, phase=None):
    """One transformer block against the paged pool (decode, prefill
    chunk, or the spec-decode verify chunk — `phase` labels the dispatch
    site)."""
    attn_out, pool_l = _paged_attn_half(
        x, p, pool_l, positions, block_tables, cfg, local_flag=local_flag,
        phase=phase)
    x = _residual_mlp(x, attn_out, p, cfg, constrain=False)
    return x, pool_l


# ----------------------------------------------------------------------
# layered decode path — for the ZeRO-Inference parameter spill tier
# ----------------------------------------------------------------------


def make_gpt_layered_model(cfg: GPTConfig = None, name="gpt2-125m", params=None,
                           seed=0):
    """LayeredModelSpec: the decode model factored into per-layer functions so
    the spill engine (`inference/zero_inference.py`) can stream one layer's
    weights host->HBM at a time. Same math as `make_gpt_decode_model` — the
    stacked `lax.scan` over resident blocks becomes a Python loop over
    streamed blocks (reference capability:
    `runtime/swap_tensor/partitioned_param_swapper.py:36`,
    `docs/_posts/2022-09-10-zero-inference.md:35`)."""
    from deepspeed_tpu.inference.zero_inference import LayeredModelSpec
    cfg = cfg or GPT2_CONFIGS[name]
    if params is None:
        params = init_gpt_params(cfg, seed=seed)
    assert _layer_local_flags(cfg) is None, \
        "per-layer local/global flags not supported on the spill path yet"

    resident = {k: v for k, v in params.items() if k != "blocks"}
    blocks = params["blocks"]

    def embed_fn(res, tokens, positions):
        return _embed(res, tokens, positions, cfg)

    def layer_prefill_fn(p, x, ck, cv, positions):
        """x: [B,T,D]; ck/cv: [B,Hkv,M,hd] (this layer's cache slice)."""
        T = x.shape[1]
        attn_out, k, v = _attn_half(x, p, cfg, positions)
        ck = ck.at[:, :, :T].set(jnp.moveaxis(k, 1, 2).astype(ck.dtype))
        cv = cv.at[:, :, :T].set(jnp.moveaxis(v, 1, 2).astype(cv.dtype))
        x = _residual_mlp(x, attn_out, p, cfg)
        return x, ck, cv

    def layer_decode_fn(p, x, ck, cv, pos):
        return _block_decode(x, p, ck, cv, pos, cfg)

    def final_fn(res, x):
        return _lm_head(res, x, cfg)

    def init_layer_cache(batch_size, max_len, dtype=jnp.bfloat16):
        shape = (batch_size, cfg.n_kv_head, max_len, cfg.head_dim)
        return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)

    # TP shardings: the stacked specs' leading (layer) entry drops for the
    # per-layer streamed trees
    specs = gpt_param_specs(cfg)
    resident_specs = {k: v for k, v in specs.items() if k != "blocks"}
    block_specs = jax.tree_util.tree_map(lambda s: P(*tuple(s)[1:]),
                                         specs["blocks"])

    # training-side spill (ZeRO-Infinity params): cache-free block + CE head
    def layer_train_fn(p, x, positions):
        return _block(x, p, cfg, positions)

    def train_loss_fn(res, x, labels):
        logits = _lm_head(res, x, cfg)
        m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
        sumexp = jnp.sum(jnp.exp((logits - m).astype(jnp.float32)), axis=-1)
        logz = m[..., 0].astype(jnp.float32) + jnp.log(sumexp)
        safe = jnp.maximum(labels, 0)
        gold = jnp.take_along_axis(logits, safe[..., None],
                                   axis=-1)[..., 0].astype(jnp.float32)
        mask = (labels >= 0).astype(jnp.float32)
        return ((logz - gold) * mask).sum() / jnp.maximum(mask.sum(), 1.0)

    # streamed paged-serving contract (inference/scheduler.py offloaded-
    # weights mode): the same `_block_paged` body as the resident paged
    # path, but the layer index arrives TRACED and the [L, ...] pool is
    # sliced / written back with dynamic_index/update — one compile serves
    # every layer of a streamed walk, and pool donation makes the update
    # write in place
    def layer_paged_fn(p, x, layer, pool, block_tables, positions):
        pool_l = {k: jax.lax.dynamic_index_in_dim(v, layer, 0,
                                                  keepdims=False)
                  for k, v in pool.items()}
        x, pool_l = _block_paged(x, p, pool_l, positions, block_tables, cfg)
        pool = {k: jax.lax.dynamic_update_index_in_dim(
                    pool[k], pool_l[k].astype(pool[k].dtype), layer, 0)
                for k in pool}
        return x, pool

    def init_paged_pool(num_blocks, block_size, dtype=jnp.bfloat16,
                        kv_group_size=0):
        return init_paged_kv_pool(cfg, num_blocks, block_size, dtype,
                                  kv_group_size)

    return LayeredModelSpec(
        embed_fn=embed_fn, layer_prefill_fn=layer_prefill_fn,
        layer_decode_fn=layer_decode_fn, final_fn=final_fn,
        layer_train_fn=layer_train_fn, train_loss_fn=train_loss_fn,
        resident=resident, blocks=blocks, num_layers=cfg.n_layer,
        init_layer_cache=init_layer_cache, resident_specs=resident_specs,
        block_specs=block_specs, name=name,
        layer_paged_fn=layer_paged_fn, init_paged_pool=init_paged_pool,
        cache_fingerprint=gpt_cache_identity(cfg, name))
