"""BERT encoder family — MLM pretraining + classification heads, TPU-first.

Reference surface: the fused BERT training kernels
(`csrc/transformer/ds_transformer_cuda.cpp`, frontend `DeepSpeedTransformerLayer`
`deepspeed/ops/transformer/transformer.py:296`) behind the "fastest BERT
pretraining" claim (`docs/_posts/2020-05-28-fastest-bert-training.md`), the BERT
injection containers (`module_inject/containers/bert.py`, `distil_bert.py`), and
the BingBertSquad model test (`tests/model/BingBertSquad`).

TPU realization mirrors models/gpt.py: stacked blocks + `lax.scan`, bf16 with
fp32 norm/softmax accumulation, remat per block, Megatron TP PartitionSpecs,
batch on the data domain. Supports post-LN (original BERT) and pre-LN
(`DeepSpeedTransformerConfig.pre_layer_norm`) residual placement.
"""

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.mesh import BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, shard_constraint
from deepspeed_tpu.runtime.engine import ModelSpec


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30528          # padded to 64 multiple (MXU-friendly)
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: Optional[int] = None       # default 4*d_model
    max_seq_len: int = 512
    type_vocab_size: int = 2
    norm_eps: float = 1e-12
    pre_layer_norm: bool = False     # reference DeepSpeedTransformerConfig knob
    remat: bool = True
    remat_prevent_cse: bool = False  # safe+faster inside the layer scan; see
                                     # GPTConfig.remat_prevent_cse
    dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.d_ff is None:
            self.d_ff = 4 * self.d_model
        assert self.d_model % self.n_head == 0

    @property
    def head_dim(self):
        return self.d_model // self.n_head


BERT_CONFIGS = {
    "bert-tiny": BertConfig(n_layer=2, n_head=4, d_model=128, max_seq_len=128,
                            vocab_size=1024),
    "bert-base": BertConfig(n_layer=12, n_head=12, d_model=768),
    "bert-large": BertConfig(n_layer=24, n_head=16, d_model=1024),
}


def init_bert_params(cfg: BertConfig, seed: int = 0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    D, F, L = cfg.d_model, cfg.d_ff, cfg.n_layer

    def norm(*shape, scale=0.02):
        return jnp.asarray(rng.normal(0.0, scale, shape), dtype)

    def zeros(*shape):
        return jnp.zeros(shape, dtype)

    def ones(*shape):
        return jnp.ones(shape, dtype)

    block = {
        "attn_qkv_w": norm(L, D, 3 * D),
        "attn_qkv_b": zeros(L, 3 * D),
        "attn_out_w": norm(L, D, D),
        "attn_out_b": zeros(L, D),
        "ln1_scale": ones(L, D),
        "ln1_bias": zeros(L, D),
        "mlp_up_w": norm(L, D, F),
        "mlp_up_b": zeros(L, F),
        "mlp_down_w": norm(L, F, D),
        "mlp_down_b": zeros(L, D),
        "ln2_scale": ones(L, D),
        "ln2_bias": zeros(L, D),
    }
    params = {
        "word_emb": norm(cfg.vocab_size, D),
        "pos_emb": norm(cfg.max_seq_len, D),
        "type_emb": norm(cfg.type_vocab_size, D),
        "emb_ln_scale": ones(D),
        "emb_ln_bias": zeros(D),
        "blocks": block,
        # MLM head: dense + LN + decoder (tied to word_emb) + bias
        "mlm_dense_w": norm(D, D),
        "mlm_dense_b": zeros(D),
        "mlm_ln_scale": ones(D),
        "mlm_ln_bias": zeros(D),
        "mlm_bias": zeros(cfg.vocab_size),
        # pooler (CLS) for classification/NSP
        "pooler_w": norm(D, D),
        "pooler_b": zeros(D),
    }
    return params


def bert_param_specs(cfg: BertConfig):
    """Megatron TP specs (column qkv/up, row out/down), like gpt_param_specs."""
    t = TENSOR_AXIS
    block = {
        "attn_qkv_w": P(None, None, t),
        "attn_qkv_b": P(None, t),
        "attn_out_w": P(None, t, None),
        "attn_out_b": P(None, None),
        "ln1_scale": P(None, None),
        "ln1_bias": P(None, None),
        "mlp_up_w": P(None, None, t),
        "mlp_up_b": P(None, t),
        "mlp_down_w": P(None, t, None),
        "mlp_down_b": P(None, None),
        "ln2_scale": P(None, None),
        "ln2_bias": P(None, None),
    }
    return {
        "word_emb": P(t, None),
        "pos_emb": P(None, None),
        "type_emb": P(None, None),
        "emb_ln_scale": P(None), "emb_ln_bias": P(None),
        "blocks": block,
        "mlm_dense_w": P(None, None), "mlm_dense_b": P(None),
        "mlm_ln_scale": P(None), "mlm_ln_bias": P(None),
        "mlm_bias": P(t),
        "pooler_w": P(None, None), "pooler_b": P(None),
    }


def _ln(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (xf * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


def _bert_block(x, p, mask_bias, cfg: BertConfig):
    """x: [B, T, D]; mask_bias: [B, 1, 1, T] additive (-inf on padding)."""
    B, T, D = x.shape
    H, hd = cfg.n_head, cfg.head_dim
    eps = cfg.norm_eps

    def attend(h):
        qkv = h @ p["attn_qkv_w"] + p["attn_qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = shard_constraint(q.reshape(B, T, H, hd), BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, None)
        k = shard_constraint(k.reshape(B, T, H, hd), BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, None)
        v = shard_constraint(v.reshape(B, T, H, hd), BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, None)
        s = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) / math.sqrt(hd)
        s = s + mask_bias
        probs = jax.nn.softmax(s, axis=-1).astype(h.dtype)
        attn = jnp.einsum("bhts,bshd->bthd", probs, v).reshape(B, T, D)
        return attn @ p["attn_out_w"] + p["attn_out_b"]

    def mlp(h):
        up = jax.nn.gelu(h @ p["mlp_up_w"] + p["mlp_up_b"], approximate=False)
        up = shard_constraint(up, BATCH_AXES, SEQ_AXIS, TENSOR_AXIS)
        return up @ p["mlp_down_w"] + p["mlp_down_b"]

    if cfg.pre_layer_norm:
        x = x + attend(_ln(x, p["ln1_scale"], p["ln1_bias"], eps))
        x = x + mlp(_ln(x, p["ln2_scale"], p["ln2_bias"], eps))
    else:  # post-LN (original BERT)
        x = _ln(x + attend(x), p["ln1_scale"], p["ln1_bias"], eps)
        x = _ln(x + mlp(x), p["ln2_scale"], p["ln2_bias"], eps)
    return shard_constraint(x, BATCH_AXES, SEQ_AXIS, None)


def bert_encode(params, input_ids, cfg: BertConfig, token_type_ids=None,
                attention_mask=None):
    """→ sequence output [B, T, D]."""
    B, T = input_ids.shape
    dtype = cfg.dtype
    x = jnp.take(params["word_emb"], input_ids, axis=0)
    x = x + jnp.take(params["pos_emb"], jnp.arange(T), axis=0)[None]
    if token_type_ids is None:
        token_type_ids = jnp.zeros_like(input_ids)
    x = x + jnp.take(params["type_emb"], token_type_ids, axis=0)
    x = _ln(x.astype(dtype), params["emb_ln_scale"], params["emb_ln_bias"],
            cfg.norm_eps)
    x = shard_constraint(x, BATCH_AXES, SEQ_AXIS, None)

    if attention_mask is None:
        mask_bias = jnp.zeros((B, 1, 1, T), jnp.float32)
    else:
        mask_bias = jnp.where(attention_mask[:, None, None, :] != 0, 0.0, -1e30) \
            .astype(jnp.float32)

    block_fn = partial(_bert_block, mask_bias=mask_bias, cfg=cfg)
    if cfg.remat:
        block_fn = jax.checkpoint(block_fn, prevent_cse=cfg.remat_prevent_cse)

    def body(x, layer_params):
        return block_fn(x, layer_params), None

    x, _ = jax.lax.scan(body, x, params["blocks"])
    return x


def bert_mlm_logits(params, seq_out, cfg: BertConfig):
    h = seq_out @ params["mlm_dense_w"] + params["mlm_dense_b"]
    h = _ln(jax.nn.gelu(h, approximate=False), params["mlm_ln_scale"], params["mlm_ln_bias"],
            cfg.norm_eps)
    return jnp.einsum("btd,vd->btv", h, params["word_emb"].astype(h.dtype)) \
        + params["mlm_bias"]


def bert_pooled(params, seq_out):
    """CLS-token pooled output (tanh dense)."""
    return jnp.tanh(seq_out[:, 0] @ params["pooler_w"] + params["pooler_b"])


def bert_mlm_loss(params, batch, rng, cfg: BertConfig):
    """batch: input_ids [B,T], labels [B,T] with -100 = unmasked (HF convention),
    optional token_type_ids / attention_mask."""
    seq = bert_encode(params, batch["input_ids"], cfg,
                      token_type_ids=batch.get("token_type_ids"),
                      attention_mask=batch.get("attention_mask"))
    logits = bert_mlm_logits(params, seq, cfg).astype(jnp.float32)
    labels = batch["labels"]
    logz = jax.nn.logsumexp(logits, axis=-1)
    safe = jnp.maximum(labels, 0)
    gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    mask = (labels >= 0).astype(jnp.float32)
    return ((logz - gold) * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def bert_cls_loss(params, batch, rng, cfg: BertConfig, num_classes=2):
    """Sequence classification on the pooled CLS (BingBertSquad-style head)."""
    seq = bert_encode(params, batch["input_ids"], cfg,
                      token_type_ids=batch.get("token_type_ids"),
                      attention_mask=batch.get("attention_mask"))
    pooled = bert_pooled(params, seq)
    logits = (pooled @ params["cls_w"] + params["cls_b"]).astype(jnp.float32)
    labels = batch["labels"]
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return (logz - gold).mean()


def make_bert_model(cfg: BertConfig = None, name="bert-base", seed=0,
                    task="mlm", num_classes=2) -> ModelSpec:
    cfg = cfg or BERT_CONFIGS[name]
    params = init_bert_params(cfg, seed=seed)
    specs = bert_param_specs(cfg)
    if task == "cls":
        rng = np.random.default_rng(seed + 1)
        params["cls_w"] = jnp.asarray(rng.normal(0, 0.02, (cfg.d_model, num_classes)),
                                      jnp.float32)
        params["cls_b"] = jnp.zeros((num_classes,), jnp.float32)
        specs = {**specs, "cls_w": P(None, None), "cls_b": P(None)}
        loss = partial(bert_cls_loss, cfg=cfg, num_classes=num_classes)
    else:
        loss = partial(bert_mlm_loss, cfg=cfg)
    return ModelSpec(loss_fn=loss, params=params, param_specs=specs,
                     apply_fn=partial(bert_encode, cfg=cfg), name=name)
