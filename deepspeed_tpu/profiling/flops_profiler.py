"""Flops profiler.

Reference: `profiling/flops_profiler/profiler.py:28` — module hooks + patched
torch.nn.functional counting MACs/latency per module, tree report, auto-invoked
from the engine at `flops_profiler_profile_step`.

TPU-native: XLA already knows the exact flop count of the compiled program —
`jitted.lower(...).compile().cost_analysis()` exposes `flops`,
`bytes accessed`, and `optimal_seconds`. The profiler wraps any jitted callable
(or the engine's train step), reports program-level numbers, and derives
utilization against the chip's peak. Per-module breakdown comes from
`jax.named_scope` annotations surfaced in the xprof trace rather than hooks.
"""

import time

import numpy as np

from deepspeed_tpu.utils.logging import logger


def _peak_flops():
    import os
    table = {"v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12}
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    for k, v in table.items():
        if k in gen:
            return v
    try:
        import jax
        kind = getattr(jax.devices()[0], "device_kind", "").lower()
        for k, v in table.items():
            if k in kind:
                return v
    except Exception:
        pass
    return 197e12


def cost_analysis(fn, *args, **kwargs):
    """Compile `fn` for the given args and return XLA's cost analysis dict."""
    import jax
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    try:
        analyses = compiled.cost_analysis()
        analysis = analyses[0] if isinstance(analyses, (list, tuple)) else analyses
    except Exception as e:
        logger.warning(f"cost_analysis unavailable: {e}")
        analysis = {}
    return dict(analysis or {})


class FlopsProfiler:
    """Program-level flops/latency profiler (reference class name/API subset:
    start_profile / stop_profile / get_total_flops / print_model_profile)."""

    def __init__(self, model=None, ds_engine=None):
        self.engine = ds_engine
        self.analysis = {}
        self.measured_seconds = None
        self.started = False

    def start_profile(self, ignore_list=None):
        self.started = True
        self._t0 = time.perf_counter()

    def stop_profile(self):
        if self.started:
            self.measured_seconds = time.perf_counter() - self._t0
            self.started = False

    def profile_fn(self, fn, *args, n_timing_runs=3, **kwargs):
        """Cost-analyze + wall-clock a jitted callable."""
        import jax
        self.analysis = cost_analysis(fn, *args, **kwargs)
        jitted = fn if callable(getattr(fn, "lower", None)) else jax.jit(fn)
        out = jitted(*args, **kwargs)          # compile+warm
        jax.tree_util.tree_map(lambda x: None, out)
        t0 = time.perf_counter()
        for _ in range(n_timing_runs):
            out = jitted(*args, **kwargs)
        flat = jax.tree_util.tree_leaves(out)
        if flat:
            np.asarray(jax.device_get(flat[0])).sum()  # completion fence
        self.measured_seconds = (time.perf_counter() - t0) / n_timing_runs
        return out

    def get_total_flops(self, as_string=False):
        f = self.analysis.get("flops", 0.0)
        return _num_to_string(f) + "FLOPS" if as_string else f

    def get_total_macs(self, as_string=False):
        f = self.get_total_flops() / 2
        return _num_to_string(f) + "MACs" if as_string else f

    def get_total_duration(self, as_string=False):
        d = self.measured_seconds or self.analysis.get("optimal_seconds", 0.0)
        return f"{d*1e3:.2f} ms" if as_string else d

    def get_total_params(self, as_string=False):
        n = 0
        if self.engine is not None:
            from deepspeed_tpu.utils.tree import tree_num_params
            n = tree_num_params(self.engine.state.params)
        return _num_to_string(n) if as_string else n

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1,
                            detailed=True, output_file=None):
        flops = self.get_total_flops()
        dur = self.get_total_duration()
        peak = _peak_flops()
        achieved = flops / dur if dur else 0.0
        lines = [
            "-------------------------- DeepSpeed-TPU Flops Profiler --------------------------",
            f"profile step:                   {profile_step}",
            f"params:                         {self.get_total_params(as_string=True)}",
            f"flops per step:                 {_num_to_string(flops)}FLOPS",
            f"step latency:                   {dur*1e3:.2f} ms",
            f"achieved:                       {achieved/1e12:.2f} TFLOPS "
            f"({100*achieved/peak:.1f}% of peak)",
            f"bytes accessed:                 {_num_to_string(self.analysis.get('bytes accessed', 0))}B",
            "----------------------------------------------------------------------------------",
        ]
        report = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(report)
        else:
            logger.info("\n" + report)
        return report

    def end_profile(self):
        self.stop_profile()


def get_model_profile(model, input_shape=None, args=(), kwargs=None, print_profile=True,
                      detailed=True, module_depth=-1, top_modules=1, warm_up=1,
                      as_string=True, output_file=None, ignore_modules=None):
    """Reference `get_model_profile` — profile a callable outside the engine.
    `model` is a jittable fn; `args` its example inputs."""
    prof = FlopsProfiler()
    prof.profile_fn(model, *args, **(kwargs or {}))
    if print_profile:
        prof.print_model_profile(detailed=detailed, module_depth=module_depth,
                                 top_modules=top_modules, output_file=output_file)
    flops = prof.get_total_flops(as_string=as_string)
    macs = prof.get_total_macs(as_string=as_string)
    params = prof.get_total_params(as_string=as_string)
    return flops, macs, params


def _num_to_string(num, precision=2):
    if num >= 1e12:
        return f"{num/1e12:.{precision}f} T"
    if num >= 1e9:
        return f"{num/1e9:.{precision}f} G"
    if num >= 1e6:
        return f"{num/1e6:.{precision}f} M"
    if num >= 1e3:
        return f"{num/1e3:.{precision}f} K"
    return f"{num:.{precision}f} "
