"""Flops profiler.

Reference: `profiling/flops_profiler/profiler.py:28` — module hooks + patched
torch.nn.functional counting MACs/latency per module, tree report, auto-invoked
from the engine at `flops_profiler_profile_step`.

TPU-native: XLA already knows the exact flop count of the compiled program —
`jitted.lower(...).compile().cost_analysis()` exposes `flops`,
`bytes accessed`, and `optimal_seconds`. The profiler wraps any jitted callable
(or the engine's train step) and reports program-level numbers plus derived
utilization against the chip's peak.

Per-module tree (the reference's `print_model_profile` MACs/latency tree):
`ModuleProfile` cost-analyzes each submodule function separately (lowered
with abstract ShapeDtypeStructs — no weights materialize) and assembles a
depth-limited tree with flops/MACs/params and the share of the whole model;
`gpt_module_profile` wires the GPT zoo's block structure (embed / N x
{attn, mlp} / lm_head) into it.
"""

import time

import numpy as np

from deepspeed_tpu.utils.logging import logger


def _peak_flops():
    import os
    table = {"v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12}
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    for k, v in table.items():
        if k in gen:
            return v
    try:
        import jax
        kind = getattr(jax.devices()[0], "device_kind", "").lower()
        for k, v in table.items():
            if k in kind:
                return v
    except Exception:
        pass
    return 197e12


def cost_analysis(fn, *args, **kwargs):
    """Compile `fn` for the given args and return XLA's cost analysis dict."""
    import jax
    # dstpu: ignore[DT004]: the profiler's job is a fresh lower+compile — it MEASURES compilation, it doesn't serve from it
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    try:
        analyses = compiled.cost_analysis()
        analysis = analyses[0] if isinstance(analyses, (list, tuple)) else analyses
    except Exception as e:
        logger.warning(f"cost_analysis unavailable: {e}")
        analysis = {}
    return dict(analysis or {})


class FlopsProfiler:
    """Program-level flops/latency profiler (reference class name/API subset:
    start_profile / stop_profile / get_total_flops / print_model_profile)."""

    def __init__(self, model=None, ds_engine=None):
        self.engine = ds_engine
        self.analysis = {}
        self.measured_seconds = None
        self.started = False
        self.module_tree = None    # ModuleProfile root (set_module_tree)

    def set_module_tree(self, tree):
        """Attach a ModuleProfile tree (e.g. `gpt_module_profile(cfg)`) so
        print_model_profile renders the reference's per-module breakdown."""
        self.module_tree = tree

    def start_profile(self, ignore_list=None):
        self.started = True
        self._t0 = time.perf_counter()

    def stop_profile(self):
        if self.started:
            self.measured_seconds = time.perf_counter() - self._t0
            self.started = False

    def profile_fn(self, fn, *args, n_timing_runs=3, **kwargs):
        """Cost-analyze + wall-clock a jitted callable."""
        import jax
        self.analysis = cost_analysis(fn, *args, **kwargs)
        # dstpu: ignore[DT004]: one-shot profiling wrapper — lives for exactly n_timing_runs calls
        jitted = fn if callable(getattr(fn, "lower", None)) else jax.jit(fn)
        out = jitted(*args, **kwargs)          # compile+warm
        jax.tree_util.tree_map(lambda x: None, out)
        t0 = time.perf_counter()
        for _ in range(n_timing_runs):
            out = jitted(*args, **kwargs)
        flat = jax.tree_util.tree_leaves(out)
        if flat:
            np.asarray(jax.device_get(flat[0])).sum()  # completion fence
        self.measured_seconds = (time.perf_counter() - t0) / n_timing_runs
        return out

    def get_total_flops(self, as_string=False):
        f = self.analysis.get("flops", 0.0)
        return _num_to_string(f) + "FLOPS" if as_string else f

    def get_total_macs(self, as_string=False):
        f = self.get_total_flops() / 2
        return _num_to_string(f) + "MACs" if as_string else f

    def get_total_duration(self, as_string=False):
        d = self.measured_seconds or self.analysis.get("optimal_seconds", 0.0)
        return f"{d*1e3:.2f} ms" if as_string else d

    def get_total_params(self, as_string=False):
        n = 0
        if self.engine is not None:
            from deepspeed_tpu.utils.tree import tree_num_params
            n = tree_num_params(self.engine.state.params)
        return _num_to_string(n) if as_string else n

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1,
                            detailed=True, output_file=None):
        flops = self.get_total_flops()
        dur = self.get_total_duration()
        peak = _peak_flops()
        achieved = flops / dur if dur else 0.0
        lines = [
            "-------------------------- DeepSpeed-TPU Flops Profiler --------------------------",
            f"profile step:                   {profile_step}",
            f"params:                         {self.get_total_params(as_string=True)}",
            f"flops per step:                 {_num_to_string(flops)}FLOPS",
            f"step latency:                   {dur*1e3:.2f} ms",
            f"achieved:                       {achieved/1e12:.2f} TFLOPS "
            f"({100*achieved/peak:.1f}% of peak)",
            f"bytes accessed:                 {_num_to_string(self.analysis.get('bytes accessed', 0))}B",
        ]
        if detailed and self.module_tree is not None:
            tree_secs = None
            if dur and flops:
                # attribute the measured step time to the fwd tree by its
                # share of the program's total flops (bwd+update included in
                # `flops`, so the fwd tree gets its proportional slice)
                tree_secs = dur * self.module_tree.total_flops / flops
            lines.append("per-module (fwd flops, est. latency):")
            lines.extend(self.module_tree.render(module_depth=module_depth,
                                                 total_seconds=tree_secs))
        lines.append(
            "----------------------------------------------------------------------------------")
        report = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(report)
        else:
            logger.info("\n" + report)
        return report

    def end_profile(self):
        self.stop_profile()


def get_model_profile(model, input_shape=None, args=(), kwargs=None, print_profile=True,
                      detailed=True, module_depth=-1, top_modules=1, warm_up=1,
                      as_string=True, output_file=None, ignore_modules=None):
    """Reference `get_model_profile` — profile a callable outside the engine.
    `model` is a jittable fn; `args` its example inputs."""
    prof = FlopsProfiler()
    prof.profile_fn(model, *args, **(kwargs or {}))
    if print_profile:
        prof.print_model_profile(detailed=detailed, module_depth=module_depth,
                                 top_modules=top_modules, output_file=output_file)
    flops = prof.get_total_flops(as_string=as_string)
    macs = prof.get_total_macs(as_string=as_string)
    params = prof.get_total_params(as_string=as_string)
    return flops, macs, params


class ModuleProfile:
    """One node of the per-module profile tree (reference
    `flops_profiler/profiler.py:28` prints this per torch module; here each
    node is a jittable submodule function cost-analyzed in isolation)."""

    def __init__(self, name, flops=0.0, params=0, multiplier=1, children=()):
        self.name = name
        self.flops = float(flops)        # per instance
        self.params = int(params)        # per instance
        self.multiplier = multiplier     # e.g. n_layer for a block node
        self.children = list(children)

    @classmethod
    def of(cls, name, fn, abstract_args, multiplier=1, params=0):
        """Cost-analyze `fn` lowered against ShapeDtypeStructs."""
        import jax
        # dstpu: ignore[DT004]: abstract cost analysis — lowered against ShapeDtypeStructs once, never executed
        analysis = cost_analysis(jax.jit(fn), *abstract_args)
        return cls(name, analysis.get("flops", 0.0), params, multiplier)

    @property
    def total_flops(self):
        return self.multiplier * (self.flops +
                                  sum(c.total_flops for c in self.children))

    @property
    def total_params(self):
        return self.multiplier * (self.params +
                                  sum(c.total_params for c in self.children))

    def render(self, total=None, depth=0, module_depth=-1, total_seconds=None):
        """Depth-limited lines; with `total_seconds` (a measured fwd walltime)
        each node also shows its flops-proportional latency estimate — the
        reference profiler's per-module latency column (`profiler.py:28`),
        attributed by share instead of per-hook timers."""
        total = total or self.total_flops or 1.0
        pct = 100.0 * self.total_flops / total
        mult = f" x{self.multiplier}" if self.multiplier > 1 else ""
        lat = ""
        if total_seconds:
            lat = f", ~{1e3 * total_seconds * self.total_flops / total:.2f} ms"
        lines = [f"{'  ' * depth}{self.name}{mult}: "
                 f"{_num_to_string(self.total_flops)}FLOPS "
                 f"({_num_to_string(self.total_flops / 2)}MACs, {pct:.1f}%)"
                 + (f", {_num_to_string(self.total_params)}params"
                    if self.total_params else "") + lat]
        if module_depth < 0 or depth < module_depth:
            for c in self.children:
                lines.extend(c.render(total, depth + 1, module_depth,
                                      total_seconds))
        return lines


def gpt_module_profile(cfg, batch_size=1, seq_len=None):
    """Per-module flops tree for a GPT-zoo config: embed / blocks x L
    {attn, mlp} / lm_head — the reference's per-module report for its
    injected transformer. Everything lowers abstractly (no weights)."""
    import jax
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S
    from deepspeed_tpu.models import gpt as G

    T = seq_len or min(cfg.max_seq_len, 512)
    B, D, L = batch_size, cfg.d_model, cfg.n_layer
    shapes = jax.eval_shape(G.gpt_init_fn(cfg, dtype=jnp.dtype(cfg.dtype)),
                            jax.random.PRNGKey(0))
    blocks = shapes["blocks"]
    layer = jax.tree_util.tree_map(lambda s: S(s.shape[1:], s.dtype), blocks)
    resident = {k: S(v.shape, v.dtype) for k, v in shapes.items()
                if k != "blocks"}
    x = S((B, T, D), jnp.dtype(cfg.dtype))
    toks = S((B, T), jnp.int32)
    pos = S((B, T), jnp.int32)

    def nparams(tree):
        import numpy as _np
        return sum(int(_np.prod(s.shape))
                   for s in jax.tree_util.tree_leaves(tree))

    def attn_fn(x, p, positions):
        return G._attn_half(x, p, cfg, positions)[0]

    def mlp_fn(x, p):
        return G._mlp(x, p, cfg)

    def embed_fn(res, toks, pos):
        return G._embed(res, toks, pos, cfg)

    def head_fn(res, x):
        return G._lm_head(res, x, cfg)

    attn_keys = [k for k in layer if k.startswith(("attn_", "ln1"))]
    mlp_keys = [k for k in layer if k.startswith(("mlp_", "ln2"))]
    block_node = ModuleProfile(
        "block", multiplier=L,
        children=[
            ModuleProfile.of("attn", attn_fn, (x, layer, pos),
                             params=nparams({k: layer[k] for k in attn_keys})),
            ModuleProfile.of("mlp", mlp_fn, (x, layer),
                             params=nparams({k: layer[k] for k in mlp_keys})),
        ])
    # param attribution: the head weight (untied) and final norm belong to the
    # lm_head node, everything else resident (wte/wpe/emb norms) to embed
    head_keys = [k for k in resident if k.startswith(("lm_head", "lnf"))]
    embed_params = nparams({k: v for k, v in resident.items()
                            if k not in head_keys})
    root = ModuleProfile(getattr(cfg, "name", "gpt"), children=[
        ModuleProfile.of("embed", embed_fn, (resident, toks, pos),
                         params=embed_params),
        block_node,
        ModuleProfile.of("lm_head", head_fn, (resident, x),
                         params=nparams({k: resident[k] for k in head_keys})),
    ])
    return root


def _num_to_string(num, precision=2):
    if num >= 1e12:
        return f"{num/1e12:.{precision}f} T"
    if num >= 1e9:
        return f"{num/1e9:.{precision}f} G"
    if num >= 1e6:
        return f"{num/1e6:.{precision}f} M"
    if num >= 1e3:
        return f"{num/1e3:.{precision}f} K"
    return f"{num:.{precision}f} "
