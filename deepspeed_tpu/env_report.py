"""Environment report — `ds_report` analog (reference `deepspeed/env_report.py`)."""

import importlib
import sys


def main(args=None):
    import deepspeed_tpu
    print("-" * 70)
    print("DeepSpeed-TPU environment report")
    print("-" * 70)
    print(f"deepspeed_tpu version ... {deepspeed_tpu.__version__}")
    print(f"python version .......... {sys.version.split()[0]}")
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        try:
            m = importlib.import_module(mod)
            ver = getattr(m, "__version__", "?")
            print(f"{mod:<22}... {ver}")
        except Exception:
            print(f"{mod:<22}... not installed")
    try:
        import jax
        print(f"default backend ......... {jax.default_backend()}")
        devs = jax.devices()
        print(f"devices ................. {len(devs)} x {getattr(devs[0], 'device_kind', '?')}")
        from deepspeed_tpu.platform import get_accelerator
        acc = get_accelerator()
        stats = acc.memory_stats()
        if stats.get("bytes_limit"):
            print(f"HBM per device .......... {stats['bytes_limit']/2**30:.1f} GiB")
        # per-device memory at a glance (capacity / in-use / peak) — the
        # CPU harness exposes no allocator stats, so say so instead of 0s
        from deepspeed_tpu.telemetry.memscope import fmt_bytes
        for i, d in enumerate(devs[:8]):
            try:
                s = d.memory_stats() or {}
            except Exception:
                s = {}
            label = f"  dev{i} HBM "
            if s.get("bytes_limit") or s.get("bytes_in_use"):
                print(f"{label:<26}"
                      f"in-use {fmt_bytes(s.get('bytes_in_use', 0))} | "
                      f"peak {fmt_bytes(s.get('peak_bytes_in_use', 0))} | "
                      f"limit {fmt_bytes(s.get('bytes_limit', 0))}")
            else:
                print(f"{label:<26}allocator stats unavailable")
        if len(devs) > 8:
            print(f"  ... ({len(devs) - 8} more devices)")
        print(f"comm backend ............ {acc.communication_backend_name()}")
    except Exception as e:
        print(f"jax devices ............. unavailable ({e})")
    print("-" * 70)
    return 0


if __name__ == "__main__":
    sys.exit(main())
