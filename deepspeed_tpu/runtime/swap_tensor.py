"""NVMe tensor swapping (ZeRO-Infinity tier).

Reference: `runtime/swap_tensor/` (1.8k LoC) — `AsyncPartitionedParameterSwapper`,
`PartitionedOptimizerSwapper`, `AsyncTensorSwapper` with double-buffered aio.

This module drives the C++ AIO library (csrc/aio) over ctypes: each pytree leaf
maps to one file under the swap folder; reads/writes are async (thread-pooled
pread/pwrite) with `wait()` barriers, so swap-out of step N overlaps compute of
step N+1 exactly like the reference's pipelined swapper.
"""

import os
import pathlib

import numpy as np

from deepspeed_tpu.utils.logging import logger


ALIGN = 4096  # O_DIRECT alignment (page / NVMe logical block)


def _padded(nbytes, align=ALIGN):
    return (int(nbytes) + align - 1) // align * align


def aligned_empty(shape, dtype, align=ALIGN):
    """numpy array whose data pointer AND total byte length are `align`-ed —
    the shape the AIO library needs to use O_DIRECT (csrc/aio). The returned
    view has the exact requested shape; its buffer is padded underneath."""
    dtype = np.dtype(dtype)
    nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    padded = (nbytes + align - 1) // align * align
    raw = np.empty(padded + align, np.uint8)
    off = (-raw.ctypes.data) % align
    flat = raw[off:off + nbytes]
    return flat.view(dtype).reshape(shape)


class AsyncTensorSwapper:
    """Swap numpy buffers to/from files asynchronously (reference
    `swap_tensor/async_swapper.py:19` role). O_DIRECT with no per-write
    fsync by default (`use_odirect=False` only for debugging): swap files
    are scratch, and buffered+fsync serializes the NVMe queue."""

    def __init__(self, swap_folder, num_threads=4, block_size=1 << 20,
                 use_odirect=True):
        from deepspeed_tpu.ops.op_builder import AsyncIOBuilder
        self.lib = AsyncIOBuilder().load()
        self.handle = self.lib.dstpu_aio_create_ex(num_threads, block_size,
                                                   1 if use_odirect else 0, 0)
        self.folder = pathlib.Path(swap_folder)
        self.folder.mkdir(parents=True, exist_ok=True)
        self._buffers = {}   # name -> np array (pinned host staging)

    def path_for(self, name):
        return str(self.folder / (name.replace("/", "__") + ".swp"))

    def swap_out(self, name, array):
        """Async write; the array must stay alive until wait().

        Zero-copy submit: the caller's buffer is handed to the AIO threads
        as-is (a staging memcpy here would serialize the submit phase — the
        window where the next step's compute overlaps this swap-out).
        Arbitrarily-aligned buffers stay O_DIRECT end-to-end anyway: the
        WORKER thread bounces them through an aligned copy before the pwrite
        (csrc/aio), so the file never mixes buffered writes with O_DIRECT
        reads (a coherency pattern open(2) discourages)."""
        arr = np.ascontiguousarray(array)
        self._buffers[name] = arr
        # exact length; padding to the 4K read boundary happens in csrc/aio
        # (bounce-buffer write length + grow-only ftruncate)
        self.lib.dstpu_aio_pwrite(self.handle, self.path_for(name).encode(),
                                  arr.ctypes.data, arr.nbytes, 0)

    def swap_in(self, name, shape, dtype):
        """Async read into a fresh buffer; returns it (valid after wait()).
        The buffer comes from `aligned_empty` (aligned pointer, padded slack
        past nbytes inside the allocation) and the writer grow-padded the
        file to the same 4K boundary, so the read is issued at the padded
        length and takes the O_DIRECT path end-to-end."""
        arr = aligned_empty(shape, dtype)
        self._buffers[name] = arr
        self.lib.dstpu_aio_pread(self.handle, self.path_for(name).encode(),
                                 arr.ctypes.data, _padded(arr.nbytes), 0)
        return arr

    def wait(self):
        errors = self.lib.dstpu_aio_wait(self.handle)
        self._buffers.clear()
        if errors:
            raise IOError(f"{errors} swap I/O requests failed in {self.folder}")

    def release(self):
        if self.handle:
            self.lib.dstpu_aio_destroy(self.handle)
            self.handle = None

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass


class OptimizerStateSwapper:
    """Keeps a dict of named fp32 states on NVMe between steps (reference
    `PartitionedOptimizerSwapper` role): swap_in_all -> step -> swap_out_all."""

    def __init__(self, swap_folder, num_threads=4):
        self.swapper = AsyncTensorSwapper(swap_folder, num_threads=num_threads)
        self.meta = {}  # name -> (shape, dtype)

    def initialize(self, named_arrays):
        for name, arr in named_arrays.items():
            self.meta[name] = (arr.shape, arr.dtype)
            self.swapper.swap_out(name, arr)
        self.swapper.wait()

    def swap_in_all(self):
        out = {name: self.swapper.swap_in(name, shape, dtype)
               for name, (shape, dtype) in self.meta.items()}
        self.swapper.wait()
        return out

    def swap_out_all(self, named_arrays, blocking=True):
        for name, arr in named_arrays.items():
            self.meta[name] = (arr.shape, arr.dtype)
            self.swapper.swap_out(name, arr)
        if blocking:
            self.swapper.wait()
