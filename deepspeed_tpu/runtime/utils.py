"""`deepspeed_tpu.runtime.utils` — reference import-path parity for the most
commonly imported helpers of `deepspeed/runtime/utils.py` (see_memory_usage,
get_global_norm, clip_grad_norm_). Implementations live in
`deepspeed_tpu/utils/`; functional JAX versions return instead of mutating."""

import jax

from deepspeed_tpu.utils.memory import see_memory_usage
from deepspeed_tpu.utils.tree import tree_global_norm


def get_global_norm(norm_list=None, parameters=None):
    """Reference `get_global_norm` (`runtime/utils.py`): combine per-group
    norms, or compute the global norm of a parameter pytree."""
    if norm_list is not None:
        return float(sum(n**2 for n in norm_list) ** 0.5)
    return float(tree_global_norm(parameters))


def clip_grad_norm_(parameters=None, max_norm=1.0, mpu=None, grads=None):
    """Reference `clip_grad_norm_` semantics, functional: returns
    (clipped_grads, total_norm) instead of mutating in place. Accepts either
    `grads` or the reference's `parameters` name for the pytree."""
    tree = grads if grads is not None else parameters
    total = tree_global_norm(tree)
    factor = jax.numpy.minimum(1.0, max_norm / (total + 1e-6))
    clipped = jax.tree_util.tree_map(
        lambda g: g * factor.astype(g.dtype), tree)
    return clipped, float(total)


__all__ = ["see_memory_usage", "get_global_norm", "clip_grad_norm_"]
