"""Native prefetching token dataset — the reference DataLoader-worker role.

Reference: `runtime/dataloader.py` (`DeepSpeedDataLoader`) delegates host-side
batch assembly to torch DataLoader worker PROCESSES. Here the corpus is a flat
token file (int32 or uint16), mmap'd by a C++ thread pool
(`csrc/dataloader/dstpu_dataloader.cpp`) that assembles `[batch, seq_len]`
int32 batches into a prefetch ring ahead of the consumer. Delivery is in
batch-index order with per-index deterministic sampling, so runs reproduce
regardless of worker count — no seeded-sampler/single-worker dance.

Use standalone or hand the iterator to `engine.train_batch(data_iter=...)` /
`deepspeed_io`:

    ds = NativeTokenDataset("corpus.bin", seq_len=513, batch_size=96, seed=0)
    for step in range(n):
        loss = engine.train_batch(next(ds))
"""

import numpy as np

from deepspeed_tpu.ops.op_builder import DataLoaderBuilder


def write_token_file(path, tokens, dtype=np.int32):
    """Write a flat token array as the loader's on-disk format."""
    arr = np.asarray(tokens, dtype)
    assert arr.dtype in (np.dtype(np.int32), np.dtype(np.uint16)), arr.dtype
    arr.tofile(path)
    return path


class NativeTokenDataset:
    """Infinite iterator of {"tokens": int32 [batch, seq_len]} batches.

    `seq_len` should be model_seq + 1 when the loss derives labels by
    shifting (`gpt_loss` with a bare "tokens" batch does exactly that).
    """

    def __init__(self, path, seq_len, batch_size, n_prefetch=4, n_threads=2,
                 seed=0, token_bytes=4):
        self.lib = DataLoaderBuilder().load()
        self.seq_len = int(seq_len)
        self.batch_size = int(batch_size)
        self.handle = self.lib.dstpu_dl_create(
            str(path).encode(), self.seq_len, self.batch_size,
            int(n_prefetch), int(n_threads), int(seed) & (2**64 - 1),
            int(token_bytes))
        if not self.handle:
            raise IOError(f"dstpu_dl_create failed for {path!r} "
                          f"(missing file or corpus shorter than seq_len?)")

    @property
    def num_tokens(self):
        return int(self.lib.dstpu_dl_num_tokens(self.handle))

    def __iter__(self):
        return self

    def __next__(self):
        out = np.empty((self.batch_size, self.seq_len), np.int32)
        idx = self.lib.dstpu_dl_next(self.handle, out.ctypes.data)
        if idx < 0:
            raise IOError("dstpu_dl_next failed")
        return {"tokens": out}

    def close(self):
        if getattr(self, "handle", None):
            self.lib.dstpu_dl_destroy(self.handle)
            self.handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
