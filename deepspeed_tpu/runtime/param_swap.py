"""Parameter spill tier — ZeRO-Infinity params / ZeRO-Inference.

Reference: `runtime/swap_tensor/partitioned_param_swapper.py:36`
(`AsyncPartitionedParameterSwapper`) and the ZeRO-Inference recipe
(`docs/_posts/2022-09-10-zero-inference.md:35`): model weights live on
host RAM or NVMe and stream through device memory layer by layer, so the
servable model size is bounded by disk, not HBM.

TPU-native shape of the same idea:

  * the transformer stack is homogeneous — ONE compiled per-layer function
    is reused for every layer (weights are arguments, not constants);
  * `LayerParamStore` owns the per-layer host copies — "cpu" backend keeps
    them as numpy trees, "nvme" keeps them on disk via the AIO library
    (O_DIRECT, threaded) with a small ring of staging buffers and async
    read-ahead;
  * `LayerStreamer` double-buffers host->HBM uploads: while layer i
    computes, layer i+1's `jax.device_put` is already in flight (uploads
    are async under JAX's dispatch model), and the NVMe read for layer i+2
    is queued behind it. HBM never holds more than `lookahead+1` layers of
    weights + the resident (embedding/norm/head) leaves.

The reference needs ~1.8k LoC of swap machinery because every torch param
object must be rewired in place; here a layer's weights are just pytree
arguments to a jitted function, so the whole tier is this file.
"""

import pathlib

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


def _tree_bytes(tree):
    return sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
               for l in jax.tree_util.tree_leaves(tree))


class LayerParamStore:
    """Host/NVMe store of L structurally-identical per-layer param trees.

    `stacked` is a pytree whose leaves carry a leading layer dimension L
    (the model zoo's `params["blocks"]` layout). device="cpu" keeps all L
    trees in host RAM; device="nvme" writes each layer to one file under
    `swap_folder` and serves reads through `staging` reusable aligned
    buffers with async read-ahead (reference
    `partitioned_param_swapper.py` double-buffering)."""

    def __init__(self, stacked, device="cpu", swap_folder=None, staging=3,
                 aio_threads=4, dtype=None):
        leaves, self.treedef = jax.tree_util.tree_flatten(stacked)
        self.num_layers = int(leaves[0].shape[0])
        assert all(int(l.shape[0]) == self.num_layers for l in leaves), \
            "every stacked leaf must share the leading layer dimension"
        self.device = device
        cast = (lambda a: a) if dtype is None else (
            lambda a: np.asarray(a).astype(dtype))

        host_layers = []
        for i in range(self.num_layers):
            host_layers.append([cast(np.asarray(l[i])) for l in leaves])
        self.leaf_meta = [(l.shape, l.dtype) for l in host_layers[0]]
        self.layer_bytes = sum(int(np.prod(s)) * np.dtype(d).itemsize
                               for s, d in self.leaf_meta)

        if device == "cpu":
            self._layers = host_layers
            self._swapper = None
        elif device == "nvme":
            from deepspeed_tpu.runtime.swap_tensor import AsyncTensorSwapper
            assert swap_folder is not None, "nvme offload needs a swap_folder"
            self._swapper = AsyncTensorSwapper(swap_folder,
                                               num_threads=aio_threads)
            self._swap_folder = swap_folder
            self._wswapper = None  # created lazily on first put()
            for i, layer in enumerate(host_layers):
                for j, arr in enumerate(layer):
                    self._swapper.swap_out(f"layer{i}_leaf{j}", arr)
            self._swapper.wait()
            self._layers = None
            # staging ring: slot -> (layer_idx or None, [buffers])
            self._ring = [(None, None) for _ in range(max(2, staging))]
            self._inflight = {}   # layer idx -> slot, read submitted not waited
            logger.info(f"LayerParamStore: {self.num_layers} layers x "
                        f"{self.layer_bytes / 1e6:.1f} MB spilled to "
                        f"{pathlib.Path(swap_folder)}")
        else:
            raise ValueError(f"unknown spill device {device!r} (cpu|nvme)")

    # ---- nvme staging ----

    def _slot_for(self, i):
        return i % len(self._ring)

    def prefetch(self, i):
        """Queue the async NVMe read for layer i (no-op on the cpu tier or if
        already staged/in flight)."""
        if self._swapper is None or not (0 <= i < self.num_layers):
            return
        slot = self._slot_for(i)
        if self._ring[slot][0] == i:
            return
        if self._ring[slot][0] in self._inflight:
            # the slot's previous occupant still has a read in flight — let it
            # land before its buffers are dropped (otherwise the AIO threads
            # would write into freed memory)
            self._swapper.wait()
            self._inflight.clear()
        bufs = [self._swapper.swap_in(f"layer{i}_leaf{j}", shape, dt)
                for j, (shape, dt) in enumerate(self.leaf_meta)]
        self._ring[slot] = (i, bufs)
        self._inflight[i] = slot

    def get(self, i):
        """Host leaf list for layer i (blocks on its NVMe read if needed)."""
        if self._layers is not None:
            return self._layers[i]
        slot = self._slot_for(i)
        if self._ring[slot][0] != i:
            self.prefetch(i)
        if i in self._inflight:
            # one completion barrier covers every queued read; reads queued as
            # deeper read-ahead also land here, becoming staged (not re-read)
            self._swapper.wait()
            self._inflight.clear()
        idx, bufs = self._ring[slot]
        assert idx == i, f"staging ring lost layer {i} (holds {idx})"
        return bufs

    def get_tree(self, i):
        return jax.tree_util.tree_unflatten(self.treedef, self.get(i))

    def put(self, i, leaves, blocking=False):
        """Write layer i's (updated) host leaves back to the store — the
        training-side swap-out (reference `AsyncPartitionedParameterSwapper`
        writes updated fp16 partitions back after the optimizer step).

        Writes go through a SEPARATE swapper so queued read-ahead stays in
        flight (a shared queue would make every put a full barrier). With
        `blocking=False` (default) the caller must `flush_writes()` before
        the next read of this layer — the training loop does it once per
        step, not per layer."""
        leaves = [np.asarray(l) for l in leaves]
        if self._layers is not None:
            self._layers[i] = leaves
            return
        if i in self._inflight:
            # a read of the OLD content is mid-flight into ring buffers under
            # the same names — let it land before the overwrite
            self._swapper.wait()
            self._inflight.clear()
        slot = self._slot_for(i)
        if self._ring[slot][0] == i:
            self._ring[slot] = (None, None)  # staged copy is now stale
        if self._wswapper is None:
            from deepspeed_tpu.runtime.swap_tensor import AsyncTensorSwapper
            self._wswapper = AsyncTensorSwapper(self._swap_folder)
        for j, arr in enumerate(leaves):
            self._wswapper.swap_out(f"layer{i}_leaf{j}", arr)
        if blocking:
            self._wswapper.wait()

    def flush_writes(self):
        """Barrier on outstanding put() writes (reads are unaffected)."""
        if getattr(self, "_wswapper", None) is not None:
            self._wswapper.wait()

    def release(self):
        if self._swapper is not None:
            self._swapper.release()
        if getattr(self, "_wswapper", None) is not None:
            self._wswapper.release()


class LayerStreamer:
    """Double-buffered host->device streaming of `LayerParamStore` layers.

    `layer(i)` returns layer i's params on device, having already issued the
    (async) upload of layers i+1..i+lookahead and queued NVMe prefetch one
    step deeper. `peak_live_layers` records the high-water mark of
    simultaneously device-resident layers — the HBM working set of the
    spill tier — for tests and memory accounting."""

    def __init__(self, store: LayerParamStore, shardings=None, lookahead=1):
        self.store = store
        self.lookahead = max(0, int(lookahead))
        self._shardings = (None if shardings is None
                           else jax.tree_util.tree_leaves(shardings))
        self._live = {}          # layer idx -> device leaf list
        self.peak_live_layers = 0
        self.uploads = 0

    def _upload(self, i):
        if i in self._live or not (0 <= i < self.store.num_layers):
            return
        host = self.store.get(i)
        if self._shardings is None:
            dev = [jax.device_put(h) for h in host]
        else:
            dev = [jax.device_put(h, s) for h, s in zip(host, self._shardings)]
        self._live[i] = dev
        self.uploads += 1
        self.peak_live_layers = max(self.peak_live_layers, len(self._live))

    def layer(self, i, direction=1):
        """Device param tree for layer i; evicts layers outside the look-ahead
        window and uploads ahead in `direction` (+1 for the forward pass, -1
        for the reversed backward pass of the Infinity trainer)."""
        lo, hi = ((i, i + self.lookahead) if direction >= 0
                  else (i - self.lookahead, i))
        for j in list(self._live):
            # frees the HBM buffers (no other reference remains); the out-of-
            # window check also catches the wrap between passes (L-1 -> 0)
            if j < lo or j > hi:
                del self._live[j]
        # uploads first (their get() may take the completion barrier), THEN
        # queue the next NVMe read-ahead so it stays truly asynchronous
        step = 1 if direction >= 0 else -1
        for d in range(0, self.lookahead + 1):
            self._upload(i + d * step)
        self.store.prefetch(i + (self.lookahead + 1) * step)
        return jax.tree_util.tree_unflatten(self.store.treedef, self._live[i])

    def reset(self):
        self._live.clear()
