"""Parameter spill tier — ZeRO-Infinity params / ZeRO-Inference.

Reference: `runtime/swap_tensor/partitioned_param_swapper.py:36`
(`AsyncPartitionedParameterSwapper`) and the ZeRO-Inference recipe
(`docs/_posts/2022-09-10-zero-inference.md:35`): model weights live on
host RAM or NVMe and stream through device memory layer by layer, so the
servable model size is bounded by disk, not HBM.

TPU-native shape of the same idea:

  * the transformer stack is homogeneous — ONE compiled per-layer function
    is reused for every layer (weights are arguments, not constants);
  * `LayerParamStore` owns the per-layer host copies — "cpu" backend keeps
    them as numpy trees, "nvme" keeps them on disk via the AIO library
    (O_DIRECT, threaded) with a ring of staging slots. Each slot owns its
    OWN aio handle, so waiting for layer i's read to land never barriers
    the deeper read-ahead queued on other slots — that per-slot wait
    granularity is what makes the disk tier genuinely double-buffered.
  * `LayerStreamer` double-buffers host->HBM uploads: while layer i
    computes, layer i+1's `jax.device_put` is already in flight (uploads
    are async under JAX's dispatch model), and the NVMe read for layer i+2
    is queued behind it. HBM never holds more than `lookahead+1` layers of
    weights + the resident (embedding/norm/head) leaves.

The streamer measures the overlap instead of asserting it: every layer
acquisition that finds its buffer already staged records a ~0
`offload/stage_wait_ms`; a genuinely late buffer records the real host
stall. `offload/staging_occupancy` / `offload/inflight_bytes` gauges and
the `stats()` counters (hits, stall_ms_total) feed the bench offload
lane's stall-fraction column (docs/offload.md).

The reference needs ~1.8k LoC of swap machinery because every torch param
object must be rewired in place; here a layer's weights are just pytree
arguments to a jitted function, so the whole tier is this file.
"""

import pathlib
import time

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


def _tree_bytes(tree):
    return sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
               for l in jax.tree_util.tree_leaves(tree))


class _StageSlot:
    """One ring slot of the NVMe staging pool: its own aio handle (so its
    completion barrier covers only its own reads), the layer it holds, and
    the aligned host buffers the reads land in."""

    __slots__ = ("swapper", "layer", "bufs", "inflight")

    def __init__(self, swap_folder, threads):
        from deepspeed_tpu.runtime.swap_tensor import AsyncTensorSwapper
        self.swapper = AsyncTensorSwapper(swap_folder, num_threads=threads)
        self.layer = None       # layer index staged (or being read) here
        self.bufs = None        # host leaf buffers for that layer
        self.inflight = False   # read submitted, completion not yet waited

    def wait(self):
        if self.inflight:
            self.swapper.wait()
            self.inflight = False

    def release(self):
        self.swapper.release()


class LayerParamStore:
    """Host/NVMe store of L structurally-identical per-layer param trees.

    `stacked` is a pytree whose leaves carry a leading layer dimension L
    (the model zoo's `params["blocks"]` layout). device="cpu" keeps all L
    trees in host RAM; device="nvme" writes each layer to one file under
    `swap_folder` and serves reads through `staging` ring slots, each with
    its own aio handle and reusable aligned buffers (reference
    `partitioned_param_swapper.py` double-buffering — here with per-slot
    completion, so read-ahead on other slots keeps flowing while one layer
    lands).

    `max_write_bytes` bounds the async write-back queue (`put(blocking=
    False)`): submitted-but-unflushed write bytes past the budget force a
    flush, so the disk tier cannot pin unbounded host RAM behind a slow
    NVMe queue. None = 8 layers' worth; 0 = unbounded (flush per step via
    `flush_writes`)."""

    def __init__(self, stacked, device="cpu", swap_folder=None, staging=3,
                 aio_threads=4, dtype=None, max_write_bytes=None):
        leaves, self.treedef = jax.tree_util.tree_flatten(stacked)
        self.num_layers = int(leaves[0].shape[0])
        assert all(int(l.shape[0]) == self.num_layers for l in leaves), \
            "every stacked leaf must share the leading layer dimension"
        self.device = device
        self.telemetry = None       # optional Telemetry, set by the owner
        cast = (lambda a: a) if dtype is None else (
            lambda a: np.asarray(a).astype(dtype))

        host_layers = []
        for i in range(self.num_layers):
            host_layers.append([cast(np.asarray(l[i])) for l in leaves])
        self.leaf_meta = [(l.shape, l.dtype) for l in host_layers[0]]
        self.layer_bytes = sum(int(np.prod(s)) * np.dtype(d).itemsize
                               for s, d in self.leaf_meta)

        # async-write accounting (both tiers expose the counters so the
        # streamer's inflight gauge has one spelling)
        self.pending_write_bytes = 0
        self.inflight_read_bytes = 0
        self.write_flushes = 0
        if max_write_bytes is None:
            max_write_bytes = 8 * self.layer_bytes
        self.max_write_bytes = int(max_write_bytes)

        if device == "cpu":
            self._layers = host_layers
            self._ring = None
            self._wswapper = None
        elif device == "nvme":
            from deepspeed_tpu.runtime.swap_tensor import AsyncTensorSwapper
            assert swap_folder is not None, "nvme offload needs a swap_folder"
            self._swap_folder = swap_folder
            # initial spill through a throwaway bulk writer
            spill = AsyncTensorSwapper(swap_folder, num_threads=aio_threads)
            for i, layer in enumerate(host_layers):
                for j, arr in enumerate(layer):
                    spill.swap_out(f"layer{i}_leaf{j}", arr)
            spill.wait()
            spill.release()
            self._layers = None
            self._wswapper = None  # created lazily on first put()
            # staging ring: per-slot aio handles split the thread budget so
            # total aio threads stay ~aio_threads regardless of depth
            n_slots = max(2, int(staging))
            per_slot = max(1, aio_threads // n_slots)
            self._ring = [_StageSlot(swap_folder, per_slot)
                          for _ in range(n_slots)]
            logger.info(f"LayerParamStore: {self.num_layers} layers x "
                        f"{self.layer_bytes / 1e6:.1f} MB spilled to "
                        f"{pathlib.Path(swap_folder)} "
                        f"({n_slots} staging slots)")
        else:
            raise ValueError(f"unknown spill device {device!r} (cpu|nvme)")

    @property
    def host_bytes(self):
        """Total host/disk-resident bytes of the spilled tier — the number
        memscope's host column must match EXACTLY (plan_training_from_
        infinity compares against this)."""
        return self.layer_bytes * self.num_layers

    @property
    def inflight_bytes(self):
        """Bytes currently in asynchronous flight through this store:
        queued NVMe reads + submitted-but-unflushed write-back."""
        return self.inflight_read_bytes + self.pending_write_bytes

    # ---- nvme staging ----

    def _slot_for(self, i):
        return self._ring[i % len(self._ring)]

    def prefetch(self, i):
        """Queue the async NVMe read for layer i (no-op on the cpu tier or if
        already staged/in flight). Only the target slot's previous read is
        waited (its buffers are about to be reused); reads on other slots
        stay in flight — the per-slot handles are what make this a
        prefetch, not a barrier."""
        if self._ring is None or not (0 <= i < self.num_layers):
            return
        slot = self._slot_for(i)
        if slot.layer == i:
            return
        if slot.inflight:
            # the slot's previous occupant still has a read in flight — let
            # it land before its buffers are dropped (otherwise the AIO
            # threads would write into freed memory)
            slot.wait()
            self.inflight_read_bytes = max(
                0, self.inflight_read_bytes - self.layer_bytes)
        slot.bufs = [slot.swapper.swap_in(f"layer{i}_leaf{j}", shape, dt)
                     for j, (shape, dt) in enumerate(self.leaf_meta)]
        slot.layer = i
        slot.inflight = True
        self.inflight_read_bytes += self.layer_bytes

    def get(self, i):
        """Host leaf list for layer i. Blocks only on layer i's OWN slot:
        read-ahead queued on other slots keeps flowing while this one
        lands (the old single-handle design paid a global completion
        barrier here, serializing the very overlap prefetch() created)."""
        if self._layers is not None:
            return self._layers[i]
        slot = self._slot_for(i)
        if slot.layer != i:
            self.prefetch(i)
        if slot.inflight:
            slot.wait()
            self.inflight_read_bytes = max(
                0, self.inflight_read_bytes - self.layer_bytes)
        assert slot.layer == i, f"staging ring lost layer {i} (holds {slot.layer})"
        return slot.bufs

    def get_tree(self, i):
        return jax.tree_util.tree_unflatten(self.treedef, self.get(i))

    def put(self, i, leaves, blocking=False):
        """Write layer i's (updated) host leaves back to the store — the
        training-side swap-out (reference `AsyncPartitionedParameterSwapper`
        writes updated fp16 partitions back after the optimizer step).

        Writes go through a SEPARATE swapper so queued read-ahead stays in
        flight (a shared queue would make every put a full barrier). The
        layer's leaves are submitted as ONE batch and budget-checked once
        per layer (not per leaf): with `blocking=False` (default) they
        accumulate against `max_write_bytes` — past the budget the put
        itself flushes, so a slow disk cannot queue unbounded host RAM.
        The caller still runs `flush_writes()` before the next read of this
        layer — the training loop does it once per step, not per layer."""
        leaves = [np.asarray(l) for l in leaves]
        if self._layers is not None:
            self._layers[i] = leaves
            return
        slot = self._slot_for(i)
        if slot.layer == i:
            if slot.inflight:
                # a read of the OLD content is mid-flight into ring buffers
                # under the same names — let it land before the overwrite
                slot.wait()
                self.inflight_read_bytes = max(
                    0, self.inflight_read_bytes - self.layer_bytes)
            slot.layer = slot.bufs = None      # staged copy is now stale
        if self._wswapper is None:
            from deepspeed_tpu.runtime.swap_tensor import AsyncTensorSwapper
            self._wswapper = AsyncTensorSwapper(self._swap_folder)
        for j, arr in enumerate(leaves):
            self._wswapper.swap_out(f"layer{i}_leaf{j}", arr)
        self.pending_write_bytes += self.layer_bytes
        if blocking or (self.max_write_bytes and
                        self.pending_write_bytes > self.max_write_bytes):
            self.flush_writes()

    def flush_writes(self):
        """Barrier on outstanding put() writes (reads are unaffected)."""
        if getattr(self, "_wswapper", None) is not None and \
                self.pending_write_bytes:
            t0 = time.perf_counter()
            self._wswapper.wait()
            self.pending_write_bytes = 0
            self.write_flushes += 1
            tel = self.telemetry
            if tel is not None and getattr(tel, "enabled", False):
                tel.observe("offload/write_flush_ms",
                            (time.perf_counter() - t0) * 1e3)

    def release(self):
        if self._ring is not None:
            for slot in self._ring:
                slot.release()
        if getattr(self, "_wswapper", None) is not None:
            self._wswapper.release()


class LayerStreamer:
    """Async double-buffered host->device staging of `LayerParamStore`
    layers.

    `layer(i)` returns layer i's params on device, having already issued
    the (async) upload of layers i+1..i+lookahead and queued NVMe prefetch
    one step deeper — layer i computes while layer i+1's `jax.device_put`
    and layer i+2's disk read are in flight, so the step never blocks
    except on a genuinely late buffer. `lookahead=0` is the blocking
    baseline (every acquisition is a miss) — the bench offload lane's
    comparison arm.

    `cyclic=True` pins the look-ahead to the scan order of a repeating
    layer walk (decode: L-1 wraps to 0), so the first layer of the next
    pass is already staged when the current pass finishes — without it the
    wrap evicts everything and every pass restarts cold.

    `peak_live_layers` records the high-water mark of simultaneously
    device-resident layers — the HBM working set of the spill tier — for
    tests and memory accounting. With `telemetry` set (any object with the
    Telemetry facade), every acquisition records `offload/stage_wait_ms`
    (0 for a staged hit, the measured host stall otherwise) and refreshes
    the `offload/staging_occupancy` / `offload/inflight_bytes` gauges."""

    def __init__(self, store: LayerParamStore, shardings=None, lookahead=1,
                 cyclic=False, telemetry=None, clock=None):
        self.store = store
        self.lookahead = max(0, int(lookahead))
        self.cyclic = bool(cyclic)
        self.telemetry = telemetry
        self._clock = clock if clock is not None else time.perf_counter
        self._shardings = (None if shardings is None
                           else jax.tree_util.tree_leaves(shardings))
        self._live = {}          # layer idx -> device leaf list
        self.peak_live_layers = 0
        self.uploads = 0
        self.acquires = 0
        self.hits = 0            # layer() calls served from the live window
        self.stall_ms_total = 0.0  # host time blocked making a layer live

    @property
    def depth(self):
        """Staging depth alias: lookahead+1 device buffers in rotation."""
        return self.lookahead + 1

    def _wrap(self, i):
        return i % self.store.num_layers if self.cyclic else i

    def _upload(self, i):
        if i in self._live or not (0 <= i < self.store.num_layers):
            return
        host = self.store.get(i)
        # jax.device_put dispatches asynchronously: the H2D copy overlaps
        # whatever compute is already enqueued — nothing here blocks on it
        if self._shardings is None:
            dev = [jax.device_put(h) for h in host]
        else:
            dev = [jax.device_put(h, s) for h, s in zip(host, self._shardings)]
        self._live[i] = dev
        self.uploads += 1
        self.peak_live_layers = max(self.peak_live_layers, len(self._live))

    def layer(self, i, direction=1):
        """Device param tree for layer i; evicts layers outside the
        look-ahead window and uploads ahead in `direction` (+1 for the
        forward pass, -1 for the reversed backward pass of the Infinity
        trainer). The stall measurement covers ONLY making layer i itself
        available — the deeper uploads/prefetch run after it, unmeasured,
        because they are the overlap machinery, not the stall."""
        self.acquires += 1
        step = 1 if direction >= 0 else -1
        window = {self._wrap(i + d * step) for d in range(self.lookahead + 1)}
        for j in list(self._live):
            # frees the HBM buffers (no other reference remains); the out-
            # of-window check also catches the turn-around between passes
            if j not in window:
                del self._live[j]
        hit = i in self._live
        if hit:
            self.hits += 1
            wait_ms = 0.0
        else:
            t0 = self._clock()
            self._upload(i)
            wait_ms = (self._clock() - t0) * 1e3
            self.stall_ms_total += wait_ms
        # look-ahead uploads (their get() may take a slot's completion
        # barrier), THEN the next NVMe read-ahead so it stays truly async
        for d in range(1, self.lookahead + 1):
            self._upload(self._wrap(i + d * step))
        self.store.prefetch(self._wrap(i + (self.lookahead + 1) * step))
        tel = self.telemetry
        if tel is not None and getattr(tel, "enabled", False):
            tel.observe("offload/stage_wait_ms", wait_ms)
            tel.set_gauge("offload/staging_occupancy", len(self._live))
            tel.set_gauge("offload/inflight_bytes", self.store.inflight_bytes)
        return jax.tree_util.tree_unflatten(self.store.treedef, self._live[i])

    def stats(self):
        """Host-side overlap counters for the bench offload lane (available
        with telemetry off): acquisitions, staged hits, and the total host
        stall — stall_ms_total / step wall time is the stall fraction."""
        return {"acquires": self.acquires, "hits": self.hits,
                "uploads": self.uploads,
                "hit_rate": self.hits / max(1, self.acquires),
                "stall_ms_total": round(self.stall_ms_total, 3),
                "peak_live_layers": self.peak_live_layers}

    def reset(self):
        self._live.clear()
