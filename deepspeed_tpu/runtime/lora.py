"""LoRA adapters — low-rank deltas with fuse/unfuse.

Reference: the Hybrid Engine's LoRA handling (`runtime/hybrid_engine.py:32`
fuses LoRA weights into the base matrices before injected-kernel inference and
unfuses for the next training phase).

TPU formulation: the adapter is a pytree mirroring the params tree with
{"a": [in, r], "b": [r, out]} at adapted 2-D leaves. Three pure functions
cover the reference's lifecycle:
  * `apply_lora`  — W_eff = W + scale·(a@b), traced into the forward (training:
    only the adapter leaves get gradients; the base stays frozen)
  * `fuse_lora`   — materialize W + scale·(a@b) once (inference/generation)
  * `unfuse_lora` — subtract it back out (resume training after generate)
"""

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    # which 2-D leaves get adapters: path predicate ("/"-joined key path)
    match: Optional[Callable[[str], bool]] = None

    @property
    def scaling(self):
        return self.alpha / self.rank


def _default_match(path):
    # attention + mlp projection matrices in the model zoo's naming; NOT the
    # embeddings (wte/wpe) or tied output head
    leaf = path.rsplit("/", 1)[-1]
    return leaf in ("attn_qkv_w", "attn_out_w", "mlp_up_w", "mlp_down_w",
                    "mlp_gate_w")


def init_lora(params, cfg: LoRAConfig, seed=0):
    """Adapter tree for every matched 2-D leaf: a ~ N(0, 1/r) (kaiming-style),
    b = 0 — so the adapted model starts EXACTLY at the base model."""
    match = cfg.match or _default_match
    rng = np.random.default_rng(seed)

    def build(tree, path=()):
        if isinstance(tree, dict):
            out = {}
            for k, v in tree.items():
                sub = build(v, path + (str(k),))
                if sub is not None:
                    out[k] = sub
            return out or None
        leaf = tree
        p = "/".join(path)
        if getattr(leaf, "ndim", 0) == 2 and match(p):
            din, dout = leaf.shape[-2], leaf.shape[-1]
            a = jnp.asarray(rng.normal(0, 1.0 / cfg.rank, (din, cfg.rank)),
                            jnp.float32)
            return {"a": a.astype(leaf.dtype),
                    "b": jnp.zeros((cfg.rank, dout), leaf.dtype)}
        if getattr(leaf, "ndim", 0) == 3 and match(p):
            # stacked-block leaves [L, din, dout] (the zoo's scan layout)
            L, din, dout = leaf.shape
            a = jnp.asarray(rng.normal(0, 1.0 / cfg.rank, (L, din, cfg.rank)),
                            jnp.float32)
            return {"a": a.astype(leaf.dtype),
                    "b": jnp.zeros((L, cfg.rank, dout), leaf.dtype)}
        return None

    return build(params) or {}


def _delta(ad, scaling):
    a, b = ad["a"], ad["b"]
    if a.ndim == 3:
        return scaling * jnp.einsum("lir,lro->lio", a, b)
    return scaling * (a @ b)


def _map_adapted(params, lora, fn):
    """Rebuild params applying fn(leaf, adapter) where an adapter exists."""
    def rec(p, l):
        if isinstance(p, dict):
            return {k: rec(v, (l or {}).get(k)) for k, v in p.items()}
        return p if not isinstance(l, dict) or "a" not in l else fn(p, l)

    return rec(params, lora)


def apply_lora(params, lora, cfg: LoRAConfig):
    """Effective weights for the forward pass (traced; grads flow to a/b)."""
    s = cfg.scaling
    return _map_adapted(params, lora,
                        lambda w, ad: w + _delta(ad, s).astype(w.dtype))


def fuse_lora(params, lora, cfg: LoRAConfig):
    """Materialize the merged weights (reference fuse before generate)."""
    return apply_lora(params, lora, cfg)


def unfuse_lora(params, lora, cfg: LoRAConfig):
    """Inverse of fuse_lora (reference unfuse after generate).

    Subtraction happens in fp32 to minimize rounding drift, but in low
    precision (bf16 base) repeated fuse/unfuse cycles still accumulate error —
    prefer keeping the pristine base tree and re-deriving with `apply_lora`
    (free under XLA) over round-tripping through the fused weights."""
    s = cfg.scaling
    return _map_adapted(
        params, lora,
        lambda w, ad: (w.astype(jnp.float32) - _delta(ad, s).astype(jnp.float32)
                       ).astype(w.dtype))


def lora_loss_fn(base_loss_fn, frozen_params, cfg: LoRAConfig):
    """loss_fn(lora, batch[, rng]) training ONLY the adapter. The base is
    frozen because it is a closed-over constant, not the differentiated
    argument; stop_gradient inside the trace documents and enforces that."""

    def loss_fn(lora, batch, rng=None):
        frozen = jax.lax.stop_gradient(frozen_params)
        return base_loss_fn(apply_lora(frozen, lora, cfg), batch, rng)

    return loss_fn
