"""Progressive Layer Drop (PLD).

Reference: `runtime/progressive_layer_drop.py` — keep-probability schedule
theta(t) = (1 - theta_bar) * exp(-gamma * t) + theta_bar feeding stochastic
layer skipping during BERT-style pretraining.

TPU-native use: `theta(step)` is a host-side scalar passed into the jitted step;
the model consumes it via a per-layer bernoulli mask folded into the `lax.scan`
over blocks (static shapes — the drop multiplies the residual branch by 0/1 and
rescales, never changing the graph).
"""

import numpy as np


class ProgressiveLayerDrop:
    def __init__(self, theta=0.5, gamma=0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step):
        def _prob(x, g, t):
            return (1.0 - t) * np.exp(-g * x) + t

        self.current_theta = float(_prob(global_step, self.gamma, self.theta))

    # reference name parity
    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}


def pld_block_scan(block_fn, x, stacked_params, theta, rng):
    """Scan over layers with stochastic depth at keep-prob theta.

    Per layer i: keep ~ Bernoulli(theta); output = x + keep/theta * f(x) — the
    inverted-dropout rescale keeps expectations unchanged. `block_fn(x, p)` must
    return the residual *delta* (not x + delta).
    """
    import jax
    import jax.numpy as jnp

    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    keys = jax.random.split(rng, n_layers)

    def body(carry, inp):
        params_i, key = inp
        keep = jax.random.bernoulli(key, theta).astype(carry.dtype)
        delta = block_fn(carry, params_i)
        return carry + delta * keep / jnp.maximum(theta, 1e-6), None

    out, _ = jax.lax.scan(body, x, (stacked_params, keys))
    return out
