"""Argparse integration — analog of `deepspeed.add_config_arguments`
(`deepspeed/__init__.py:246`, `runtime/config.py` `_add_core_arguments`)."""


def add_config_arguments(parser):
    group = parser.add_argument_group("DeepSpeed-TPU", "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed",
                       default=False,
                       action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag for config toggling)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the config JSON file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated alias of --deepspeed")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated alias of --deepspeed_config")
    group.add_argument("--local_rank", type=int, default=-1,
                       help="Accepted for launcher parity; unused (one process drives all local chips)")
    return parser
