"""Activation checkpointing.

Reference: `runtime/activation_checkpointing/checkpointing.py` (1,248 LoC) —
Megatron-style `CheckpointFunction` with partitioned activations across MP ranks,
CPU checkpointing, contiguous buffers, and a CUDA RNG tracker.

On TPU the mechanism collapses into `jax.checkpoint` policies:
  * `checkpoint(fn)`                → recompute in backward (same semantics)
  * partition_activations          → `save_and_offload_only_these_names` /
                                     sharding constraints on residuals (XLA keeps
                                     saved activations sharded already under SPMD)
  * cpu_checkpointing              → `jax.checkpoint` + host offload policy
                                     (`offload_dot_with_no_batch_dims` family)
  * RNG tracker                    → explicit PRNG keys (pure functional already)

`configure()`/`is_configured()` keep the reference's module-level API so ported
client code (Megatron-style) runs unchanged.
"""

from functools import partial

import jax

from deepspeed_tpu.utils.logging import logger

_CONFIG = {
    "partition_activations": False,
    "contiguous_memory_optimization": False,
    "cpu_checkpointing": False,
    "num_checkpoints": None,
    "synchronize": False,
    "profile": False,
    "policy": None,
}
_CONFIGURED = False

POLICIES = {
    "full": None,  # save nothing, recompute everything
    "nothing_saveable": None,
    "dots": "dots_saveable",
    "dots_saveable": "dots_saveable",
    "dots_with_no_batch_dims": "dots_with_no_batch_dims_saveable",
    "dots_with_no_batch_dims_saveable": "dots_with_no_batch_dims_saveable",
    "offload_dots": "save_and_offload_dot_with_no_batch_dims",
}


def configure(mpu_=None,
              deepspeed_config=None,
              partition_activations=None,
              contiguous_checkpointing=None,
              num_checkpoints=None,
              checkpoint_in_cpu=None,
              synchronize=None,
              profile=None,
              policy=None):
    """Reference `configure` (`checkpointing.py:1057`) signature."""
    global _CONFIGURED
    if deepspeed_config is not None:
        ac = getattr(deepspeed_config, "activation_checkpointing", None)
        if ac is not None:
            _CONFIG.update(partition_activations=ac.partition_activations,
                           cpu_checkpointing=ac.cpu_checkpointing,
                           contiguous_memory_optimization=ac.contiguous_memory_optimization,
                           num_checkpoints=ac.number_checkpoints,
                           policy=ac.policy)
    for key, val in (("partition_activations", partition_activations),
                     ("contiguous_memory_optimization", contiguous_checkpointing),
                     ("num_checkpoints", num_checkpoints),
                     ("cpu_checkpointing", checkpoint_in_cpu),
                     ("synchronize", synchronize),
                     ("profile", profile),
                     ("policy", policy)):
        if val is not None:
            _CONFIG[key] = val
    _CONFIGURED = True


def is_configured():
    return _CONFIGURED


def _resolve_policy(name):
    if name is None:
        name = _CONFIG.get("policy") or "full"
    mapped = POLICIES.get(name, name)
    if mapped is None:
        return None
    pol = getattr(jax.checkpoint_policies, mapped, None)
    if pol is None:
        logger.warning(f"unknown remat policy '{name}', defaulting to full recompute")
    return pol


def checkpoint(function, *args, policy=None, prevent_cse=True):
    """Reference `CheckpointFunction.apply` style entry: runs `function(*args)`
    under remat. Also usable as a decorator factory via `checkpoint_wrapper`."""
    fn = jax.checkpoint(function, policy=_resolve_policy(policy),
                        prevent_cse=prevent_cse)
    return fn(*args)


def checkpoint_wrapper(function, policy=None, prevent_cse=True):
    """Decorator form: `block = checkpoint_wrapper(block_fn)`.

    Pass `prevent_cse=False` when the wrapped fn is applied inside
    `lax.scan`/`lax.while_loop` — the loop boundary already blocks the CSE
    that prevent_cse guards against, and the relaxed form lets XLA schedule
    the recompute better (measured +6% MFU on the GPT bench lanes)."""
    return jax.checkpoint(function, policy=_resolve_policy(policy),
                          prevent_cse=prevent_cse)


class CheckpointFunction:
    """Name-parity shim (reference `checkpointing.py:477`)."""

    @staticmethod
    def apply(run_function, *args):
        return checkpoint(run_function, *args)


# RNG-tracker parity: functional keys make this a bookkeeping no-op, but Megatron
# imports these names.
class CudaRNGStatesTracker:
    def __init__(self):
        self.states_ = {}

    def add(self, name, seed):
        self.states_[name] = jax.random.PRNGKey(seed)

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def fork(self, name="model-parallel-rng"):
        import contextlib
        return contextlib.nullcontext()


_RNG_TRACKER = CudaRNGStatesTracker()


def get_cuda_rng_tracker():
    return _RNG_TRACKER


def model_parallel_cuda_manual_seed(seed):
    _RNG_TRACKER.add("model-parallel-rng", seed)
