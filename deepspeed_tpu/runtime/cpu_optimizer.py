"""Host-side optimizer step for ZeRO-Offload / ZeRO-Infinity.

Reference: `deepspeed/ops/adam/cpu_adam.py:13` over `csrc/adam/cpu_adam_impl.cpp`
— fp32 master weights + moments live on host (or NVMe), the step runs on CPU
cores while the accelerator computes, and only bit16 params return to the device.

`HostOffloadOptimizer` owns: fp32 master (numpy), moments (numpy or NVMe-swapped),
the C++ step (OpenMP-SIMD), and the device push of updated compute-dtype params.
The engine uses it when `zero_optimization.offload_optimizer.device == "nvme"`
(state on disk) or `"cpu"` with `offload_optimizer.fast_init` … any config where
the step itself must leave the device.
"""

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


class HostOffloadOptimizer:
    """Flat-leaf host Adam/AdamW (+Lion/Adagrad) with optional NVMe state tier."""

    def __init__(self, params, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adamw_mode=True, bias_correction=True,
                 optimizer="adam", nvme_folder=None, lr_schedule=None,
                 aio_threads=4):
        from deepspeed_tpu.ops.op_builder import CPUAdamBuilder
        self.lib = CPUAdamBuilder().load()
        self.lr = lr
        self.lr_schedule = lr_schedule
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.bias_correction = bias_correction
        self.optimizer = optimizer
        self.step_count = 0

        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        self.shapes = [l.shape for l in leaves]
        # dstpu: ignore[DT001]: host-offload tier — the fp32 master lives in host RAM by design (built once)
        self.master = [np.asarray(jax.device_get(l), np.float32).copy() for l in leaves]

        self.nvme = None
        if nvme_folder is not None:
            from deepspeed_tpu.runtime.swap_tensor import OptimizerStateSwapper
            self.nvme = OptimizerStateSwapper(nvme_folder, num_threads=aio_threads)
            init = {}
            for i, m in enumerate(self.master):
                init[f"m_{i}"] = np.zeros_like(m)
                if optimizer == "adam":
                    init[f"v_{i}"] = np.zeros_like(m)
            self.nvme.initialize(init)
            self.exp_avg = None
            self.exp_avg_sq = None
        else:
            self.exp_avg = [np.zeros_like(m) for m in self.master]
            self.exp_avg_sq = ([np.zeros_like(m) for m in self.master]
                               if optimizer == "adam" else None)

    def _current_lr(self):
        if self.lr_schedule is not None:
            return float(self.lr_schedule(self.step_count))
        return self.lr

    def step(self, grads_tree):
        """grads_tree: pytree of (device or numpy) fp32 grads. Returns updated
        master params as a pytree of numpy fp32."""
        self.step_count += 1
        lr = self._current_lr()
        leaves = jax.tree_util.tree_flatten(grads_tree)[0]
        # ONE bulk device->host transfer per step: dispatch every leaf's
        # D2H copy first (non-blocking under JAX's dispatch model), then
        # land them together — the old per-leaf device_get paid a host
        # sync per leaf, serializing the transfer against the conversion
        for g in leaves:
            if hasattr(g, "copy_to_host_async"):
                g.copy_to_host_async()
        # dstpu: ignore[DT001]: host-offload tier — grads MUST land in host RAM for the C++ optimizer; the copies were dispatched async above, this is the single landing barrier per step
        grads = [np.asarray(g, np.float32) for g in jax.device_get(leaves)]

        if self.nvme is not None:
            states = self.nvme.swap_in_all()
            exp_avg = [states[f"m_{i}"] for i in range(len(self.master))]
            exp_avg_sq = [states.get(f"v_{i}") for i in range(len(self.master))]
        else:
            exp_avg, exp_avg_sq = self.exp_avg, self.exp_avg_sq or [None] * len(self.master)

        for i, (p, g, m) in enumerate(zip(self.master, grads, exp_avg)):
            n = p.size
            if self.optimizer == "adam":
                v = exp_avg_sq[i]
                self.lib.dstpu_cpu_adam_step(
                    p.ctypes.data, np.ascontiguousarray(g).ctypes.data,
                    m.ctypes.data, v.ctypes.data, n, lr,
                    self.betas[0], self.betas[1], self.eps, self.weight_decay,
                    1 if self.adamw_mode else 0, self.step_count,
                    1 if self.bias_correction else 0)
            elif self.optimizer == "lion":
                self.lib.dstpu_cpu_lion_step(
                    p.ctypes.data, np.ascontiguousarray(g).ctypes.data,
                    m.ctypes.data, n, lr, self.betas[0], self.betas[1],
                    self.weight_decay)
            else:
                self.lib.dstpu_cpu_adagrad_step(
                    p.ctypes.data, np.ascontiguousarray(g).ctypes.data,
                    m.ctypes.data, n, lr, self.eps, self.weight_decay)

        if self.nvme is not None:
            out = {}
            for i, m in enumerate(exp_avg):
                out[f"m_{i}"] = m
                if exp_avg_sq[i] is not None:
                    out[f"v_{i}"] = exp_avg_sq[i]
            self.nvme.swap_out_all(out)

        return jax.tree_util.tree_unflatten(self.treedef, self.master)

    def state_dict(self):
        sd = {"step": self.step_count, "master": self.master}
        if self.nvme is None:
            sd["exp_avg"] = self.exp_avg
            if self.exp_avg_sq is not None:
                sd["exp_avg_sq"] = self.exp_avg_sq
        else:
            # NVMe-swapped moments are still part of the optimizer state:
            # pull them through the swapper so a checkpoint of this tier is
            # complete (previously they were silently dropped)
            states = self.nvme.swap_in_all()
            n = len(self.master)
            sd["exp_avg"] = [np.array(states[f"m_{i}"]) for i in range(n)]
            if self.optimizer == "adam":
                sd["exp_avg_sq"] = [np.array(states[f"v_{i}"])
                                    for i in range(n)]
        return sd

    def load_state_dict(self, sd):
        self.step_count = int(np.asarray(sd["step"]))
        self.master = [np.asarray(m, np.float32) for m in sd["master"]]
        if "exp_avg" not in sd:
            return
        exp_avg = [np.asarray(m, np.float32) for m in sd["exp_avg"]]
        exp_avg_sq = None
        if "exp_avg_sq" in sd:
            exp_avg_sq = [np.asarray(m, np.float32) for m in sd["exp_avg_sq"]]
        if self.nvme is None:
            self.exp_avg = exp_avg
            if exp_avg_sq is not None:
                self.exp_avg_sq = exp_avg_sq
        else:
            out = {}
            for i, m in enumerate(exp_avg):
                out[f"m_{i}"] = m
                if exp_avg_sq is not None:
                    out[f"v_{i}"] = exp_avg_sq[i]
            self.nvme.swap_out_all(out)
