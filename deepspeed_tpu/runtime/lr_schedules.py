"""LR schedules with the reference's names and semantics.

Reference: `runtime/lr_schedules.py` (763 LoC) — WarmupLR, WarmupDecayLR,
WarmupCosineLR, OneCycle, LRRangeTest. Each is a pure, **jnp-traceable** function
`step -> lr` (optax-schedule style) so it folds into the jitted train step; a thin
stateful wrapper preserves the torch-scheduler-like `step()/get_lr()` API the
engine exposes.
"""

import math
from typing import Any

import jax.numpy as jnp

WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"
ONE_CYCLE = "OneCycle"
LR_RANGE_TEST = "LRRangeTest"


def warmup_lr(warmup_min_lr=0.0, warmup_max_lr=0.001, warmup_num_steps=1000, warmup_type="log", **_):
    """WarmupLR: warm from min→max then hold (reference WarmupLR)."""
    wn = max(warmup_num_steps, 1)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        if warmup_type == "log":
            frac = jnp.log(step + 1.0) / math.log(wn + 1.0)
        else:
            frac = step / wn
        frac = jnp.clip(frac, 0.0, 1.0)
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * frac

    return schedule


def warmup_decay_lr(total_num_steps,
                    warmup_min_lr=0.0,
                    warmup_max_lr=0.001,
                    warmup_num_steps=1000,
                    warmup_type="log",
                    **_):
    """WarmupDecayLR: warmup then linear decay to 0 at total_num_steps."""
    wl = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
    wn = max(warmup_num_steps, 1)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        decay = jnp.clip((total_num_steps - step) / max(total_num_steps - wn, 1), 0.0, 1.0)
        return jnp.where(step < wn, wl(step), warmup_max_lr * decay)

    return schedule


def warmup_cosine_lr(total_num_steps,
                     warmup_min_ratio=0.0,
                     warmup_num_steps=1000,
                     cos_min_ratio=0.0001,
                     warmup_max_lr=0.001,
                     **_):
    wn = max(warmup_num_steps, 1)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = warmup_max_lr * (warmup_min_ratio + (1 - warmup_min_ratio) * jnp.clip(step / wn, 0.0, 1.0))
        progress = jnp.clip((step - wn) / max(total_num_steps - wn, 1), 0.0, 1.0)
        cos = 0.5 * (1 + jnp.cos(jnp.pi * progress))
        decay = warmup_max_lr * (cos_min_ratio + (1 - cos_min_ratio) * cos)
        return jnp.where(step < wn, warm, decay)

    return schedule


def one_cycle(cycle_min_lr,
              cycle_max_lr,
              decay_lr_rate=0.0,
              cycle_first_step_size=2000,
              cycle_second_step_size=None,
              cycle_first_stair_count=0,
              cycle_second_stair_count=None,
              decay_step_size=0,
              **_):
    """OneCycle: min→max over first phase, max→min over second, then decay."""
    first = max(cycle_first_step_size, 1)
    second = max(cycle_second_step_size if cycle_second_step_size is not None else first, 1)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        up = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * jnp.clip(step / first, 0.0, 1.0)
        down = cycle_max_lr - (cycle_max_lr - cycle_min_lr) * jnp.clip((step - first) / second, 0.0, 1.0)
        post = jnp.maximum(step - first - second, 0.0)
        if decay_step_size > 0:
            decayed = cycle_min_lr * (1.0 - decay_lr_rate)**jnp.floor(post / decay_step_size)
        else:
            decayed = jnp.asarray(cycle_min_lr, jnp.float32)
        return jnp.where(step <= first, up, jnp.where(step <= first + second, down, decayed))

    return schedule


def lr_range_test(lr_range_test_min_lr=1e-3,
                  lr_range_test_step_size=2000,
                  lr_range_test_step_rate=1.0,
                  lr_range_test_staircase=False,
                  **_):
    size = max(lr_range_test_step_size, 1)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        interval = step / size
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1 + interval * lr_range_test_step_rate)

    return schedule


SCHEDULE_REGISTRY = {
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
    WARMUP_COSINE_LR: warmup_cosine_lr,
    ONE_CYCLE: one_cycle,
    LR_RANGE_TEST: lr_range_test,
}


def build_schedule(scheduler_config) -> Any:
    if scheduler_config is None or scheduler_config.type is None:
        return None
    name = scheduler_config.type
    if name not in SCHEDULE_REGISTRY:
        raise ValueError(f"Unknown scheduler '{name}'. Known: {sorted(SCHEDULE_REGISTRY)}")
    return SCHEDULE_REGISTRY[name](**scheduler_config.params)


class LRScheduler:
    """Stateful wrapper with the torch-like API the reference engine exposes
    (`engine.lr_scheduler.step()`, `.get_lr()`)."""

    def __init__(self, schedule_fn, last_step=0):
        self.schedule_fn = schedule_fn
        self.last_step = last_step

    def step(self, increment=1):
        self.last_step += increment

    def get_lr(self):
        return [float(self.schedule_fn(self.last_step))]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_step": self.last_step}

    def load_state_dict(self, sd):
        self.last_step = sd["last_step"]


def add_tuning_arguments(parser):
    """Reference `add_tuning_arguments` (`runtime/lr_schedules.py:56`): the
    convergence-tuning CLI surface. Same flag names so reference training
    scripts parse unchanged; values feed the `scheduler` config block."""
    g = parser.add_argument_group("Convergence Tuning",
                                  "Convergence tuning configurations")
    g.add_argument("--lr_schedule", type=str, default=None)
    # LR range test
    g.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    g.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    g.add_argument("--lr_range_test_step_size", type=int, default=1000)
    g.add_argument("--lr_range_test_staircase", type=bool, default=False)
    # OneCycle
    g.add_argument("--cycle_first_step_size", type=int, default=1000)
    g.add_argument("--cycle_first_stair_count", type=int, default=-1)
    g.add_argument("--cycle_second_step_size", type=int, default=-1)
    g.add_argument("--cycle_second_stair_count", type=int, default=-1)
    g.add_argument("--decay_step_size", type=int, default=1000)
    g.add_argument("--cycle_min_lr", type=float, default=0.01)
    g.add_argument("--cycle_max_lr", type=float, default=0.1)
    g.add_argument("--decay_lr_rate", type=float, default=0.0)
    g.add_argument("--cycle_momentum", default=False, action="store_true")
    g.add_argument("--cycle_min_mom", type=float, default=0.8)
    g.add_argument("--cycle_max_mom", type=float, default=0.9)
    g.add_argument("--decay_mom_rate", type=float, default=0.0)
    # Warmup
    g.add_argument("--warmup_min_lr", type=float, default=0)
    g.add_argument("--warmup_max_lr", type=float, default=0.001)
    g.add_argument("--warmup_num_steps", type=int, default=1000)
    g.add_argument("--warmup_type", type=str, default="log")
    return parser
