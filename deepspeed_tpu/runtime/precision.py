"""Mixed precision: dynamic loss scaling + master-weight policy.

Reference: `runtime/fp16/loss_scaler.py` (LossScaler/DynamicLossScaler),
`runtime/fp16/fused_optimizer.py:31` (fp32 master copy + overflow-check + skip step),
`runtime/bf16_optimizer.py:30` (bf16 params + fp32 master).

TPU-native formulation: the scaler is a tiny pytree threaded through the jitted
train step; overflow-skip is a `jnp.where` masked update (no Python branch, so the
step stays a single compiled program — the reference re-runs the step eagerly).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.tree import tree_all_finite


class LossScaleState(NamedTuple):
    scale: jnp.ndarray          # f32 scalar
    good_steps: jnp.ndarray     # i32 scalar — consecutive overflow-free steps
    overflows: jnp.ndarray      # i32 scalar — total skipped steps (diagnostics)
    hysteresis_left: jnp.ndarray  # i32 scalar — overflows tolerated before scale cut


class LossScaler:
    """Static or dynamic loss scaling as pure functions over LossScaleState."""

    def __init__(self,
                 static_scale=None,
                 initial_scale_power=16,
                 loss_scale_window=1000,
                 hysteresis=2,
                 consecutive_hysteresis=False,
                 min_loss_scale=1.0,
                 scale_factor=2.0,
                 enabled=True):
        self.enabled = enabled
        self.dynamic = static_scale in (None, 0, 0.0)
        self.static_scale = float(static_scale or 2.0**initial_scale_power)
        self.initial_scale = float(2.0**initial_scale_power) if self.dynamic else self.static_scale
        self.loss_scale_window = loss_scale_window
        self.hysteresis = hysteresis
        self.consecutive_hysteresis = consecutive_hysteresis
        self.min_loss_scale = float(min_loss_scale)
        self.scale_factor = float(scale_factor)

    def init(self) -> LossScaleState:
        return LossScaleState(scale=jnp.asarray(self.initial_scale if self.enabled else 1.0, jnp.float32),
                              good_steps=jnp.asarray(0, jnp.int32),
                              overflows=jnp.asarray(0, jnp.int32),
                              hysteresis_left=jnp.asarray(self.hysteresis, jnp.int32))

    def scale_loss(self, loss, state: LossScaleState):
        if not self.enabled:
            return loss
        return loss * state.scale.astype(loss.dtype)

    def unscale_grads(self, grads, state: LossScaleState):
        if not self.enabled:
            return grads
        # unscale in fp32 (reference FP16_Optimizer semantics): dividing in
        # fp16 underflows small grads to zero once the scale grows (fp16 min
        # normal is 6e-5), silently freezing training
        inv = (1.0 / state.scale).astype(jnp.float32)
        return jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) * inv, grads)

    def check_overflow(self, grads):
        """True == all finite (no overflow)."""
        if not self.enabled:
            return jnp.asarray(True)
        return tree_all_finite(grads)

    def update(self, state: LossScaleState, grads_finite) -> LossScaleState:
        """Dynamic scale update (jittable), matching reference DynamicLossScaler
        semantics (`runtime/fp16/loss_scaler.py`): on overflow, decrement the
        hysteresis budget and only cut the scale when it is exhausted; double after
        `loss_scale_window` consecutive clean steps (which also refills hysteresis
        unless `consecutive_hysteresis` tracking keeps it drained)."""
        if not self.enabled or not self.dynamic:
            return state._replace(
                good_steps=state.good_steps + 1,
                overflows=state.overflows + jnp.where(grads_finite, 0, 1),
            )
        new_good = jnp.where(grads_finite, state.good_steps + 1, 0)
        grow = new_good >= self.loss_scale_window
        scale_up = jnp.where(grow, state.scale * self.scale_factor, state.scale)

        hyst_exhausted = state.hysteresis_left <= 1
        cut_scale = jnp.maximum(state.scale / self.scale_factor, self.min_loss_scale)
        new_scale = jnp.where(grads_finite,
                              scale_up,
                              jnp.where(hyst_exhausted, cut_scale, state.scale))
        # refill hysteresis on a clean step unless consecutive_hysteresis is set
        new_hyst = jnp.where(grads_finite,
                             (state.hysteresis_left if self.consecutive_hysteresis
                              else jnp.asarray(self.hysteresis, jnp.int32)),
                             jnp.maximum(state.hysteresis_left - 1, 1))
        return LossScaleState(scale=new_scale,
                              good_steps=jnp.where(grow, 0, new_good).astype(jnp.int32),
                              overflows=(state.overflows + jnp.where(grads_finite, 0, 1)).astype(jnp.int32),
                              hysteresis_left=new_hyst.astype(jnp.int32))


def masked_update(new_tree, old_tree, apply_mask):
    """Elementwise select: apply_mask ? new : old — the jittable skip-step."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(apply_mask, n.astype(o.dtype), o), new_tree, old_tree)
