"""Mixed-precision quantization (MoQ) scheduling.

Reference: `runtime/quantize.py` (`Quantizer`) — progressive bit reduction
during QAT: each time a layer's quantization period expires its bit width
drops by one and the next period doubles; when eigenvalue estimation is on,
the period is additionally stretched by `1 + floor(ev * 4)` so high-curvature
layers keep precision longer (`quantize.py:129-137`, `engine.py:1769-1780`).

TPU-native split of responsibilities:
  * the fake-quant itself is a pure transform inside the compiled loss
    (`compression/basic_layer.fake_quantize`, STE);
  * `MoQScheduler` here is host-side bookkeeping — per-layer bits/periods
    advanced once per optimizer step. When bits change the engine retraces
    its step program (bounded by layers × (start_bits - target_bits)
    recompiles over a whole run, not per step);
  * `block_eigenvalues` replaces the reference's per-block autograd loops
    (`runtime/eigenvalue.py:60-120`) with ONE jitted program: the stacked
    `blocks` [L, ...] layout lets a vmapped Hessian-vector product run the
    power iteration for every layer's diagonal block H_ii simultaneously
    (masking v to one layer's slice makes (Hv)_i = H_ii v_i exact).
"""

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import log_dist

TWO_D_PARAMS = 6  # reference quantize.py:17 — schedule granularity constant


class MoQScheduler:
    """Per-layer progressive bit-reduction schedule (reference `Quantizer`)."""

    def __init__(self, start_bits: int = 16, target_bits: int = 8,
                 period: int = 100, layer_num: int = 1):
        self.layer_num = max(int(layer_num), 1)
        self.target_bits = int(target_bits)
        self.bits = [int(start_bits)] * self.layer_num
        self.period = [int(period)] * self.layer_num
        self.qsteps = 0

    def any_precision_switch(self) -> bool:
        """True while some layer still has bits to shed (reference
        `any_precision_switch`, quantize.py:38)."""
        return any(b > self.target_bits for b in self.bits)

    def step(self, block_eigenvalue: Optional[Sequence[float]] = None) -> bool:
        """Advance one optimizer step. `block_eigenvalue`: per-layer values in
        [0, 1] (see `post_process_eigenvalues`). Returns True when any layer's
        bit width changed — the caller must retrace its compiled loss."""
        self.qsteps += 1
        changed = False
        for i in range(self.layer_num):
            if self.bits[i] <= self.target_bits:
                continue
            if self.qsteps >= self.period[i]:
                ev = None
                if block_eigenvalue is not None and len(block_eigenvalue):
                    ev = float(block_eigenvalue[min(i, len(block_eigenvalue) - 1)])
                factor = 1 + math.floor(ev * 4) if ev is not None else 1
                # reference quantize.py:133-135: double, then scale by curvature
                self.period[i] = self.period[i] * 2 * factor
                self.bits[i] -= 1
                changed = True
                log_dist(f"MoQ: layer {i} -> {self.bits[i]} bits "
                         f"(next period {self.period[i]}"
                         + (f", ev factor {factor}" if ev is not None else "")
                         + ")", ranks=[0])
        return changed

    def bits_vector(self, n_layers: int):
        """Per-layer bits broadcast to `n_layers` (models whose stacked depth
        differs from the schedule's layer_num reuse the last entry)."""
        if self.layer_num >= n_layers:
            return list(self.bits[:n_layers])
        return list(self.bits) + [self.bits[-1]] * (n_layers - self.layer_num)


def post_process_eigenvalues(evs):
    """Map raw per-layer eigenvalues to [0, 1] relative to the largest;
    non-finite / zero entries become 1.0 (keep full precision longest) —
    reference `Eigenvalue.post_process` (`runtime/eigenvalue.py:145-149`)."""
    evs = [float(v) for v in evs]
    finite = [abs(v) for v in evs if math.isfinite(v) and v != 0.0]
    if not finite:
        return [1.0] * len(evs)
    mx = max(finite)
    return [abs(v) / mx if math.isfinite(v) and v != 0.0 else 1.0 for v in evs]


def block_eigenvalues(loss_fn, params, batch, max_iter: int = 100,
                      tol: float = 1e-2, stability: float = 1e-6,
                      seed: int = 0):
    """Per-layer dominant eigenvalue of the block-diagonal Hessian.

    `params` must carry the model zoo's stacked layout (`params['blocks']`
    leaves with leading layer dim L). For a tangent v supported on layer i
    only, the Hessian-vector product restricted to slice i equals H_ii v_i
    exactly, so one vmapped hvp advances all L power iterations per sweep —
    the whole estimation is a single XLA program vs the reference's L
    Python-side autograd loops. Returns a length-L list of raw eigenvalues
    (feed through `post_process_eigenvalues` before scheduling).
    """
    blocks = params["blocks"]
    L = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    rest = {k: v for k, v in params.items() if k != "blocks"}

    grad_fn = jax.grad(lambda b: loss_fn({**rest, "blocks": b}, batch))

    def layer_mask(i, tree):
        def leaf(a):
            sel = (jnp.arange(a.shape[0]) == i).astype(a.dtype)
            return a * sel.reshape((a.shape[0],) + (1,) * (a.ndim - 1))
        return jax.tree_util.tree_map(leaf, tree)

    def layer_hvp(i, v):
        # v: blocks-shaped, row i of every leaf holds layer i's vector.
        # Slice row i of the product: (Hv)_i = H_ii v_i exactly (the tangent
        # is supported on layer i only), and returning just that row keeps the
        # mapped output at [L, ...] — one model's worth — instead of an
        # [L, L, ...] stack of masked copies.
        hv = jax.jvp(grad_fn, (blocks,), (layer_mask(i, v),))[1]
        return jax.tree_util.tree_map(lambda l: l[i], hv)

    def norms(v):
        """Per-layer L2 norms [L] over all leaves."""
        sq = sum(jnp.sum((l.astype(jnp.float32))**2,
                         axis=tuple(range(1, l.ndim)))
                 for l in jax.tree_util.tree_leaves(v))
        return jnp.sqrt(sq)

    def normalize(v):
        n = norms(v)
        return jax.tree_util.tree_map(
            lambda l: l / (n.reshape((L,) + (1,) * (l.ndim - 1)) + stability), v)

    @jax.jit
    def run():
        leaves, treedef = jax.tree_util.tree_flatten(blocks)
        keys = jax.random.split(jax.random.PRNGKey(seed), len(leaves))
        v0 = treedef.unflatten([jax.random.normal(k, l.shape, jnp.float32)
                                for k, l in zip(keys, leaves)])
        v0 = normalize(v0)
        idx = jnp.arange(L)

        def body(carry):
            v, prev, it, _ = carry
            # vmap batches L tangent copies (L x model memory in
            # intermediates) — fine for typical depths; deep models switch to
            # lax.map (sequential: one tangent's activations live at a time,
            # same one-program property). Both produce [L, ...] outputs.
            if L <= 16:
                hv = jax.vmap(layer_hvp, in_axes=(0, None))(idx, v)
            else:
                hv = jax.lax.map(lambda i: layer_hvp(i, v), idx)
            ev = sum(jnp.sum(a.astype(jnp.float32) * b.astype(jnp.float32),
                             axis=tuple(range(1, a.ndim)))
                     for a, b in zip(jax.tree_util.tree_leaves(v),
                                     jax.tree_util.tree_leaves(hv)))
            done = jnp.all(jnp.abs(ev - prev) <=
                           tol * jnp.maximum(jnp.abs(ev), 1e-12))
            return normalize(hv), ev, it + 1, done

        def cond(carry):
            _, _, it, done = carry
            return (~done) & (it < max_iter)

        _, ev, _, _ = jax.lax.while_loop(
            cond, body, (v0, jnp.full((L,), jnp.inf, jnp.float32),
                         jnp.asarray(0, jnp.int32), jnp.asarray(False)))
        return ev

    return [float(x) for x in jax.device_get(run())]
