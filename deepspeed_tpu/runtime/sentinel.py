"""Training-loop bad-state sentinels.

The fp16 path already masks a single overflowing step (skip-step + loss-scale
backoff); what it cannot express is *persistent* bad state — NaN/Inf loss that
keeps coming back under bf16/fp32 (no scaler to mask it), an overflow streak
that outlives every loss-scale halving, or a loss spike that signals silently
corrupted params. `BadStateSentinel` watches the per-step metrics host-side
and reports a cause once a budget is exhausted; the engine then either rolls
back in-process to the last good checkpoint (`fault_tolerance.auto_rollback`)
or raises `BadStateError` for the elastic agent to classify and restart on.

Deliberately stdlib-only: `elasticity/elastic_agent.py` imports
`BadStateError` for its restart-cause taxonomy without pulling in jax.
"""

import math
from collections import deque


class BadStateError(RuntimeError):
    """Training state is unrecoverable in place (persistent non-finite loss,
    overflow streak, loss spike). Carries `cause` for the elastic agent's
    restart taxonomy."""

    def __init__(self, cause, message):
        super().__init__(message)
        self.cause = cause


CAUSE_NONFINITE = "nonfinite_loss"
CAUSE_OVERFLOW = "overflow_streak"
CAUSE_LOSS_SPIKE = "loss_spike"


class BadStateSentinel:
    """Consecutive-budget tracker over (loss, overflow) observations.

    * `nonfinite_budget`: consecutive non-finite losses tolerated past the
      masked skip-step (fp16 overflow steps count separately).
    * `overflow_budget`: consecutive fp16 overflow skip-steps tolerated —
      a healthy dynamic scaler recovers in a handful; a streak this long
      means the state itself is bad.
    * `loss_spike_window`/`loss_spike_factor`: a finite loss above
      factor × (rolling median over the window) for `loss_spike_patience`
      consecutive steps trips the spike cause. window=0 disables.
    """

    def __init__(self, config=None, *, enabled=None, recorder=None):
        cfg = config
        g = (lambda name, d: getattr(cfg, name, d)) if cfg is not None \
            else (lambda name, d: d)
        self.enabled = bool(g("enabled", False) if enabled is None else enabled)
        # optional telemetry FlightRecorder: every trip becomes a black-box
        # event (duck-typed `.record(kind, **fields)`; None = no recording,
        # keeping this module stdlib-only and telemetry-agnostic)
        self.recorder = recorder
        self.nonfinite_budget = int(g("nonfinite_budget", 3))
        self.overflow_budget = int(g("overflow_budget", 50))
        self.loss_spike_window = int(g("loss_spike_window", 0))
        self.loss_spike_factor = float(g("loss_spike_factor", 10.0))
        self.loss_spike_patience = int(g("loss_spike_patience", 3))
        self.reset()

    def reset(self):
        """Clear all streaks — called after a rollback/restore so the restored
        state gets a fresh budget."""
        self._nonfinite = 0
        self._overflows = 0
        self._spikes = 0
        self._history = deque(maxlen=max(self.loss_spike_window, 1))

    def observe(self, loss, overflow=False):
        """Feed one optimizer step's (host) loss and overflow flag. Returns a
        cause string once a budget is exhausted, else None."""
        if not self.enabled:
            return None
        if overflow:
            # masked skip-step: params untouched, scaler backing off — only a
            # *streak* is pathological
            self._overflows += 1
            if self.overflow_budget > 0 and self._overflows >= self.overflow_budget:
                return self._trip(CAUSE_OVERFLOW, loss)
            return None
        self._overflows = 0
        if loss is None or not math.isfinite(loss):
            self._nonfinite += 1
            if self.nonfinite_budget > 0 and self._nonfinite >= self.nonfinite_budget:
                return self._trip(CAUSE_NONFINITE, loss)
            return None
        self._nonfinite = 0
        if self.loss_spike_window > 0:
            if len(self._history) >= self.loss_spike_window:
                med = sorted(self._history)[len(self._history) // 2]
                if med > 0 and loss > self.loss_spike_factor * med:
                    self._spikes += 1
                    if self._spikes >= self.loss_spike_patience:
                        return self._trip(CAUSE_LOSS_SPIKE, loss)
                    return None  # spike suspects stay out of the baseline
                self._spikes = 0
            self._history.append(loss)
        return None

    def _trip(self, cause, loss):
        """A budget just exhausted: file the black-box event (best-effort —
        a broken recorder must never mask the cause) and hand the cause up
        for the engine's rollback/restart decision."""
        if self.recorder is not None:
            try:
                self.recorder.record("sentinel_trip", cause=cause,
                                     loss=None if loss is None else float(loss),
                                     detail=self.describe(cause))
            except Exception:
                pass
        return cause

    def describe(self, cause):
        return {
            CAUSE_NONFINITE: (f"loss non-finite for {self._nonfinite} "
                              f"consecutive steps (budget "
                              f"{self.nonfinite_budget})"),
            CAUSE_OVERFLOW: (f"{self._overflows} consecutive fp16 overflow "
                             f"skip-steps (budget {self.overflow_budget})"),
            CAUSE_LOSS_SPIKE: (f"loss > {self.loss_spike_factor}x rolling "
                               f"median for {self._spikes} steps"),
        }.get(cause, cause)
