"""ZeRO as SPMD sharding policy.

The reference implements ZeRO with ~10k LoC of hook-driven partitioning
(`runtime/zero/stage_1_and_2.py:96`, `stage3.py:72`, `partition_parameters.py:723`,
`partitioned_param_coordinator.py:58`). On TPU the same memory behavior is a set of
sharding decisions handed to XLA:

  stage 0 — params/grads/opt replicated over the data domain (grad allreduce).
  stage 1 — optimizer state + fp32 master sharded over the data domain; grads
            allreduced; each shard updates its slice; updated params re-replicated
            (all-gather) by sharding propagation.
  stage 2 — same, plus gradients constrained to the master sharding before the
            update → XLA emits reduce-scatter instead of all-reduce (the
            `average_tensor` hot loop, `stage_1_and_2.py:956`).
  stage 3 — parameters themselves sharded; XLA inserts all-gathers before use and
            frees gathered copies after (what `fetch_sub_module`/`release_sub_module`
            do by hand); its latency-hiding scheduler is the prefetcher.

Small parameters stay replicated below `stage3_param_persistence_threshold`
(reference `zero/config.py` same knob). TP-annotated axes (from the model's
PartitionSpecs) are preserved; ZeRO shards a remaining free axis.

MiCS (`zero/mics.py:55`) = shard over a sub-axis of the data domain; hpZ
(ZeRO++ secondary partition) = same idea applied to a secondary copy. Both are
expressed here by splitting the data domain; see `partition_domain()`.
"""

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.utils.logging import logger


def _spec_axes(spec):
    """Set of mesh axis names already used in a PartitionSpec."""
    used = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


def _axis_size(mesh: Mesh, axes):
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if isinstance(axes, (tuple, list)):
        return int(np.prod([sizes[a] for a in axes]))
    return sizes[axes]


def shard_leaf_spec(shape,
                    base_spec: Optional[P],
                    shard_axes,
                    shard_size: int,
                    min_size: int = 0) -> P:
    """Add `shard_axes` (e.g. ('data','sequence')) to one free dimension of a leaf.

    Picks the largest dimension divisible by `shard_size` that is not already
    sharded; returns `base_spec` unchanged if none qualifies or the leaf is smaller
    than `min_size` elements (persistence threshold).
    """
    base = tuple(base_spec) if base_spec is not None else ()
    base = base + (None,) * (len(shape) - len(base))
    if shard_size <= 1:
        return P(*base)
    if int(np.prod(shape)) < max(min_size, 1) or len(shape) == 0:
        return P(*base)
    used = _spec_axes(base)
    if any(a in used for a in shard_axes):
        return P(*base)

    # candidate dims: unsharded, divisible. Prefer dim 0 on ties (reduce-scatter
    # friendly); otherwise the largest.
    best_dim, best_size = None, -1
    for d, n in enumerate(shape):
        if base[d] is not None:
            # dimension already sharded by TP; it could take extra axes, but keep
            # ZeRO orthogonal to TP for clean collective placement.
            continue
        if n % shard_size == 0 and n > best_size:
            best_dim, best_size = d, n
    if best_dim is None:
        return P(*base)
    new = list(base)
    existing = new[best_dim]
    if existing is None:
        new[best_dim] = tuple(shard_axes) if len(shard_axes) > 1 else shard_axes[0]
    return P(*new)


class ZeroShardingPolicy:
    """Resolves the sharding of every training-state tensor for a ZeRO stage."""

    def __init__(self, zero_config, mesh: Mesh):
        self.config = zero_config
        self.mesh = mesh
        self.stage = zero_config.stage
        self.mics = bool(zero_config.mics_shard_size and zero_config.mics_shard_size > 0)
        self.hpz = int(getattr(zero_config, "zero_hpz_partition_size", 1) or 1)
        self.domain = self.partition_domain()
        self.domain_size = _axis_size(mesh, self.domain)
        self.param_domain = self.param_partition_domain()
        self.param_domain_size = _axis_size(mesh, self.param_domain)
        self.persistence_threshold = (zero_config.stage3_param_persistence_threshold
                                      if self.stage == 3 else 0)

    def partition_domain(self):
        """Mesh axes forming the ZeRO state partition domain.

        MiCS (`mics_shard_size`, reference `zero/mics.py:55`) confines ALL sharding
        (params, grads, optimizer states) to the inner `zero` sub-axis — the
        sub-group rides adjacent ICI neighbors; XLA reduces within the group
        (reduce-scatter over `zero`) and replicates across groups (all-reduce over
        `data`), the MiCS hierarchical communication pattern.
        """
        if self.mics:
            return (mesh_mod.ZERO_INNER_AXIS,)
        return mesh_mod.ZERO_AXES

    def param_partition_domain(self):
        """Axes over which stage-3 *parameters* shard.

        hpZ (ZeRO++ secondary partition, `zero/config.py:256`): optimizer states
        shard over the full domain, but the bf16 params gather from a secondary
        copy sharded only within the `zero` sub-group (one node) — forward/backward
        all-gathers ride ICI, never DCN.
        """
        if self.stage == 3 and self.hpz > 1 and not self.mics:
            return (mesh_mod.ZERO_INNER_AXIS,)
        return self.domain

    # ---- params ----

    def param_spec(self, shape, base_spec=None) -> P:
        if self.stage < 3:
            base = tuple(base_spec) if base_spec is not None else ()
            base = base + (None,) * (len(shape) - len(base))
            return P(*base)
        return shard_leaf_spec(shape, base_spec, self.param_domain,
                               self.param_domain_size,
                               min_size=self.persistence_threshold)

    def param_shardings(self, params, param_specs=None):
        def leaf(path, p):
            base = None
            if param_specs is not None:
                base = _get_path(param_specs, path)
            return NamedSharding(self.mesh, self.param_spec(p.shape, base))

        return _tree_map_with_path(leaf, params)

    # ---- optimizer state / fp32 master ----

    def state_spec(self, shape, base_spec=None) -> P:
        if self.stage == 0:
            base = tuple(base_spec) if base_spec is not None else ()
            base = base + (None,) * (len(shape) - len(base))
            return P(*base)
        # stages 1-3: shard everything shardable over the domain
        return shard_leaf_spec(shape, base_spec, self.domain, self.domain_size, min_size=0)

    def state_shardings(self, state_shapes, base_specs=None):
        """Shardings for a pytree of ShapeDtypeStructs (from jax.eval_shape)."""
        # TP-annotation-loss guard (r4 advisor): _get_path returns None for
        # paths it cannot resolve, which is CORRECT for scalar bookkeeping
        # leaves (count, step) but silently drops tensor-parallel layouts on
        # matrix-shaped moments if an optimizer nests its state in a
        # container shape the suffix-retry does not recognize — warn loudly
        # on exactly that signature instead of quietly replicating
        any_nontrivial = base_specs is not None and any(
            isinstance(sp, P) and any(e is not None for e in sp)
            for sp in jax.tree_util.tree_leaves(base_specs))

        # per-POLICY dedup (not module-global): a later engine in the same
        # process must still get its own warning for the same state path
        warned = self.__dict__.setdefault("_unresolved_state_paths", set())

        def leaf(path, s):
            base = _get_path(base_specs, path) if base_specs is not None else None
            if base is None and any_nontrivial and len(s.shape) >= 2:
                key = jax.tree_util.keystr(path)
                if key not in warned:
                    warned.add(key)
                    logger.warning(
                        "optimizer-state leaf %s (shape %s) resolved no base "
                        "PartitionSpec: its shard will not carry the model's "
                        "TP annotations (unrecognized state-tree nesting — "
                        "see zero.py _get_path)", key, tuple(s.shape))
            return NamedSharding(self.mesh, self.state_spec(s.shape, base))

        return _tree_map_with_path(leaf, state_shapes)

    # ---- gradients ----

    def reduce_domain(self, compressed_comm_axis=None):
        """Split the grad-reduce domain into (fast_axes, slow_axis) for the
        engine's explicit hierarchical reduce: plain psum rides the fast
        (ICI) axes, the transform-compressed wire rides the slow axis — on a
        pod slice the outermost data axis is the DCN tier (the reference
        qgZ intra-node/inter-node split, `coalesced_collectives.py:31`).

        Returns `(fast_axes, slow_axis)`; `slow_axis` is None when the data
        domain is a single device (nothing to reduce).
        """
        axes = [a for a in mesh_mod.ZERO_AXES if _axis_size(self.mesh, a) > 1]
        if not axes:
            return (), None
        slow = compressed_comm_axis or axes[0]
        if slow not in axes:
            raise ValueError(
                f"compressed_comm_axis {slow!r} is not a data-domain axis "
                f"with size > 1 on this mesh; candidates: {axes}")
        return tuple(a for a in axes if a != slow), slow

    def grad_shardings(self, params, param_shardings, master_shardings):
        """Sharding constraint applied to grads before the optimizer update.

        stage <=1: match params (allreduce semantics — XLA reduces then replicates).
        stage >=2: match the master/opt sharding → reduce-scatter.
        """
        if self.stage >= 2:
            return master_shardings
        return param_shardings


def _tree_map_with_path(fn, tree):
    return jax.tree_util.tree_map_with_path(lambda path, leaf: fn(path, leaf), tree)


def _get_path(tree, path, _suffix_retry=True):
    """Fetch same-path leaf from a parallel tree (returns None when absent).

    Falls back to suffix matching: optimizer state wraps the param tree in extra
    levels (e.g. (0, 'mu', <param path...>)), so we retry after dropping leading
    path components until the param-spec tree resolves.
    """
    if tree is None:
        return None

    def resolve(p):
        node = tree
        for key in p:
            if hasattr(key, "key"):
                node = node[key.key]
            elif hasattr(key, "idx"):
                node = node[key.idx]
            elif hasattr(key, "name"):
                node = getattr(node, key.name)
            else:
                return None
        return node

    for start in range(len(path) + 1 if _suffix_retry else 1):
        try:
            node = resolve(path[start:])
        except (KeyError, IndexError, TypeError, AttributeError):
            continue
        # only accept leaves (PartitionSpec), not subtrees
        from jax.sharding import PartitionSpec
        if isinstance(node, PartitionSpec):
            return node
        if start == 0 and node is None:
            return None
    return None
