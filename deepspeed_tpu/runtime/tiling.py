"""Tiled linear — split a large matmul to cap live activation memory.

Reference: `TiledLinear` (`zero/tiling.py:32`) splits a big Linear into
in/out-feature tiles so ZeRO-3 only gathers one tile's weights at a time.
On TPU the same pressure point is VMEM/HBM working set: `tiled_matmul` runs the
output tiles through `lax.scan` (or in one fused pass when tiling is 1), so
peak live memory is one tile of weights + accumulator instead of the whole
product. With ZeRO-3 sharded weights, each scan step gathers only its slice —
the direct analog of the reference's per-tile gather.
"""

import jax
import jax.numpy as jnp
import numpy as np


def tiled_matmul(x, w, b=None, out_splits=1, in_splits=1):
    """x: [..., K] @ w: [K, N] (+ b[N]) with output/input-dim tiling.

    out_splits tiles N (concatenated results); in_splits tiles K (summed
    partial products, scan-accumulated in f32).
    """
    K, N = w.shape
    assert N % out_splits == 0 and K % in_splits == 0
    assert in_splits == 1 or out_splits == 1, (
        "tile one dimension at a time (combined K and N tiling is not supported)")

    if in_splits > 1:
        xt = jnp.stack(jnp.split(x, in_splits, axis=-1))       # [S, ..., K/S]
        wt = jnp.stack(jnp.split(w, in_splits, axis=0))        # [S, K/S, N]

        def body(acc, inp):
            xi, wi = inp
            return acc + (xi @ wi).astype(jnp.float32), None

        acc0 = jnp.zeros(x.shape[:-1] + (N,), jnp.float32)
        out, _ = jax.lax.scan(body, acc0, (xt, wt))
        out = out.astype(x.dtype)
    elif out_splits > 1:
        wt = jnp.stack(jnp.split(w, out_splits, axis=1))       # [S, K, N/S]

        def body(_, wi):
            return None, x @ wi

        _, tiles = jax.lax.scan(body, None, wt)                # [S, ..., N/S]
        out = jnp.moveaxis(tiles, 0, -2).reshape(x.shape[:-1] + (N,))
    else:
        out = x @ w
    if b is not None:
        out = out + b
    return out


class TiledLinear:
    """Functional module with the reference's constructor surface
    (`zero/tiling.py:32`: in_splits/out_splits/input_is_already_split)."""

    def __init__(self, in_features, out_features, bias=True, in_splits=1,
                 out_splits=1, input_is_already_split=False, seed=0,
                 dtype=jnp.float32):
        rng = np.random.default_rng(seed)
        bound = 1.0 / np.sqrt(in_features)
        self.weight = jnp.asarray(
            rng.uniform(-bound, bound, (in_features, out_features)), dtype)
        self.bias = jnp.zeros((out_features,), dtype) if bias else None
        self.in_splits = in_splits
        self.out_splits = out_splits
        self.input_is_already_split = input_is_already_split

    def __call__(self, x):
        if self.input_is_already_split:
            x = jnp.concatenate(x, axis=-1)
        return tiled_matmul(x, self.weight, self.bias,
                            out_splits=self.out_splits, in_splits=self.in_splits)
