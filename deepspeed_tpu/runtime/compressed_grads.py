"""Compressed-gradient (1-bit) optimizers.

Reference: `runtime/fp16/onebit/adam.py:14` (OnebitAdam), `onebit/lamb.py`,
`onebit/zoadam.py`, with the error-feedback compressed allreduce in
`runtime/comm/nccl.py:51` (cupy bit-packing).

TPU-native realization: error-feedback quantization happens *inside* the jitted
step — grads are quantized to 1-bit sign + per-tensor scale, the quantization error
is carried in optimizer state and added back next step. The communication saving
materializes when the grad sharding constraint forces a collective on the quantized
representation; in the fully-compiled SPMD formulation we apply the
quantize→dequantize (with error feedback) transform to preserve the optimizer's
numerics and convergence behavior, and rely on int8 collective lowering for the
wire format (see ops/quant.py).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class ErrorFeedbackState(NamedTuple):
    error: optax.Updates  # residual from previous quantization
    inner: optax.OptState
    step: jnp.ndarray


def error_feedback_compress(warmup_steps: int = 100):
    """Transform: after `warmup_steps`, replace grads with sign(grad+error)*scale and
    carry the residual (1-bit Adam's compression stage)."""

    def init(params):
        return ErrorFeedbackState(
            error=jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32), params),
            inner=optax.EmptyState(),
            step=jnp.zeros((), jnp.int32),
        )

    def update(updates, state, params=None):
        in_warmup = state.step < warmup_steps

        # two passes producing plain array trees (no tuple leaves, which would
        # collide with tuple-structured pytrees)
        def compressed_leaf(g, e):
            corrected = g.astype(jnp.float32) + e
            scale = jnp.mean(jnp.abs(corrected))
            q = (jnp.sign(corrected) * scale).astype(g.dtype)
            return jnp.where(in_warmup, g, q)

        def error_leaf(g, e):
            corrected = g.astype(jnp.float32) + e
            scale = jnp.mean(jnp.abs(corrected))
            q = jnp.sign(corrected) * scale
            return jnp.where(in_warmup, e, corrected - q)

        out = jax.tree_util.tree_map(compressed_leaf, updates, state.error)
        new_err = jax.tree_util.tree_map(error_leaf, updates, state.error)
        return out, ErrorFeedbackState(error=new_err, inner=state.inner, step=state.step + 1)

    return optax.GradientTransformation(init, update)


def onebit_adam(lr, params_dict):
    betas = params_dict.get("betas", (0.9, 0.999))
    warmup = params_dict.get("freeze_step", params_dict.get("warmup_steps", 100))
    return optax.chain(
        error_feedback_compress(warmup_steps=warmup),
        optax.adam(lr, b1=betas[0], b2=betas[1], eps=params_dict.get("eps", 1e-8)),
    )
