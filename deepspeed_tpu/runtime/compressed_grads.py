"""1-bit (compressed-communication) optimizer family.

Reference: `runtime/fp16/onebit/adam.py:14` (OnebitAdam), `onebit/lamb.py:15`
(OnebitLamb), `onebit/zoadam.py:14` (ZeroOneAdam), built on the error-feedback
compressed allreduce `runtime/comm/nccl.py:51` (cupy sign packing, gather-scatter
over chunks).

Shared structure of all three (and of this module): a **warmup phase** running
the exact base optimizer, then a **compressed phase** where the second moment is
frozen and the quantity communicated across data-parallel workers is the 1-bit
sign of the momentum plus one scale, with the quantization residual carried
forward (error feedback) so the compression bias cancels over steps.

TPU-native realization: the optimizer is an `optax.GradientTransformation` whose
post-freeze update applies sign+scale quantization with error feedback to the
momentum *inside the compiled step*. Numerics (and therefore convergence
behavior) match the reference's compressed path; the wire-format saving on a
real pod comes from the int8/int4 quantized collective layer
(`runtime/quantized_collectives.py`, config `zero_quantized_gradients`) that the
engine swaps in for the gradient reduction — mesh-wide sign bits ride ICI as
int8, the TPU equivalent of the reference's cupy bit-packed NCCL allreduce.
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class ErrorFeedbackState(NamedTuple):
    error: optax.Updates  # residual from previous quantization
    inner: optax.OptState
    step: jnp.ndarray


def _sign_compress(x):
    """1-bit quantization: sign scaled so the L1 norm is preserved
    (reference `compressed_allreduce` uses mean-|x| scaling per chunk).

    Runs through the comm facade's onebit wire — a full
    `onebit_encode`/`onebit_decode` roundtrip (`comm/collectives.py`), the
    SAME code the compressed all-reduce sends over the slow axis — so the
    error-feedback quantization rule lives in exactly one place. The wire
    maps sign(0) → +1 (every value packs to one bit) where the old inline
    `jnp.sign(x)*mean|x|` mapped it to 0; momenta are never exactly zero,
    and the EF residual absorbs the difference when they are."""
    from deepspeed_tpu.comm.collectives import onebit_decode, onebit_encode
    flat = x.astype(jnp.float32).ravel()
    packed, scale = onebit_encode(flat)
    return onebit_decode(packed, scale, flat.shape[0]).reshape(x.shape)


def error_feedback_compress(warmup_steps: int = 100):
    """Standalone transform: after `warmup_steps`, replace grads with
    sign(grad+error)*scale and carry the residual (gradient-compression stage
    usable in front of any base optimizer)."""

    def init(params):
        return ErrorFeedbackState(
            error=jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32), params),
            inner=optax.EmptyState(),
            step=jnp.zeros((), jnp.int32),
        )

    def update(updates, state, params=None):
        in_warmup = state.step < warmup_steps

        def compressed_leaf(g, e):
            corrected = g.astype(jnp.float32) + e
            q = _sign_compress(corrected)
            return jnp.where(in_warmup, g, q.astype(g.dtype))

        def error_leaf(g, e):
            corrected = g.astype(jnp.float32) + e
            q = _sign_compress(corrected)
            return jnp.where(in_warmup, e, corrected - q)

        out = jax.tree_util.tree_map(compressed_leaf, updates, state.error)
        new_err = jax.tree_util.tree_map(error_leaf, updates, state.error)
        return out, ErrorFeedbackState(error=new_err, inner=state.inner, step=state.step + 1)

    return optax.GradientTransformation(init, update)


class OnebitAdamState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates        # first moment
    nu: optax.Updates        # second moment (FROZEN after freeze_step)
    error: optax.Updates     # worker error feedback on compressed momentum


def _onebit_core(freeze_step, b1, b2, eps, nu_update_mask_fn=None,
                 compress_from=None):
    """Shared Adam-with-compressed-momentum machinery.

    nu_update_mask_fn(count) -> bool array deciding whether nu updates this step
    (OnebitAdam: count < freeze_step; ZeroOneAdam: variance-update intervals).
    compress_from: step at which momentum compression starts (defaults to
    freeze_step; ZeroOneAdam compresses from step 0 — the "0 warmup" in its
    name).
    """
    if compress_from is None:
        compress_from = freeze_step
    if nu_update_mask_fn is None:
        def nu_update_mask_fn(count):
            return count < freeze_step

    def init(params):
        z = lambda p: jnp.zeros_like(p, jnp.float32)
        return OnebitAdamState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree_util.tree_map(z, params),
            nu=jax.tree_util.tree_map(z, params),
            error=jax.tree_util.tree_map(z, params),
        )

    def moments(updates, state):
        in_warmup = state.count < compress_from
        update_nu = nu_update_mask_fn(state.count)

        def mu_leaf(g, m):
            return b1 * m + (1 - b1) * g.astype(jnp.float32)

        def nu_leaf(g, v):
            v_new = b2 * v + (1 - b2) * jnp.square(g.astype(jnp.float32))
            return jnp.where(update_nu, v_new, v)

        new_mu = jax.tree_util.tree_map(mu_leaf, updates, state.mu)
        new_nu = jax.tree_util.tree_map(nu_leaf, updates, state.nu)

        # compressed phase: communicate sign(mu)+scale with error feedback.
        # The compressed tensor REPLACES the momentum on every worker (the
        # reference's server-synchronized exp_avg after compressed allreduce).
        def comp_leaf(m, e):
            corrected = m + e
            q = _sign_compress(corrected)
            return jnp.where(in_warmup, m, q)

        def err_leaf(m, e):
            corrected = m + e
            q = _sign_compress(corrected)
            return jnp.where(in_warmup, e, corrected - q)

        mu_eff = jax.tree_util.tree_map(comp_leaf, new_mu, state.error)
        new_err = jax.tree_util.tree_map(err_leaf, new_mu, state.error)
        return mu_eff, new_mu, new_nu, new_err, in_warmup

    return init, moments


def onebit_adam_tx(lr, freeze_step=100, b1=0.9, b2=0.999, eps=1e-8,
                   weight_decay=0.0):
    """OnebitAdam (`onebit/adam.py:14`): Adam in warmup; after `freeze_step` the
    variance freezes and the momentum is sign-compressed with error feedback."""
    init, moments = _onebit_core(freeze_step, b1, b2, eps)

    def update(updates, state, params=None):
        if weight_decay and params is None:
            raise ValueError("onebit_adam with weight_decay requires params")
        mu_eff, new_mu, new_nu, new_err, _ = moments(updates, state)
        count = state.count + 1
        bc1 = 1 - b1 ** count.astype(jnp.float32)
        bc2 = 1 - b2 ** count.astype(jnp.float32)

        def upd_leaf(m, v, p):
            step_val = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay:
                step_val = step_val + weight_decay * p.astype(jnp.float32)
            return step_val

        p_tree = params if params is not None else new_mu
        steps = jax.tree_util.tree_map(upd_leaf, mu_eff, new_nu, p_tree)
        lr_t = lr(state.count) if callable(lr) else lr
        out = jax.tree_util.tree_map(lambda s: (-lr_t * s), steps)
        # stored momentum IS the compressed one post-freeze (all workers agree)
        return out, OnebitAdamState(count=count, mu=mu_eff, nu=new_nu, error=new_err)

    return optax.GradientTransformation(init, update)


class OnebitLambState(NamedTuple):
    base: OnebitAdamState
    scaling: optax.Updates   # per-tensor trust ratios, frozen at freeze_step


def onebit_lamb_tx(lr, freeze_step=100, b1=0.9, b2=0.999, eps=1e-6,
                   weight_decay=0.0, max_coeff=10.0, min_coeff=0.01):
    """OnebitLamb (`onebit/lamb.py:15`): LAMB in warmup (clamped trust ratio per
    tensor); at the freeze boundary the trust ratios ("lamb coefficients") are
    frozen and reused through the compressed phase."""
    init_core, moments = _onebit_core(freeze_step, b1, b2, eps)

    def init(params):
        ones = jax.tree_util.tree_map(
            lambda p: jnp.ones((), jnp.float32), params)
        return OnebitLambState(base=init_core(params), scaling=ones)

    def update(updates, state, params=None):
        assert params is not None, "onebit_lamb needs params for the trust ratio"
        mu_eff, _new_mu, new_nu, new_err, in_warmup = moments(updates, state.base)
        count = state.base.count + 1
        bc1 = 1 - b1 ** count.astype(jnp.float32)
        bc2 = 1 - b2 ** count.astype(jnp.float32)

        def raw_step(m, v, p):
            s = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay:
                s = s + weight_decay * p.astype(jnp.float32)
            return s

        steps = jax.tree_util.tree_map(raw_step, mu_eff, new_nu, params)

        def trust(p, s, frozen):
            w_norm = jnp.linalg.norm(p.astype(jnp.float32).ravel())
            s_norm = jnp.linalg.norm(s.ravel())
            ratio = jnp.where(s_norm > 0, w_norm / (s_norm + 1e-12), 1.0)
            ratio = jnp.clip(ratio, min_coeff, max_coeff)
            # freeze the coefficient after warmup (reference lamb_coeff_freeze)
            return jnp.where(in_warmup, ratio, frozen)

        new_scaling = jax.tree_util.tree_map(trust, params, steps, state.scaling)
        lr_t = lr(state.base.count) if callable(lr) else lr
        out = jax.tree_util.tree_map(lambda s, c: -lr_t * c * s, steps, new_scaling)
        return out, OnebitLambState(
            base=OnebitAdamState(count=count, mu=mu_eff, nu=new_nu, error=new_err),
            scaling=new_scaling)

    return optax.GradientTransformation(init, update)


def zero_one_adam_tx(lr, var_freeze_step=100, var_update_scaler=16,
                     local_step_clipper=16,
                     b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0):
    """ZeroOneAdam / 0/1 Adam (`onebit/zoadam.py:14`): momentum communication is
    compressed from step 0 (the "0 warmup" the name refers to); the variance is
    updated only at exponentially-spaced "variance update" steps before
    `var_freeze_step` and frozen afterwards, the interval growth capped at
    `local_step_clipper` doublings. The reference's `local_step` policy
    additionally skips whole synchronizations; in compiled SPMD every step is
    synchronized, so that knob has no TPU equivalent and is not accepted here
    (the config-facing constructor tolerates it for config compatibility)."""

    def nu_mask(count):
        # reference doubles the interval every var_update_scaler updates,
        # clipped at local_step_clipper doublings
        interval = jnp.maximum(
            1, 2 ** jnp.minimum(count // var_update_scaler, local_step_clipper))
        at_boundary = (count % interval) == 0
        return jnp.logical_and(count < var_freeze_step, at_boundary)

    init, moments = _onebit_core(var_freeze_step, b1, b2, eps,
                                 nu_update_mask_fn=nu_mask, compress_from=0)

    def update(updates, state, params=None):
        if weight_decay and params is None:
            raise ValueError("zero_one_adam with weight_decay requires params")
        mu_eff, new_mu, new_nu, new_err, _ = moments(updates, state)
        count = state.count + 1
        bc1 = 1 - b1 ** count.astype(jnp.float32)
        bc2 = 1 - b2 ** count.astype(jnp.float32)

        def upd_leaf(m, v, p):
            s = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay:
                s = s + weight_decay * p.astype(jnp.float32)
            return s

        p_tree = params if params is not None else new_mu
        steps = jax.tree_util.tree_map(upd_leaf, mu_eff, new_nu, p_tree)
        lr_t = lr(state.count) if callable(lr) else lr
        out = jax.tree_util.tree_map(lambda s: -lr_t * s, steps)
        return out, OnebitAdamState(count=count, mu=mu_eff, nu=new_nu, error=new_err)

    return optax.GradientTransformation(init, update)


# ---- config-facing constructors (ops/optim.py registry) ----------------------

def onebit_adam(lr, params_dict):
    betas = params_dict.get("betas", (0.9, 0.999))
    freeze = params_dict.get("freeze_step", params_dict.get("warmup_steps", 100))
    return onebit_adam_tx(lr, freeze_step=freeze, b1=betas[0], b2=betas[1],
                          eps=params_dict.get("eps", 1e-8),
                          weight_decay=params_dict.get("weight_decay", 0.0))


def onebit_lamb(lr, params_dict):
    betas = params_dict.get("betas", (0.9, 0.999))
    freeze = params_dict.get("freeze_step", 100)
    return onebit_lamb_tx(lr, freeze_step=freeze, b1=betas[0], b2=betas[1],
                          eps=params_dict.get("eps", 1e-6),
                          weight_decay=params_dict.get("weight_decay", 0.0),
                          max_coeff=params_dict.get("max_coeff", 10.0),
                          min_coeff=params_dict.get("min_coeff", 0.01))


def zero_one_adam(lr, params_dict):
    betas = params_dict.get("betas", (0.9, 0.999))
    # local_step_scaler is accepted (reference config surface) but inert: every
    # SPMD step is synchronized, so there is no local-step skipping to schedule.
    return zero_one_adam_tx(
        lr,
        var_freeze_step=params_dict.get("var_freeze_step", 100),
        var_update_scaler=params_dict.get("var_update_scaler", 16),
        local_step_clipper=params_dict.get("local_step_clipper", 16),
        b1=betas[0], b2=betas[1],
        eps=params_dict.get("eps", 1e-8),
        weight_decay=params_dict.get("weight_decay", 0.0))
