"""Random layerwise token dropping (random-LTD).

Reference: `runtime/data_pipeline/data_routing/` (+ `csrc/random_ltd/
token_sort.cu`, `gather_scatter.cu`): middle transformer layers process a random
subset of tokens; the rest bypass the layer; the kept count ramps up by schedule.

TPU formulation: static-shape gather/scatter with a per-step permutation — the
kept count changes only at schedule boundaries (each distinct count is one
compiled program, like the reference's reserved-length buckets).
"""

import jax
import jax.numpy as jnp


class RandomLTDScheduler:
    """Kept-token count ramp (reference `data_routing/scheduler.py:38`)."""

    def __init__(self, total_layers, start_ratio=0.5, end_ratio=1.0,
                 total_steps=10000, ltd_start_layer=1, ltd_end_layer=None,
                 bucket=64):
        self.start_ratio = start_ratio
        self.end_ratio = end_ratio
        self.total_steps = max(total_steps, 1)
        self.start_layer = ltd_start_layer
        self.end_layer = ltd_end_layer if ltd_end_layer is not None else total_layers - 1
        self.bucket = bucket

    def keep_ratio(self, step):
        frac = min(step / self.total_steps, 1.0)
        return self.start_ratio + (self.end_ratio - self.start_ratio) * frac

    def keep_count(self, step, seq_len):
        # reference schema passes ABSOLUTE token counts as
        # random_ltd_schedule.min_value/max_value (scheduler.py:38); values
        # <= 1 are treated as ratios of the live sequence length
        raw = self.keep_ratio(step)
        raw = int(raw if raw > 1 else raw * seq_len)
        bucketed = max((raw // self.bucket) * self.bucket, self.bucket)
        return min(bucketed, seq_len)


def random_ltd_layer(layer_fn, x, keep_count, rng):
    """Apply `layer_fn` to a random `keep_count`-token subset of x [B, T, D];
    dropped tokens pass through unchanged (gather→process→scatter, the role of
    `token_sort.cu`/`gather_scatter.cu`)."""
    B, T, D = x.shape
    if keep_count >= T:
        return layer_fn(x)
    perm = jax.vmap(lambda k: jax.random.permutation(k, T))(
        jax.random.split(rng, B))                       # [B, T]
    keep_idx = jnp.sort(perm[:, :keep_count], axis=1)   # preserve order
    sub = jnp.take_along_axis(x, keep_idx[..., None], axis=1)
    sub_out = layer_fn(sub)
    return x.at[jnp.arange(B)[:, None], keep_idx].set(sub_out)
