"""Difficulty-indexed data sampling.

Reference: `DeepSpeedDataSampler` (`data_pipeline/data_sampling/data_sampler.py:36`)
— curriculum-driven sampler that restricts each step's candidate pool to samples
whose difficulty metric(s) <= the current scheduled difficulty, using the
precomputed metric→sample index built offline by the `DataAnalyzer` map-reduce.

Two construction paths:
  * direct: `difficulties` = one array aligned with the dataset (single
    metric) or {metric_name: array} (multi-metric — the pool is the
    INTERSECTION of per-metric pools, each with its own schedule, matching
    the reference's per-metric CurriculumScheduler dict);
  * `from_config`: the reference `curriculum_learning` JSON block with
    `curriculum_metrics: {name: {index_to_metric_path | sample_to_metric_path,
    difficulty_type: value|percentile, ...schedule...}}` — index files are the
    analyzer's `sample_to_metric.npy` outputs.
"""

import os

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum import CurriculumScheduler


def _resolve_metric_path(path, name):
    """Accept the analyzer's save dir, the metric dir, or the .npy itself."""
    if os.path.isdir(path):
        for cand in (os.path.join(path, "sample_to_metric.npy"),
                     os.path.join(path, name, "sample_to_metric.npy")):
            if os.path.exists(cand):
                return cand
    return path


class DeepSpeedDataSampler:
    def __init__(self, dataset_len, batch_size, difficulties=None,
                 curriculum_config=None, seed=0, drop_last=True,
                 difficulty_types=None):
        self.dataset_len = dataset_len
        self.batch_size = batch_size
        self.seed = seed
        self.global_step = 0
        # normalize to {name: array} / {name: scheduler} / {name: type}
        self.metrics = {}
        if difficulties is not None and not isinstance(difficulties, dict):
            difficulties = {"difficulty": np.asarray(difficulties)}
        metric_cfgs = {}
        if curriculum_config:
            if "curriculum_metrics" in curriculum_config:
                metric_cfgs = curriculum_config["curriculum_metrics"]
                if difficulties and set(difficulties) == {"difficulty"} \
                        and "difficulty" not in metric_cfgs:
                    # a bare array paired with a single named metric config:
                    # key the array to that metric rather than silently
                    # attaching no scheduler at all
                    assert len(metric_cfgs) == 1, (
                        "a bare difficulties array cannot pair with multiple "
                        "curriculum_metrics — pass {name: array} instead")
                    difficulties = {next(iter(metric_cfgs)):
                                    difficulties["difficulty"]}
            elif difficulties:
                metric_cfgs = {n: curriculum_config for n in difficulties}
        if difficulties:
            types = difficulty_types or {}
            for name, vals in difficulties.items():
                mc = metric_cfgs.get(name)
                arr = np.asarray(vals)
                mtype = types.get(name) or (mc or {}).get(
                    "difficulty_type", "value")
                self.metrics[name] = {
                    "values": arr,
                    # percentile thresholds read a once-sorted copy
                    # (np.percentile would re-sort per batch); value-type
                    # metrics never touch it, so don't pay the memory
                    "sorted": np.sort(arr) if mtype == "percentile" else None,
                    "scheduler": CurriculumScheduler(mc) if mc else None,
                    "type": mtype,
                }

    @property
    def scheduler(self):
        """Single-metric convenience (legacy callers): THE scheduler, or None."""
        scheds = [m["scheduler"] for m in self.metrics.values()
                  if m["scheduler"] is not None]
        return scheds[0] if len(scheds) == 1 else None

    @property
    def difficulties(self):
        """Single-metric convenience: THE difficulty array, or None."""
        if len(self.metrics) == 1:
            return next(iter(self.metrics.values()))["values"]
        return None

    @classmethod
    def from_config(cls, dataset_len, batch_size, curriculum_learning, seed=0):
        """Build from the reference `curriculum_learning` block, loading each
        metric's merged analyzer index (sample_to_metric.npy)."""
        metrics_cfg = curriculum_learning.get("curriculum_metrics") or {}
        assert metrics_cfg, ("curriculum_learning.curriculum_metrics is empty "
                             "— run the DataAnalyzer and point each metric at "
                             "its index (index_to_metric_path)")
        difficulties = {}
        for name, m in metrics_cfg.items():
            path = (m.get("index_to_metric_path")
                    or m.get("sample_to_metric_path") or m.get("index_path"))
            assert path, (f"curriculum metric {name!r} needs "
                          "index_to_metric_path (the DataAnalyzer output)")
            vals = np.load(_resolve_metric_path(path, name))
            assert len(vals) == dataset_len, (
                f"metric {name!r} index covers {len(vals)} samples but the "
                f"dataset has {dataset_len} — rebuild the analyzer index")
            difficulties[name] = vals
        return cls(dataset_len, batch_size, difficulties=difficulties,
                   curriculum_config=curriculum_learning, seed=seed)

    # -- scheduling ------------------------------------------------------

    def set_step(self, global_step):
        self.global_step = global_step
        for m in self.metrics.values():
            if m["scheduler"] is not None:
                m["scheduler"].update_difficulty(global_step)

    def _metric_pool(self, m):
        vals, sched = m["values"], m["scheduler"]
        if sched is None:
            return None
        limit = sched.current_difficulty
        if m["type"] == "percentile":
            # scheduled difficulty is a percentile in [0, 100]; index the
            # pre-sorted copy instead of re-sorting per batch
            q = np.clip(limit, 0, 100) / 100.0
            s = m["sorted"]
            limit = s[min(int(q * (len(s) - 1) + 0.5), len(s) - 1)]
        return vals <= limit

    def candidate_pool(self):
        mask = None
        for m in self.metrics.values():
            mm = self._metric_pool(m)
            if mm is None:
                continue
            mask = mm if mask is None else (mask & mm)
        if mask is None:
            return np.arange(self.dataset_len)
        pool = np.nonzero(mask)[0]
        if len(pool) < self.batch_size:          # never starve the batch
            # fall back to the easiest samples by the (first) metric sum
            total = sum(m["values"].astype(np.float64)
                        for m in self.metrics.values())
            pool = np.argsort(total)[:self.batch_size]
        return pool

    def next_indices(self):
        for m in self.metrics.values():
            if m["scheduler"] is not None:
                m["scheduler"].update_difficulty(self.global_step)
        pool = self.candidate_pool()
        # stateless draw keyed on (seed, global_step): checkpoint resume at step N
        # continues the exact uninterrupted sequence
        rng = np.random.default_rng((self.seed, self.global_step))
        idx = rng.choice(pool, size=self.batch_size,
                         replace=len(pool) < self.batch_size)
        self.global_step += 1
        return idx

    def __iter__(self):
        while True:
            yield self.next_indices()

    def state_dict(self):
        return {"global_step": self.global_step, "seed": self.seed}

    def load_state_dict(self, sd):
        self.global_step = sd["global_step"]
        self.set_step(self.global_step)
