"""Difficulty-indexed data sampling.

Reference: `DeepSpeedDataSampler` (`data_pipeline/data_sampling/data_sampler.py:36`)
— curriculum-driven sampler that restricts each epoch's candidate pool to samples
whose difficulty metric <= current difficulty, using a precomputed
metric→sample index (the offline `DataAnalyzer` map-reduce).

Here: `difficulties` is an array aligned with the dataset (the analyzer output);
sampling masks the pool per step and draws global batches deterministically.
"""

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum import CurriculumScheduler


class DeepSpeedDataSampler:
    def __init__(self, dataset_len, batch_size, difficulties=None,
                 curriculum_config=None, seed=0, drop_last=True):
        self.dataset_len = dataset_len
        self.batch_size = batch_size
        self.difficulties = (np.asarray(difficulties) if difficulties is not None
                             else None)
        self.scheduler = (CurriculumScheduler(curriculum_config)
                          if curriculum_config else None)
        self.seed = seed
        self.global_step = 0

    def set_step(self, global_step):
        self.global_step = global_step
        if self.scheduler is not None:
            self.scheduler.update_difficulty(global_step)

    def candidate_pool(self):
        if self.scheduler is None or self.difficulties is None:
            return np.arange(self.dataset_len)
        limit = self.scheduler.current_difficulty
        pool = np.nonzero(self.difficulties <= limit)[0]
        if len(pool) < self.batch_size:          # never starve the batch
            order = np.argsort(self.difficulties)
            pool = order[:self.batch_size]
        return pool

    def next_indices(self):
        pool = self.candidate_pool()
        # stateless draw keyed on (seed, global_step): checkpoint resume at step N
        # continues the exact uninterrupted sequence
        rng = np.random.default_rng((self.seed, self.global_step))
        idx = rng.choice(pool, size=self.batch_size,
                         replace=len(pool) < self.batch_size)
        self.global_step += 1
        if self.scheduler is not None:
            self.scheduler.update_difficulty(self.global_step)
        return idx

    def __iter__(self):
        while True:
            yield self.next_indices()

    def state_dict(self):
        return {"global_step": self.global_step, "seed": self.seed}

    def load_state_dict(self, sd):
        self.global_step = sd["global_step"]
        self.set_step(self.global_step)
