"""Offline data analysis (map-reduce metric indexing).

Reference: `DataAnalyzer` (`deepspeed/runtime/data_pipeline/data_sampling/
data_analyzer.py`) — each worker walks a shard of the dataset computing
per-sample metrics (e.g. sequence length, vocab rarity), writes index files,
and a reduce step merges them into (a) `sample_to_metric`: metric value per
sample, aligned with the dataset, and (b) `metric_to_sample`: value → sample
ids. Curriculum learning (`DeepSpeedDataSampler`) consumes the merged output
as its `difficulties` array.

Storage is plain .npy per worker + a merged .npy / .json — the reference's
indexed-dataset binary format is a torch-ecosystem artifact, not a capability.
"""

import json
import os
from typing import Callable, Dict, Sequence

import numpy as np

SINGLE_VALUE = "single_value_per_sample"   # one number per sample (indexable)
ACCUMULATE = "accumulate_value"            # running reduction over samples


class DataAnalyzer:
    """Map-reduce per-sample metric computation over dataset shards.

    `metric_functions[name](sample) -> scalar` (SINGLE_VALUE) or
    `-> np.ndarray` contribution (ACCUMULATE, summed). `worker_id` /
    `num_workers` shard the dataset by contiguous ranges, mirroring the
    reference's batch-start/end split.
    """

    def __init__(self, dataset, metric_names: Sequence[str],
                 metric_functions: Dict[str, Callable],
                 metric_types: Dict[str, str] = None,
                 num_workers: int = 1, worker_id: int = 0,
                 save_path: str = "./data_analysis"):
        self.dataset = dataset
        self.metric_names = list(metric_names)
        self.metric_functions = metric_functions
        self.metric_types = metric_types or {n: SINGLE_VALUE for n in metric_names}
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.save_path = save_path

    # -- map ------------------------------------------------------------

    def _shard_range(self):
        n = len(self.dataset)
        per = (n + self.num_workers - 1) // self.num_workers
        start = self.worker_id * per
        return start, min(start + per, n)

    def _worker_file(self, metric, worker_id):
        return os.path.join(self.save_path, metric,
                            f"worker{worker_id}_of_{self.num_workers}.npz")

    def run_map(self):
        """Compute this worker's shard and persist per-metric partial results."""
        start, end = self._shard_range()
        results = {}
        for name in self.metric_names:
            fn = self.metric_functions[name]
            if self.metric_types[name] == SINGLE_VALUE:
                ids = np.arange(start, end, dtype=np.int64)
                vals = np.asarray([fn(self.dataset[i]) for i in range(start, end)])
                results[name] = ("single", ids, vals)
            else:
                acc = None
                for i in range(start, end):
                    contrib = np.asarray(fn(self.dataset[i]))
                    acc = contrib if acc is None else acc + contrib
                results[name] = ("accum", np.zeros(0, np.int64),
                                 acc if acc is not None else np.zeros(0))
        for name, (kind, ids, vals) in results.items():
            path = self._worker_file(name, self.worker_id)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            np.savez(path, kind=kind, ids=ids, values=vals)
        return results

    # -- reduce ----------------------------------------------------------

    def run_reduce(self):
        """Merge all workers' partials into the final per-metric index:
        `<save_path>/<metric>/sample_to_metric.npy` (SINGLE_VALUE, aligned
        with the dataset), `metric_to_sample.json` (value → sample ids), or
        `accumulated.npy` (ACCUMULATE)."""
        for name in self.metric_names:
            kinds, ids_all, vals_all = [], [], []
            for w in range(self.num_workers):
                with np.load(self._worker_file(name, w), allow_pickle=False) as z:
                    kinds.append(str(z["kind"]))
                    ids_all.append(z["ids"])
                    vals_all.append(z["values"])
            mdir = os.path.join(self.save_path, name)
            if kinds[0] == "single":
                ids = np.concatenate(ids_all)
                vals = np.concatenate(vals_all)
                order = np.argsort(ids)
                sample_to_metric = vals[order]
                np.save(os.path.join(mdir, "sample_to_metric.npy"), sample_to_metric)
                index = {}
                for sid, val in zip(ids[order].tolist(), sample_to_metric.tolist()):
                    index.setdefault(str(val), []).append(sid)
                with open(os.path.join(mdir, "metric_to_sample.json"), "w") as f:
                    json.dump(index, f)
            else:
                total = None
                for v in vals_all:
                    if v.size == 0:  # empty shard (more workers than samples)
                        continue
                    total = v if total is None else total + v
                np.save(os.path.join(mdir, "accumulated.npy"),
                        total if total is not None else np.zeros(0))

    def run(self):
        """Single-process convenience: map all shards then reduce."""
        orig = self.worker_id
        try:
            for w in range(self.num_workers):
                self.worker_id = w
                self.run_map()
        finally:
            self.worker_id = orig
        self.run_reduce()


def load_sample_to_metric(save_path, metric_name):
    """The merged difficulty array for `DeepSpeedDataSampler(difficulties=...)`."""
    return np.load(os.path.join(save_path, metric_name, "sample_to_metric.npy"))


def load_metric_to_sample(save_path, metric_name):
    with open(os.path.join(save_path, metric_name, "metric_to_sample.json")) as f:
        raw = json.load(f)
    return {float(k): v for k, v in raw.items()}


def load_accumulated(save_path, metric_name):
    return np.load(os.path.join(save_path, metric_name, "accumulated.npy"))
