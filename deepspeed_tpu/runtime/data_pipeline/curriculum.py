"""Curriculum learning.

Reference: `runtime/data_pipeline/data_sampling/` + the legacy seqlen truncation
path (`runtime/engine.py:1792-1795`): difficulty (e.g. sequence length) ramps
from `min_difficulty` to `max_difficulty` by a schedule of the global step.

TPU note: changing sequence length per step would retrigger XLA compilation.
`apply_seqlen_curriculum` therefore keeps the batch shape STATIC and masks
tokens beyond the current difficulty (labels -> ignore index) — same learning
signal, one compiled program. Bucketed true-truncation (a few fixed shapes) is
available via `bucketize=`.
"""

import numpy as np

from deepspeed_tpu.utils.logging import logger

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"


class CurriculumScheduler:
    """Reference `CurriculumScheduler` (data_pipeline/curriculum_scheduler.py):
    difficulty(step) by fixed_linear / fixed_root / fixed_discrete schedules."""

    def __init__(self, config):
        self.schedule_type = config.get("curriculum_type", config.get("schedule_type",
                                                                      FIXED_LINEAR))
        self.min_difficulty = config.get("min_difficulty", 8)
        self.max_difficulty = config.get("max_difficulty", 1024)
        cfg = config.get("schedule_config", config)
        self.total_step = cfg.get("total_curriculum_step", cfg.get("total_step", 10000))
        self.difficulty_step = cfg.get("difficulty_step", 8)
        self.root_degree = cfg.get("root_degree", 2)
        self.difficulties = cfg.get("difficulty", [])
        self.max_steps = cfg.get("max_step", [])
        self.current_difficulty = self.min_difficulty

    def update_difficulty(self, global_steps):
        t = self.schedule_type
        if t == FIXED_LINEAR:
            frac = min(global_steps / max(self.total_step, 1), 1.0)
        elif t == FIXED_ROOT:
            frac = min((global_steps / max(self.total_step, 1))**(1.0 / self.root_degree), 1.0)
        elif t == FIXED_DISCRETE:
            d = self.min_difficulty
            for diff, until in zip(self.difficulties, self.max_steps):
                if global_steps >= until:
                    d = diff
            self.current_difficulty = d
            return d
        else:
            frac = 1.0
        raw = self.min_difficulty + (self.max_difficulty - self.min_difficulty) * frac
        stepped = int(raw // self.difficulty_step * self.difficulty_step)
        self.current_difficulty = max(stepped, self.min_difficulty)
        return self.current_difficulty

    def get_difficulty(self, global_steps=None):
        if global_steps is not None:
            self.update_difficulty(global_steps)
        return self.current_difficulty


def apply_seqlen_curriculum(batch, difficulty, ignore_index=-1, bucketize=None):
    """Mask labels past `difficulty` tokens (static-shape curriculum)."""
    out = dict(batch)
    tokens = out.get("tokens", out.get("input_ids"))
    if tokens is None:
        return out
    T = tokens.shape[1]
    if bucketize:
        difficulty = min((b for b in bucketize if b >= difficulty), default=T)
        out_tokens = np.asarray(tokens)[:, :difficulty]
        for k in ("tokens", "input_ids", "labels", "attention_mask"):
            if k in out:
                out[k] = np.asarray(out[k])[:, :difficulty]
        return out
    labels = out.get("labels")
    if labels is None:
        # causal LM: ALWAYS derive shifted labels (stable batch contract across
        # the whole ramp — at full difficulty the mask is simply all-keep, so
        # the loss_fn's shapes and keys never change mid-training)
        tokens_np = np.asarray(tokens)
        inputs = tokens_np[:, :-1]
        labels = tokens_np[:, 1:].astype(np.int32).copy()
        if difficulty < T:
            labels[:, max(difficulty - 1, 0):] = ignore_index
        out["tokens"] = inputs
        if "input_ids" in out:  # keep the alternative key consistent with labels
            out["input_ids"] = inputs
        out["labels"] = labels
    elif difficulty < T:
        labels = np.asarray(labels).astype(np.int32).copy()
        labels[:, difficulty:] = ignore_index
        out["labels"] = labels
    return out
