from deepspeed_tpu.runtime.data_pipeline.curriculum import (
    CurriculumScheduler,
    apply_seqlen_curriculum,
)
from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
    DataAnalyzer,
    load_accumulated,
    load_metric_to_sample,
    load_sample_to_metric,
)
from deepspeed_tpu.runtime.data_pipeline.data_sampler import DeepSpeedDataSampler
from deepspeed_tpu.runtime.data_pipeline.random_ltd import (
    RandomLTDScheduler,
    random_ltd_layer,
)
