"""Hybrid engine — one model flipping between training and fast generation (RLHF).

Reference: `runtime/hybrid_engine.py:32` (`DeepSpeedHybridEngine`): inside an
RLHF step the actor both generates rollouts (inference-optimized: gathered
params, injected kernels, KV cache) and trains (ZeRO-3 partitioned). The
reference juggles this with param gather/release and module swapping.

TPU-native: params are global sharded arrays, so "flipping" is free — the decode
program simply reads the CURRENT training params (XLA re-gathers per program as
its sharding demands); no cache retake machinery needed. LoRA-based RLHF uses
`runtime/lora.py` (apply/fuse/unfuse — the reference's LoRA lifecycle as pure
functions). `HybridEngine` = training Engine + a decode path compiled against
the live params, with the reference's `generate()` surface.
"""

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# one sampling rule across the framework (hoisted: this used to be a local
# import inside _build_generate — the serving scheduler, the spill engine
# and this rollout all share the exact same sampler)
from deepspeed_tpu.inference.engine import sample_logits
from deepspeed_tpu.runtime.engine import Engine, ModelSpec
from deepspeed_tpu.utils.logging import logger, log_dist
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer


class HybridEngine(Engine):
    """Engine + generate(). Construct via `initialize(..., hybrid_engine=...)` or
    directly with a DecodeModelSpec for the generation path."""

    def __init__(self, model: ModelSpec, config, decode_spec=None, **kw):
        super().__init__(model, config, **kw)
        self._decode_spec = decode_spec
        self._generate_fn = None
        self._gen_timer = SynchronizedWallClockTimer()
        self.latency = 0.0
        self.generate_count = 0

    def set_decode_spec(self, decode_spec):
        self._decode_spec = decode_spec
        self._generate_fn = None

    def as_draft_spec(self):
        """This engine's decode spec bound to the CURRENT training params —
        the reusable draft-model path: the RLHF actor (or any model this
        engine trains) can draft for a bigger serving target via
        ``target.serving(draft_spec=hybrid.as_draft_spec(),
        spec_decode={"drafter": "model"})``, and conversely a small frozen
        copy of the actor speeds up the rollout itself when rollouts run
        through a ServingEngine. Params are live sharded arrays, so
        "binding" is a dataclass field swap — no gather, no copy."""
        assert self._decode_spec is not None, \
            "HybridEngine needs a DecodeModelSpec (set_decode_spec)"
        return dataclasses.replace(self._decode_spec,
                                   params=self.state.params)

    def _build_generate(self, max_new, greedy, temperature, top_k, top_p):
        spec = self._decode_spec
        assert spec is not None, "HybridEngine needs a DecodeModelSpec (set_decode_spec)"
        # one sampling rule across the framework: the inference engines'
        # sample_logits (module-level import) — the RLHF rollout path must
        # not grow a second, weaker sampler (reference `hybrid_engine.py:174`
        # generates through its inference module)
        def sample(logits, rng):
            return sample_logits(logits, None if greedy else rng, greedy=greedy,
                                 temperature=temperature, top_k=top_k,
                                 top_p=top_p)

        def generate(params, tokens, cache, prompt_len, rng):
            logits, cache = spec.prefill_fn(params, tokens, cache, None)
            last = jnp.take_along_axis(logits, (prompt_len - 1)[:, None, None],
                                       axis=1)[:, 0, :]
            first = sample(last, rng)

            def body(carry, _):
                tok, pos, cache, rng = carry
                rng, sub = jax.random.split(rng)
                lg, cache = spec.decode_fn(params, tok, pos, cache)
                nxt = sample(lg, sub)
                return (nxt, pos + 1, cache, rng), tok

            (_, _, cache, _), toks = jax.lax.scan(
                body, (first, prompt_len, cache, rng), None, length=max_new)
            return jnp.moveaxis(toks, 0, 1)

        return jax.jit(generate)

    def generate(self, tokens, max_new_tokens=32, greedy=True, temperature=1.0,
                 top_k=0, top_p=1.0, rng=None):
        """Rollout with the CURRENT training params (reference `generate` :174)."""
        key = (max_new_tokens, greedy, float(temperature), int(top_k),
               float(top_p))
        if self._generate_fn is None or getattr(self, "_gen_key", None) != key:
            self._generate_fn = self._build_generate(max_new_tokens, greedy,
                                                     temperature, top_k, top_p)
            self._gen_key = key
        tokens = jnp.asarray(tokens)
        B, T = tokens.shape
        cache = self._decode_spec.init_cache(B, T + max_new_tokens,
                                             self.compute_dtype)
        prompt_len = jnp.full((B,), T, jnp.int32)
        if rng is None:
            # independent draws per call and per training step
            rng = jax.random.fold_in(
                jax.random.fold_in(self.state.rng, int(self.state.step)),
                self.generate_count)
        self._gen_timer("generate").start()
        out = self._generate_fn(self.state.params, tokens, cache, prompt_len, rng)
        # dstpu: ignore[DT001]: rollout API boundary — RLHF consumers take host tokens, one transfer per generate()
        out = np.asarray(jax.device_get(out))
        self._gen_timer("generate").stop()
        self.generate_count += 1
        self.latency = self._gen_timer("generate").elapsed(reset=True)
        return out


def make_gpt_hybrid_engine(cfg, ds_config, name="gpt-hybrid", seed=0, mesh=None):
    """Convenience: GPT model wired for RLHF-style train+generate."""
    from deepspeed_tpu.models.gpt import make_gpt_model, make_gpt_decode_model
    model = make_gpt_model(cfg=cfg, name=name, seed=seed)
    engine = HybridEngine(model, ds_config, mesh=mesh)
    decode = make_gpt_decode_model(cfg=cfg, name=name, params=model.params)
    engine.set_decode_spec(decode)
    return engine
