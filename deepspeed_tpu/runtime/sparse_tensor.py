"""Sparse gradients — TPU-native analog of the reference's sparse embedding path.

Reference: `runtime/sparse_tensor.py:1` (`SparseTensor` wrapping torch sparse
COO) and the engine's sparse allreduce (`runtime/engine.py:2427`
`sparse_allreduce_no_retain`): embedding gradients travel over the DP group as
(indices, values) pairs instead of dense [V, D] buffers.

TPU formulation: a `SparseTensor` here is a static-shape pytree — `indices`
[N] int32 row ids, `values` [N, D] rows, `dense_shape` static — where N is the
number of touched rows (≈ tokens in the batch), fixed at trace time so the
whole thing jits. Duplicate indices are legal and carry sum semantics
(`to_dense` scatter-adds). The collective is an all-gather of indices+values
over the mesh data axes: wire cost dp·N·(D+1) elements vs the dense V·D psum —
a win whenever tokens-per-step · dp ≪ vocab (the same regime where the
reference's sparse path wins).
"""

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.comm import mesh as mesh_mod


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SparseTensor:
    """Row-sparse tensor: rows `indices` of a dense [V, ...] array, summed on
    materialization (reference `runtime/sparse_tensor.py` SparseTensor)."""
    indices: jnp.ndarray                               # [N] int32
    values: jnp.ndarray                                # [N, ...]
    dense_shape: Tuple[int, ...] = dataclasses.field(metadata=dict(static=True),
                                                     default=())

    @classmethod
    def from_dense_rows(cls, dense, indices):
        """Compress a dense gradient to the rows listed in `indices` (e.g. the
        batch's token ids). Rows not listed are dropped — for an embedding
        gradient they are exactly zero. A dense gradient row already sums all
        occurrences of its id, so repeated ids must contribute once: duplicates
        keep their slot (static shape) but carry zero values."""
        indices = jnp.asarray(indices, jnp.int32).reshape(-1)
        n = indices.shape[0]
        order = jnp.argsort(indices)
        sorted_idx = indices[order]
        first_sorted = jnp.concatenate([jnp.ones((1,), bool),
                                        sorted_idx[1:] != sorted_idx[:-1]])
        first = jnp.zeros((n,), bool).at[order].set(first_sorted)
        bshape = (n,) + (1,) * (dense.ndim - 1)
        values = jnp.take(dense, indices, axis=0) * first.reshape(bshape).astype(dense.dtype)
        return cls(indices=indices, values=values,
                   dense_shape=tuple(dense.shape))

    def to_dense(self):
        base = jnp.zeros(self.dense_shape, self.values.dtype)
        return base.at[self.indices].add(self.values)

    def dedup(self):
        """Merge duplicate indices (segment-sum over sorted rows). Keeps shape
        [N]; vacated slots point at row 0 with zero values."""
        order = jnp.argsort(self.indices)
        idx = self.indices[order]
        vals = self.values[order]
        first = jnp.concatenate([jnp.ones((1,), bool), idx[1:] != idx[:-1]])
        seg = jnp.cumsum(first) - 1                     # [N] segment id
        n = self.indices.shape[0]
        summed = jnp.zeros_like(vals).at[seg].add(vals)
        uniq = jnp.zeros((n,), self.indices.dtype).at[seg].set(idx)
        keep = jnp.arange(n) < seg[-1] + 1
        kshape = (n,) + (1,) * (vals.ndim - 1)
        return SparseTensor(indices=jnp.where(keep, uniq, 0),
                            values=summed * keep.reshape(kshape).astype(summed.dtype),
                            dense_shape=self.dense_shape)

    @property
    def nnz_rows(self):
        return self.indices.shape[0]


def _gather_axes(axis):
    if axis is None:
        return mesh_mod.BATCH_AXES
    if isinstance(axis, str):
        return (axis,)
    return tuple(axis)


def sparse_all_reduce(st: SparseTensor, axis=None) -> SparseTensor:
    """Sum a SparseTensor across the mesh data axes without densifying.

    Implemented as an all-gather of (indices, values) — concatenated rows with
    duplicate indices still sum on `to_dense()`. Eager (like `comm.all_reduce`);
    inside `shard_map` call `jax.lax.all_gather` directly.
    """
    from deepspeed_tpu.comm.comm import all_gather
    axes = _gather_axes(axis)
    if mesh_mod.axis_size(axes) == 1:
        return st
    # comm.all_gather caches the compiled shard_map per (mesh, axes) — two
    # cached collectives instead of a per-call retrace
    gi = all_gather(st.indices, axis=axes)
    gv = all_gather(st.values, axis=axes)
    return SparseTensor(indices=gi, values=gv, dense_shape=st.dense_shape)


def sparse_embedding_grad(loss_fn, params, batch, ids, embedding_key):
    """Gradient of `loss_fn(params, batch)` with the embedding leaf at
    `embedding_key` returned as a SparseTensor over the batch's token `ids`
    (all other leaves dense). The dense [V, D] cotangent is formed locally by
    XLA's scatter-add but never shipped: callers `sparse_all_reduce` the
    compressed rows instead (the reference's engine does the same exchange in
    `sparse_allreduce_no_retain`)."""
    grads = jax.grad(loss_fn)(params, batch)
    emb_grad = grads[embedding_key]
    grads[embedding_key] = SparseTensor.from_dense_rows(emb_grad, ids)
    return grads
