"""Host-ward half of the async offload staging pipeline.

The device-ward half lives in `runtime/param_swap.py` (`LayerStreamer`
staging host layers into HBM ahead of compute). This module carries the
opposite direction: gradients (and any other device-resident tree) leaving
for the host optimizer of the ZeRO-Offload/Infinity tier
(`runtime/cpu_optimizer.py`, `runtime/infinity.py`).

`HostwardPipe` turns the blocking per-layer `jax.device_get` of the old
path into dispatch + deferred landing: `submit()` fires
`copy_to_host_async()` on every leaf the moment the producing program is
enqueued — the D2H copy then overlaps the NEXT layer's backward — and the
consumer collects landed entries a configurable depth behind. The step
only blocks on a transfer that is genuinely late, and that block is
measured (`offload/hostward_wait_ms`), not assumed away.

Metric names are centralized in `OFFLOAD_METRICS` so docs/profiling.md's
catalog and the tests pin one spelling.
"""

import collections
import time

import jax
import numpy as np

# the offload tier's metric vocabulary (docs/profiling.md "Metric catalog";
# docs/offload.md explains the overlap-efficiency math built on them)
OFFLOAD_METRICS = (
    "offload/stage_wait_ms",       # host stall making a layer device-ready
    "offload/hostward_wait_ms",    # host stall landing a device->host tree
    "offload/write_flush_ms",      # NVMe write-back flush barrier
    "offload/staging_occupancy",   # live device-resident staged layers
    "offload/inflight_bytes",      # bytes in async flight (reads + writes)
    "offload/bytes_to_host",       # cumulative device->host traffic
)


def _leaf_bytes(leaves):
    return sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
               for l in leaves if hasattr(l, "shape"))


class HostwardPipe:
    """Bounded async device->host landing queue.

    `submit(key, value)` dispatches `copy_to_host_async()` on every jax
    leaf of `value` (a non-blocking D2H enqueue under JAX's dispatch
    model) and returns the entries that fell out of the depth window —
    each landed as numpy, oldest first. `depth` is how many trees may be
    in flight at once: 1 is classic double buffering (layer i's grads
    land while layer i-1's backward runs), 0 degenerates to the blocking
    path (submit returns its own landing immediately).

    The landing conversion (`np.asarray`) is where a late transfer blocks;
    that wait is measured into `offload/hostward_wait_ms` when a telemetry
    facade is attached.
    """

    def __init__(self, depth=1, telemetry=None, clock=None):
        self.depth = max(0, int(depth))
        self.telemetry = telemetry
        self._clock = clock if clock is not None else time.perf_counter
        self._q = collections.deque()   # (key, leaves, treedef)
        self.bytes_in_flight = 0
        self.bytes_total = 0
        self.landings = 0
        self.wait_ms_total = 0.0

    def __len__(self):
        return len(self._q)

    def submit(self, key, value):
        """Dispatch `value`'s D2H copies and enqueue it; returns the list of
        (key, landed_value) entries popped past the depth window."""
        leaves, treedef = jax.tree_util.tree_flatten(value)
        for l in leaves:
            # non-blocking: enqueues the copy behind the producing program;
            # plain numpy leaves (already host) have no such method
            if hasattr(l, "copy_to_host_async"):
                l.copy_to_host_async()
        self._q.append((key, leaves, treedef))
        self.bytes_in_flight += _leaf_bytes(leaves)
        out = []
        while len(self._q) > self.depth:
            out.append(self._land(*self._q.popleft()))
        return out

    def _land(self, key, leaves, treedef):
        t0 = self._clock()
        nbytes = _leaf_bytes(leaves)
        # the landing point of a transfer dispatched async at submit(); a
        # late transfer blocks HERE and the wait is measured, not hidden
        host = [np.asarray(l) for l in leaves]
        wait_ms = (self._clock() - t0) * 1e3
        self.bytes_in_flight = max(0, self.bytes_in_flight - nbytes)
        self.bytes_total += nbytes
        self.landings += 1
        self.wait_ms_total += wait_ms
        tel = self.telemetry
        if tel is not None and getattr(tel, "enabled", False):
            tel.observe("offload/hostward_wait_ms", wait_ms)
            tel.inc("offload/bytes_to_host", nbytes)
        return key, jax.tree_util.tree_unflatten(treedef, host)

    def drain(self):
        """Land every remaining entry, oldest first."""
        out = []
        while self._q:
            out.append(self._land(*self._q.popleft()))
        return out

    def stats(self):
        return {"landings": self.landings,
                "bytes_total": self.bytes_total,
                "wait_ms_total": round(self.wait_ms_total, 3),
                "in_flight": len(self._q)}
