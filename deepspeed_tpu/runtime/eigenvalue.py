"""Curvature (eigenvalue) estimation — power iteration on the loss Hessian.

Reference: `runtime/eigenvalue.py:1` — per-layer power iteration using repeated
autograd passes, feeding the compression scheduler's quantization period.
TPU-native: the Hessian-vector product is a single `jax.jvp`-of-`jax.grad`
composition inside one jitted loop (`lax.while_loop` with a tolerance), so the
whole estimation compiles to one XLA program instead of N python-side backward
passes.
"""

import functools

import jax
import jax.numpy as jnp


class Eigenvalue:
    """API parity with the reference class (verbose/max_iter/tol/stability)."""

    def __init__(self, verbose=False, max_iter=100, tol=1e-2, stability=1e-6,
                 gas_boundary_resolution=1, layer_name="", layer_num=0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def compute_eigenvalue(self, loss_fn, params, batch, rng=None, seed=0):
        """Dominant eigenvalue of the Hessian of `loss_fn(params, batch)` w.r.t.
        params. Returns (eigenvalue: f32, iterations_run: i32)."""
        return power_iteration_hessian(loss_fn, params, batch,
                                       max_iter=self.max_iter, tol=self.tol,
                                       stability=self.stability, seed=seed)


@functools.partial(jax.jit, static_argnums=(0, 3, 4, 5))
def power_iteration_hessian(loss_fn, params, batch, max_iter=100, tol=1e-2,
                            stability=1e-6, seed=0):
    grad_fn = jax.grad(lambda p: loss_fn(p, batch))

    def hvp(v):
        return jax.jvp(grad_fn, (params,), (v,))[1]

    leaves, treedef = jax.tree_util.tree_flatten(params)
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, len(leaves))
    v0 = treedef.unflatten([jax.random.normal(k, l.shape, l.dtype)
                            for k, l in zip(keys, leaves)])

    def normalize(v):
        n = jnp.sqrt(sum(jnp.vdot(x, x).real for x in jax.tree_util.tree_leaves(v)))
        return jax.tree_util.tree_map(lambda x: x / (n + stability), v)

    def body(carry):
        v, prev_ev, i, _ = carry
        w = hvp(v)
        ev = sum(jnp.vdot(a, b).real for a, b in zip(
            jax.tree_util.tree_leaves(v), jax.tree_util.tree_leaves(w)))
        done = jnp.abs(ev - prev_ev) <= tol * jnp.maximum(jnp.abs(ev), 1e-12)
        return normalize(w), ev.astype(jnp.float32), i + 1, done

    def cond(carry):
        _, _, i, done = carry
        return (~done) & (i < max_iter)

    v0 = normalize(v0)
    _, ev, iters, _ = jax.lax.while_loop(
        cond, body, (v0, jnp.asarray(jnp.inf, jnp.float32),
                     jnp.asarray(0, jnp.int32), jnp.asarray(False)))
    return ev, iters
