"""Training engine.

TPU-native analog of `DeepSpeedEngine` (`runtime/engine.py:175`, 3.5k LoC) and the
top-level `deepspeed.initialize` (`deepspeed/__init__.py:64`). The reference wraps an
eager nn.Module and orchestrates forward/backward/step with hooks; here the entire
step — gradient-accumulation scan, loss scaling, ZeRO collectives, optimizer update,
parameter re-materialization — is ONE compiled XLA program over the global mesh:

    state' , metrics = train_step(state, batch, )     # jit, donated state

ZeRO stages are sharding policies (see runtime/zero.py); fp16/bf16 master-weight
handling mirrors `runtime/fp16/fused_optimizer.py:31` / `runtime/bf16_optimizer.py:30`;
the overflow skip-step is a masked update instead of a host-side branch.

API parity with the reference engine: `train_batch`, `forward`, `backward`, `step`,
`eval_batch`, `save_checkpoint`/`load_checkpoint`, `global_steps`, `get_lr`,
`cur_scale` (loss scale), `set_dataloader` etc.
"""

import dataclasses
import inspect
import time
from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu import comm
from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.config.core import TpuTrainConfig
from deepspeed_tpu.ops.optim import build_optimizer
from deepspeed_tpu.runtime import lr_schedules
from deepspeed_tpu.runtime.dataloader import TpuDataLoader, RepeatingLoader
from deepspeed_tpu.runtime.precision import LossScaler, LossScaleState, masked_update
from deepspeed_tpu.runtime.sentinel import BadStateError, BadStateSentinel
from deepspeed_tpu.runtime.zero import ZeroShardingPolicy
from deepspeed_tpu.telemetry import Telemetry
from deepspeed_tpu.utils.logging import logger, log_dist
from deepspeed_tpu.utils.timer import (SynchronizedWallClockTimer, ThroughputTimer,
                                       FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                                       STEP_GLOBAL_TIMER, TRAIN_BATCH_TIMER)
from deepspeed_tpu.utils.tree import tree_cast, tree_global_norm, tree_num_params


@dataclasses.dataclass
class ModelSpec:
    """What the engine needs from a model.

    `loss_fn(params, batch[, rng]) -> loss` or `(loss, aux)`. The reference takes an
    nn.Module; in functional JAX the (pure) loss function + params pytree is the
    model. `param_specs` optionally carries tensor-parallel PartitionSpecs per leaf
    (the TP planner in parallel/tp.py produces them).
    """
    loss_fn: Callable
    params: Any = None
    param_specs: Any = None
    apply_fn: Optional[Callable] = None   # raw forward (for inference/eval use)
    grad_fn: Optional[Callable] = None    # custom (loss, grads) — e.g. the 1F1B
                                          # pipeline schedule computes grads with
                                          # its own backward pass, not jax.grad
    init_fn: Optional[Callable] = None    # (rng) -> params, used when `params` is
                                          # None: the engine materializes each
                                          # leaf DIRECTLY into its ZeRO/TP shard
                                          # (zero.Init's construction-time
                                          # partitioning, partition_parameters.py:723)
    quantize_scheduler: Any = None        # MoQScheduler from init_compression —
                                          # the engine advances it per step and
                                          # retraces when bit widths change
    compression_steppers: Any = None      # [SnipMomentumPruner/ActQuantGate]:
                                          # .step(engine) -> retrace-needed
    has_aux: bool = False
    arch_cfg: Any = None                  # architecture config (e.g. GPTConfig)
                                          # — lets the flops profiler build a
                                          # per-module tree for the zoo models
    pipeline_info: Any = None             # pipeline schedule facts for
                                          # telemetry: {num_stages,
                                          # num_microbatches, schedule,
                                          # bubble_fraction}
    name: str = "model"


class TrainState(NamedTuple):
    params: Any                  # compute-dtype parameters
    master: Any                  # fp32 master copy (None if params are fp32)
    opt_state: Any
    scaler: LossScaleState
    step: jnp.ndarray            # i32 global step counter
    rng: jnp.ndarray             # PRNG key


def _gather_site(spec, axes):
    """(dim, axes-to-gather-over) for the dim of a stage-3 shard whose spec
    entry names a gather axis. Entries can be composite tuples like
    ('data','zero','sequence') and other dims may carry size-1 'tensor'
    entries BEFORE it — first-non-None picked the wrong dim for the zoo's
    TP-annotated leaves. Gather over exactly the axes in the entry: under
    hpZ, weight leaves are secondary-sharded over 'zero' only while
    axes=('data','zero') — gathering over both would blow the leaf up
    'data'-fold. Gathers in the SPEC ENTRY's axis order (the shard layout
    order); deriving from `axes` would interleave shards wrongly if a
    partitioner ever emitted ('zero','data')."""
    for i, e in enumerate(spec):
        names = e if isinstance(e, tuple) else (e,)
        ax = tuple(a for a in names if a in axes)
        if ax:
            return i, ax
    return None, ()


def _normalize_init_fn(init_fn):
    """init_fn() or init_fn(rng) → uniform fn(rng)."""
    try:
        takes_rng = len(inspect.signature(init_fn).parameters) >= 1
    except (TypeError, ValueError):
        takes_rng = True
    if takes_rng:
        return init_fn
    return lambda rng: init_fn()


def _wrap_loss_fn(loss_fn, has_aux):
    """Normalize to loss_fn(params, batch, rng) -> (loss, aux)."""
    sig_params = None
    try:
        sig_params = list(inspect.signature(loss_fn).parameters)
    except (TypeError, ValueError):
        pass
    takes_rng = sig_params is None or len(sig_params) >= 3

    def wrapped(params, batch, rng):
        out = loss_fn(params, batch, rng) if takes_rng else loss_fn(params, batch)
        if has_aux:
            return out[0], out[1]
        if isinstance(out, tuple):
            return out[0], (out[1] if len(out) > 1 else None)
        return out, None

    return wrapped


class Engine:
    """See module docstring. Constructed via `deepspeed_tpu.initialize()`."""

    def __init__(self,
                 model: ModelSpec,
                 config: "Union[str, dict, TpuTrainConfig]",
                 optimizer=None,
                 lr_scheduler=None,
                 training_data=None,
                 collate_fn=None,
                 mesh=None,
                 dont_change_device=False):
        if not isinstance(config, TpuTrainConfig):
            # accept a dict / JSON path like initialize() does — direct
            # Engine/HybridEngine construction is a public surface
            config = TpuTrainConfig.load(config)
        self.config = config
        self.model_spec = model

        # ---- mesh / distributed (reference: init_distributed + groups, engine.py:1063)
        if mesh is not None:
            mesh_mod.set_mesh(mesh)
        elif not mesh_mod.has_mesh():
            self._factor_zero_subgroup(config)
            comm.init_distributed(mesh_config=config.mesh)
        self.mesh = mesh_mod.get_mesh()
        self.spec = mesh_mod.get_spec()

        # ---- batch triad over the data domain (reference config.py batch arithmetic)
        self.dp_world_size = self.spec.data * self.spec.zero
        (self.train_batch_size_value, self.micro_batch_size,
         self.gradient_accumulation_steps_value) = config.resolve_batch_sizes(self.dp_world_size)

        # ---- precision policy
        self.compute_dtype = config.compute_dtype()
        self.fp16_enabled = config.fp16_enabled
        self.bf16_enabled = config.bf16_enabled
        keep_master = (self.compute_dtype != jnp.float32) and (
            not self.bf16_enabled or config.bf16.master_weights)
        self.keep_master = keep_master

        self.scaler = LossScaler(
            static_scale=(None if config.fp16.dynamic else config.fp16.loss_scale),
            initial_scale_power=config.fp16.initial_scale_power,
            loss_scale_window=config.fp16.loss_scale_window,
            hysteresis=config.fp16.hysteresis,
            consecutive_hysteresis=config.fp16.consecutive_hysteresis,
            min_loss_scale=config.fp16.min_loss_scale,
            enabled=self.fp16_enabled,
        )

        # ---- ZeRO sharding policy
        self.zero_policy = ZeroShardingPolicy(config.zero_optimization, self.mesh)
        self.zero_stage = config.zero_optimization.stage

        # ---- explicit compressed grad-reduce wire (comm facade transforms)
        # "onebit" > "int8" > "none": onebit_gradients implies the explicit
        # path; explicit_grad_reduce + zero_quantized_gradients runs the qgZ
        # int8 wire through the facade; bare explicit_grad_reduce keeps an
        # fp32 wire (useful as the measured baseline arm).
        zcfg = config.zero_optimization
        self._explicit_wire = None
        if getattr(zcfg, "onebit_gradients", False):
            self._explicit_wire = "onebit"
        elif getattr(zcfg, "explicit_grad_reduce", False):
            self._explicit_wire = "int8" if zcfg.zero_quantized_gradients \
                else "none"
        self._comm_err = None            # onebit error-feedback residuals
        self._comm_err_shardings = None

        # ---- LR schedule + optimizer
        self.schedule_fn = None
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is None:
            self.schedule_fn = lr_schedules.build_schedule(config.scheduler)
            if self.schedule_fn is not None:
                self.lr_scheduler = lr_schedules.LRScheduler(self.schedule_fn)
        elif isinstance(lr_scheduler, lr_schedules.LRScheduler):
            self.schedule_fn = lr_scheduler.schedule_fn

        if optimizer is None:
            if config.optimizer is None:
                raise ValueError("No optimizer: pass one to initialize() or set the "
                                 "'optimizer' config block")
            optimizer = build_optimizer(config.optimizer, self.schedule_fn)
        self.optimizer = optimizer  # optax GradientTransformation
        off_cfg = config.zero_optimization.offload_optimizer
        # "cpu": optimizer state in pinned host memory; the compiled step
        #   streams it through HBM — fast, but the fp32 state must FIT through
        #   HBM transiently. Models too big for that (and "nvme", and forced
        #   CPU-optimizer configs) take the ZeRO-Infinity tier: fp32 master +
        #   moments owned by the C++ host optimizer (csrc/cpu_optim), the step
        #   runs on the host while only bit16 params live on device — the
        #   reference ZeRO-Offload's "step on CPU" semantics.
        self.nvme_offload = off_cfg is not None and off_cfg.device == "nvme"
        cpu_off = off_cfg is not None and off_cfg.device == "cpu"
        force_host_step = bool(
            config.zero_force_ds_cpu_optimizer
            or (config.optimizer and
                config.optimizer.type.lower().startswith("deepspeedcpu")))
        if cpu_off and not force_host_step:
            try:
                from deepspeed_tpu.platform import get_accelerator
                hbm = get_accelerator().total_memory()
            except Exception:
                hbm = 0
            if not hbm:  # stats unavailable (e.g. tunneled runtimes): assume v5e
                hbm = 16 * 2**30
            # params bf16 + fp32 master + adam m/v transit HBM in the update —
            # PER DEVICE: ZeRO partitions the state over the data domain
            shards = max(mesh_mod.axis_size(mesh_mod.ZERO_AXES), 1)
            if model.params is not None:
                n_model = tree_num_params(model.params)
            else:  # abstract shapes only — zero.Init path
                n_model = tree_num_params(jax.eval_shape(
                    _normalize_init_fn(model.init_fn),
                    jax.random.PRNGKey(config.seed)))
            est = 14 * n_model // shards
            opt_name = (config.optimizer.type.lower() if config.optimizer else "adam")
            host_kind_known = any(k in opt_name for k in ("adam", "lion", "adagrad"))
            if est > 0.6 * hbm:
                if host_kind_known:
                    log_dist(f"offload_optimizer(cpu): per-device fp32 state "
                             f"(~{est/2**30:.1f}G) cannot stream through "
                             f"{hbm/2**30:.1f}G HBM — using the host (C++) "
                             "optimizer step", ranks=[0])
                    force_host_step = True
                else:
                    logger.warning(
                        f"offload_optimizer(cpu): per-device fp32 state "
                        f"(~{est/2**30:.1f}G) likely exceeds HBM during the "
                        f"streamed update, but optimizer '{opt_name}' has no "
                        "host (C++) implementation — keeping the streamed step "
                        "(may OOM); use adam/lion/adagrad for host offload")
        self.nvme_offload = self.nvme_offload or (cpu_off and force_host_step)
        self.offload_optimizer_states = bool(
            getattr(optimizer, "offload_to_host", False)
            or (cpu_off and not force_host_step))
        self.host_optimizer = None

        # ---- loss fn
        self._loss_fn = _wrap_loss_fn(model.loss_fn, model.has_aux)

        # ---- state init (sharded placement)
        self.state = self._init_state(model.params, model.param_specs)
        n_params = tree_num_params(self.state.params)
        log_dist(f"engine: {model.name} | params={n_params/1e6:.2f}M | "
                 f"dtype={jnp.dtype(self.compute_dtype).name} | zero_stage={self.zero_stage} | "
                 f"mesh={self.spec} | micro_bs={self.micro_batch_size} | "
                 f"gas={self.gradient_accumulation_steps_value} | "
                 f"global_bs={self.train_batch_size_value}", ranks=[0])

        # ---- onebit wire: error-feedback residuals, sharded over the slow
        # axis (one residual copy per slow-tier rank — what compression lost
        # last step feeds back next step; not checkpointed, a cold restart
        # just re-pays one step of compression error)
        if self._explicit_wire == "onebit" and \
                getattr(model, "grad_fn", None) is None:
            if self.offload_optimizer_states or self.nvme_offload:
                raise ValueError(
                    "onebit_gradients is incompatible with offload_optimizer: "
                    "the split/host step cannot thread the error-feedback "
                    "residuals through the fused program")
            _, slow = self.zero_policy.reduce_domain(
                getattr(zcfg, "compressed_comm_axis", None))
            if slow is not None:
                n_slow = self.spec.axis_sizes()[slow]
                self._comm_err_shardings = jax.tree_util.tree_map(
                    lambda p: NamedSharding(self.mesh, P(slow)),
                    self.state.params)
                self._comm_err = jax.tree_util.tree_map(
                    lambda p, s: jax.device_put(
                        np.zeros((n_slow,) + tuple(p.shape), np.float32), s),
                    self.state.params, self._comm_err_shardings)
                opt_type = (config.optimizer.type if config.optimizer
                            else "").lower()
                if opt_type.startswith(("onebit", "zeroone")):
                    log_dist(
                        f"onebit_gradients: error-feedback 1-bit wire active "
                        f"over axis {slow!r}, paired with the "
                        f"{config.optimizer.type} optimizer (its in-optimizer "
                        "compression shapes momentum; this knob shrinks the "
                        "actual grad wire)", ranks=[0])

        # ---- jitted programs
        if self.host_optimizer is not None:
            self._train_step = None
            self._grad_program = self._build_grad_program()
            self._push_params = jax.jit(
                lambda m: tree_cast(m, self.compute_dtype),
                out_shardings=self.param_shardings)
        else:
            self._train_step = self._build_train_step()
        self._eval_step = self._build_eval_step()
        self._grad_step = None        # built lazily for forward/backward/step API
        self._apply_step = None
        self._pending = []            # accumulated micro-batch grads (parity API)

        # ---- dataloader
        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data, collate_fn=collate_fn)

        # ---- bookkeeping / monitoring
        self.global_steps = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(batch_size=self.train_batch_size_value,
                                          steps_per_output=config.steps_per_print)
        self.monitor = self._build_monitor()
        self.losses = None
        self._last_metrics = {}

        # unified telemetry (deepspeed_tpu/telemetry/, `telemetry` config
        # block): step-time histograms, tokens/s + achieved-MFU gauges,
        # device-memory watermarks. Opt-in; the default-disabled object costs
        # one attribute check per step and writes nothing.
        self.telemetry = Telemetry(config.telemetry, subsystem="train",
                                   monitor=self.monitor)
        self._program_flops = None   # per-train_batch flops, measured once
        # comm facade stats mirror into this registry: comm/<op>_bytes,
        # comm/<op>_calls, comm/<op>_ms rows (see comm/collectives.py)
        comm.collectives.stats.bind_telemetry(self.telemetry)
        # pipeline bubble accounting (parallel/pipeline.py bubble_fraction):
        # models built by make_gpt_pipeline_model attach their schedule here
        pinfo = getattr(model, "pipeline_info", None)
        if pinfo:
            self.telemetry.set_gauge("train/pipe_bubble_frac",
                                     float(pinfo.get("bubble_fraction", 0.0)))

        # HBM memory ledger + OOM forensics (telemetry/memscope.py):
        # params/master/optimizer byte attribution as mem/* gauges, a
        # pre-flight ZeRO model-states capacity verdict (the reference
        # estimate_zero* analog, judged against real HBM when known), and
        # a ledger+planner+flight dump on RESOURCE_EXHAUSTED in the step
        # dispatch. Off by default — no object, no gauges, no files.
        self.memscope = None
        if self.telemetry.enabled and getattr(config.telemetry,
                                              "memscope", False):
            from deepspeed_tpu.telemetry.memscope import TrainMemScope
            self.memscope = TrainMemScope(self)
            self.memscope.preflight(
                str(getattr(config.telemetry, "memscope_preflight", "warn")))

        # ---- fault tolerance: bad-state sentinel + rollback bookkeeping
        # (docs/fault_tolerance.md; opt-in via the fault_tolerance block —
        # observing the loss costs a host sync per step)
        self._sentinel = BadStateSentinel(
            config.fault_tolerance,
            # every sentinel trip lands in the training black box (no-op
            # unless telemetry.flight_recorder is on)
            recorder=self.telemetry.flightrec
            if self.telemetry.flightrec.enabled else None)
        self._last_ckpt_dir = None     # newest save/load root = rollback target
        self._ckpt_pending = None      # async-save finalizer (checkpoint/saver.py)
        self._ckpt_pending_error = None
        self.rollbacks = 0

        # flops profiler (lazy)
        self._flops_profiler = None

        # MoQ: progressive quantization schedule + curvature cache
        # (reference engine.py:214-215 eigenvalue/block_eigenvalue)
        self.quantize_scheduler = model.quantize_scheduler
        self.compression_steppers = model.compression_steppers or []
        self.block_eigenvalue = None

        # curriculum learning: legacy seqlen scheduling applied in train_batch
        # (reference `engine.forward` truncation, engine.py:1792-1795; v2 config
        # block data_efficiency.data_sampling.curriculum_learning)
        de = self.config.data_efficiency
        cl = (de.data_sampling or {}).get("curriculum_learning", {}) \
            if de and de.enabled else {}
        self.curriculum_scheduler = None
        if cl.get("enabled") and cl.get("curriculum_metrics") \
                and training_data is None:
            logger.warning(
                "curriculum_learning.curriculum_metrics is configured but no "
                "training_data was passed to initialize(): the metric-driven "
                "sampler only applies to loaders built by engine.deepspeed_io "
                "— batches from a user data_iter will NOT be difficulty-gated")
        if cl.get("enabled") and not cl.get("curriculum_metrics"):
            # legacy in-batch seqlen masking; the v2 metric-driven pipeline
            # (curriculum_metrics) selects SAMPLES in deepspeed_io instead
            from deepspeed_tpu.runtime.data_pipeline.curriculum import CurriculumScheduler
            self.curriculum_scheduler = CurriculumScheduler(cl)

        # progressive layer drop (reference engine.py:234-236 constructs
        # ProgressiveLayerDrop from config and feeds theta every step): the
        # kept-layer INDICES are sampled host-side per step and ride into the
        # jitted step as a [B, n_keep] batch leaf — its shape carries the
        # count, so XLA compiles one program per distinct kept count (<=
        # n_layer of them) and the dropped layers' flops genuinely disappear
        pld_cfg = self.config.progressive_layer_drop
        rl = (de.data_routing or {}).get("random_ltd", {}) if de and de.enabled else {}
        if pld_cfg.enabled or rl.get("enabled"):
            # fail LOUDLY at init if the model cannot consume the routing
            # directives (only the zoo's gpt_loss reads them; a pipeline or
            # custom-loss model would otherwise silently train at full cost
            # while the scheduler ramps)
            which = "progressive_layer_drop" if pld_cfg.enabled else "random_ltd"
            assert getattr(self.model_spec, "arch_cfg", None) is not None, (
                f"{which}: this model does not expose ModelSpec.arch_cfg, so "
                "the routing directives would be silently ignored — only the "
                "GPT zoo's loss path (models/gpt.gpt_loss) consumes them")
            assert getattr(self.model_spec, "grad_fn", None) is None, (
                f"{which}: models with a custom grad_fn (pipeline 1F1B) do "
                "not consume routing directives yet")
        self.progressive_layer_drop = None
        if pld_cfg.enabled:
            from deepspeed_tpu.runtime.progressive_layer_drop import \
                ProgressiveLayerDrop
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=pld_cfg.theta, gamma=pld_cfg.gamma)
            self._pld_rng = np.random.default_rng(self.config.seed ^ 0x9E3779B9)

        # random-LTD (reference data_routing/scheduler.py:38 + basic_layer.py):
        # per-sample kept-TOKEN subsets for the middle layers, sampled
        # host-side; the kept count ramps by schedule and is bucketed, so each
        # bucket is one compiled program (the reference's reserved-length
        # buckets)
        self.random_ltd_scheduler = None
        if rl.get("enabled"):
            from deepspeed_tpu.runtime.data_pipeline.random_ltd import \
                RandomLTDScheduler
            sched = rl.get("random_ltd_schedule", {})
            sched_cfg = sched.get("schedule_config", {})
            total_layers = int(rl.get("total_layer_num", 0))
            assert total_layers > 0, \
                "data_routing.random_ltd needs total_layer_num (reference schema)"
            layer_ids = rl.get("random_ltd_layer_id")
            if layer_ids:
                layer_ids = sorted(int(i) for i in layer_ids)
                assert layer_ids == list(range(layer_ids[0], layer_ids[-1] + 1)), \
                    "random_ltd_layer_id must be a contiguous range (the " \
                    "stacked-scan formulation splits layers into three slices)"
                start_layer, end_layer = layer_ids[0], layer_ids[-1]
            else:
                start_layer = int(rl.get("ltd_start_layer", 1))
                end_layer = rl.get("ltd_end_layer")
            model_layers = getattr(self.model_spec.arch_cfg, "n_layer", None)
            if model_layers is not None:
                assert total_layers == model_layers, (
                    f"random_ltd total_layer_num={total_layers} does not match "
                    f"the model's n_layer={model_layers}")
                last = end_layer if end_layer is not None else model_layers - 1
                assert 0 <= start_layer <= last < model_layers, (
                    f"random_ltd layer range [{start_layer}, {last}] is out of "
                    f"bounds for an {model_layers}-layer model")
            self.random_ltd_scheduler = RandomLTDScheduler(
                total_layers=total_layers,
                start_ratio=float(sched.get("min_value", 0.5)),
                end_ratio=float(sched.get("max_value", 1.0)),
                total_steps=int(sched_cfg.get("require_steps", 10000)),
                ltd_start_layer=start_layer,
                ltd_end_layer=end_layer,
                bucket=int(sched_cfg.get("seq_per_step", 64)))
            self._ltd_rng = np.random.default_rng(self.config.seed ^ 0x51ED270B)

    @staticmethod
    def _factor_zero_subgroup(config):
        """MiCS/hpZ: factor the data axis into data × zero so params shard over an
        inner sub-group that rides ICI (reference `zero/mics.py:55` sub-group
        sharding; `zero/config.py:256` hpZ secondary partition size)."""
        zcfg = config.zero_optimization
        sub = 0
        if zcfg.mics_shard_size and zcfg.mics_shard_size > 0:
            sub = zcfg.mics_shard_size
        elif zcfg.zero_hpz_partition_size and zcfg.zero_hpz_partition_size > 1:
            sub = zcfg.zero_hpz_partition_size
        if sub > 1 and config.mesh.zero == 1:
            config.mesh.zero = sub
            if config.mesh.data != -1:
                assert config.mesh.data % sub == 0, (
                    f"data axis {config.mesh.data} not divisible by "
                    f"MiCS/hpZ sub-group size {sub}")
                config.mesh.data //= sub

    # ------------------------------------------------------------------
    # state construction
    # ------------------------------------------------------------------

    def _init_state(self, params, param_specs):
        policy = self.zero_policy
        if params is None:
            # zero.Init contract (`zero/partition_parameters.py:723`): the full
            # model never materializes on one host/device. Shardings come from
            # abstract shapes (jax.eval_shape = the meta device); XLA then runs
            # init_fn with out_shardings so every leaf is CREATED in its shard.
            if self.model_spec.init_fn is None:
                raise ValueError("ModelSpec needs either params or init_fn")
            from deepspeed_tpu.utils.init_on_device import materialize_sharded
            init_fn = _normalize_init_fn(self.model_spec.init_fn)
            init_rng = jax.random.PRNGKey(self.config.seed)
            abstract = jax.eval_shape(init_fn, init_rng)
            self.param_shardings = policy.param_shardings(abstract, param_specs)
            params_c = materialize_sharded(
                lambda r: tree_cast(init_fn(r), self.compute_dtype),
                self.param_shardings, init_rng)
        else:
            self.param_shardings = policy.param_shardings(params, param_specs)
            # place params (compute dtype)
            params_c = tree_cast(params, self.compute_dtype)
            params_c = jax.device_put(params_c, self.param_shardings)

        if self.nvme_offload:
            if params is None:
                # the host (C++) optimizer owns an fp32 master in host RAM by
                # design — pull the sharded compute params back once
                params = jax.tree_util.tree_map(
                    # dstpu: ignore[DT001]: engine build, runs once — the host optimizer's fp32 master starts from a device pull
                    lambda x: np.asarray(x, np.float32), jax.device_get(params_c))
            return self._init_state_host_offload(params, params_c)

        # fp32 master (ZeRO-partitioned — reference stage_1_and_2.py:630).
        # base_specs carry the model's TP/PP axes so master/opt shards inherit them.
        if self.keep_master:
            master_shapes = jax.eval_shape(lambda p: tree_cast(p, jnp.float32), params_c)
            self.master_shardings = policy.state_shardings(master_shapes,
                                                           base_specs=param_specs)
            master = jax.jit(lambda p: tree_cast(p, jnp.float32),
                             out_shardings=self.master_shardings)(params_c)
        else:
            master = None
            self.master_shardings = policy.state_shardings(
                jax.eval_shape(lambda p: p, params_c), base_specs=param_specs)

        opt_target = master if master is not None else params_c
        opt_shapes = jax.eval_shape(self.optimizer.init, opt_target)
        self.opt_shardings = policy.state_shardings(opt_shapes, base_specs=param_specs)
        opt_state = jax.jit(self.optimizer.init, out_shardings=self.opt_shardings)(opt_target)
        if self.offload_optimizer_states:
            opt_state = self._to_host(opt_state)
        # single device: the step streams states through HBM with IN-JIT
        # device_puts (XLA overlaps them). Multi-device: the SPMD partitioner
        # rejects in-jit memory-kind transfers of sharded leaves (RET_CHECK
        # "Side-effect HLO must have sharding"), so the engine streams the
        # opt tree EAGERLY around the compiled step instead.
        self._offload_in_jit = (self.offload_optimizer_states
                                and self.mesh.devices.size == 1)

        rep = NamedSharding(self.mesh, P())
        scaler_state = jax.device_put(self.scaler.init(), rep)
        step = jax.device_put(jnp.asarray(0, jnp.int32), rep)
        rng = jax.device_put(jax.random.PRNGKey(self.config.seed), rep)

        # the step program's in/out shardings must carry the ACTUAL placement —
        # pinned host memory when the "cpu" offload tier streams in-jit; the
        # eager-streaming variant calls the step with device-placed states
        opt_state_shardings = (self._host_opt_shardings()
                               if self._offload_in_jit
                               else self.opt_shardings)
        self.state_shardings = TrainState(
            params=self.param_shardings,
            master=self.master_shardings if master is not None else None,
            opt_state=opt_state_shardings,
            scaler=LossScaleState(rep, rep, rep, rep),
            step=rep,
            rng=rep,
        )
        return TrainState(params=params_c, master=master, opt_state=opt_state,
                          scaler=scaler_state, step=step, rng=rng)

    def _init_state_host_offload(self, params, params_c):
        """ZeRO-Infinity state: master + moments owned by HostOffloadOptimizer
        (fp32 numpy, moments optionally NVMe-swapped); device holds only the
        compute-dtype params and the loss-scaler scalars."""
        from deepspeed_tpu.runtime.cpu_optimizer import HostOffloadOptimizer
        off = self.config.zero_optimization.offload_optimizer
        opt_cfg = self.config.optimizer
        opt_params = dict(opt_cfg.params if opt_cfg else {})
        opt_name = (opt_cfg.type.lower() if opt_cfg else "adam")
        kind = ("lion" if "lion" in opt_name
                else "adagrad" if "adagrad" in opt_name else "adam")
        self.host_optimizer = HostOffloadOptimizer(
            params,
            lr=opt_params.get("lr", 1e-3),
            betas=tuple(opt_params.get("betas", (0.9, 0.999))),
            eps=opt_params.get("eps", 1e-8),
            weight_decay=opt_params.get("weight_decay", 0.0),
            adamw_mode="adamw" in opt_name or kind != "adam",
            optimizer=kind,
            nvme_folder=off.nvme_path,
            lr_schedule=self.schedule_fn,
            aio_threads=off.buffer_count,
        )
        rep = NamedSharding(self.mesh, P())
        self.master_shardings = None
        self.opt_shardings = None
        self.state_shardings = TrainState(
            params=self.param_shardings, master=None, opt_state=None,
            scaler=LossScaleState(rep, rep, rep, rep), step=rep, rng=rep)
        return TrainState(
            params=params_c, master=None, opt_state=None,
            scaler=jax.device_put(self.scaler.init(), rep),
            step=jax.device_put(jnp.asarray(0, jnp.int32), rep),
            rng=jax.device_put(jax.random.PRNGKey(self.config.seed), rep))

    def _host_opt_shardings(self):
        """Pinned-host variants of the optimizer-state shardings (one source
        of truth for the offload tier's placement)."""
        return jax.tree_util.tree_map(lambda s: s.with_memory_kind("pinned_host"),
                                      self.opt_shardings)

    def _to_host(self, tree):
        """Move a pytree to pinned host memory (ZeRO-Offload optimizer states)."""
        try:
            return jax.device_put(tree, self._host_opt_shardings())
        except Exception as e:  # CPU backend has no pinned_host memory space
            logger.warning(f"optimizer-state host offload unavailable on this platform ({e}); "
                           "keeping states in device memory")
            self.offload_optimizer_states = False
            return tree

    def _stream_opt_to_device(self, state):
        """Eager half of the multi-device offload tier: states → HBM."""
        return state._replace(opt_state=jax.device_put(state.opt_state,
                                                       self.opt_shardings))

    def _stream_opt_to_host(self, state):
        """Eager half of the multi-device offload tier: states → pinned host."""
        return state._replace(opt_state=jax.device_put(
            state.opt_state, self._host_opt_shardings()))

    def _run_stateful_step(self, step_fn, *args):
        """Invoke a (state, ...) -> (state, metrics) program, eagerly streaming
        offloaded optimizer states through HBM when the in-jit streaming path
        is unavailable (multi-device meshes).

        The eager tier runs SPLIT programs with transfer/compute overlap:
        dispatch the grads program first (it reads no optimizer state), THEN
        queue the host->HBM opt-tree upload — async dispatch runs the DMA
        during the grads computation instead of stalling a fused step on it.
        Only train_batch routes here with step_fn=_train_step; other stateful
        programs (if any) take the round-trip fallback."""
        if self.offload_optimizer_states and not self._offload_in_jit:
            if step_fn is self._train_step:
                if getattr(self, "_off_grads_step", None) is None:
                    self._build_offload_split_step()
                state = self.state
                grads, loss = self._off_grads_step(
                    state.params, *args, state.rng, state.step, state.scaler)
                # queued AFTER the grads dispatch: overlaps with its execution
                state = self._stream_opt_to_device(state)
                new_state, metrics = self._off_apply_step(state, grads, loss)
                return self._stream_opt_to_host(new_state), metrics
            new_state, metrics = step_fn(self._stream_opt_to_device(self.state),
                                         *args)
            return self._stream_opt_to_host(new_state), metrics
        return step_fn(self.state, *args)

    # ------------------------------------------------------------------
    # compiled step programs
    # ------------------------------------------------------------------

    def _grad_shardings(self):
        master_like = self.master_shardings
        return self.zero_policy.grad_shardings(None, self.param_shardings, master_like)

    def _micro_grad_fn(self, with_extras=False):
        """Per-micro-batch grad compute. With `with_extras` the standard
        branch also surfaces slash-namespaced f32 scalars from the loss's aux
        dict (e.g. `moe/aux_loss`, `moe/dropped_frac`) so the fused step can
        merge them into the metrics/telemetry stream; the custom-backward
        (pipeline) branch has no aux channel and returns `{}`."""
        loss_fn = self._loss_fn
        scaler = self.scaler
        custom_grad = getattr(self.model_spec, "grad_fn", None)

        if custom_grad is not None:
            # model computes its own backward (1F1B pipeline schedule); apply
            # the loss scale to the grads directly (linear in the loss)
            def compute(params, micro_batch, rng, scale_state):
                loss, grads = custom_grad(params, micro_batch, rng)
                scale = scaler.scale_loss(jnp.asarray(1.0, jnp.float32),
                                          scale_state)
                grads = jax.tree_util.tree_map(
                    lambda g: g * scale.astype(g.dtype), grads)
                if with_extras:
                    return grads, loss, {}
                return grads, loss

            return compute

        def compute(params, micro_batch, rng, scale_state):
            def scaled(p):
                loss, aux = loss_fn(p, micro_batch, rng)
                return scaler.scale_loss(loss, scale_state), (loss, aux)

            grads, (loss, aux) = jax.grad(scaled, has_aux=True)(params)
            if with_extras:
                extras = {}
                if isinstance(aux, dict):
                    extras = {k: jnp.asarray(v, jnp.float32)
                              for k, v in aux.items()
                              if "/" in k and jnp.ndim(v) == 0}
                return grads, loss, extras
            return grads, loss

        return compute

    def _apply_grads_fn(self):
        """(state, grads, mean loss) -> (new_state, metrics). Shared by the
        fused train step and the forward/backward/step parity path.

        Grads arrive in COMPUTE dtype at gas==1 (bf16→f32 promotion inside the
        fused update is exact; an eager upcast would only burn HBM) and in
        fp32 at gas>1 (cross-micro-batch accumulation) or after fp16
        unscaling (`LossScaler.unscale_grads` upcasts)."""
        scaler = self.scaler
        optimizer = self.optimizer
        clip = self.config.gradient_clipping
        keep_master = self.keep_master
        compute_dtype = self.compute_dtype
        grad_shardings = self._grad_shardings()
        param_shardings = self.param_shardings
        schedule_fn = self.schedule_fn

        offload_opt = bool(getattr(self, "_offload_in_jit", False))
        opt_dev_shardings = self.opt_shardings
        opt_host_shardings = self._host_opt_shardings() if offload_opt else None

        def apply_grads(state, grads, loss):
            # ZeRO: constrain grads → reduce-scatter (stage>=2) or allreduce layout
            grads = jax.lax.with_sharding_constraint(grads, grad_shardings)
            grads = scaler.unscale_grads(grads, state.scaler)

            finite = scaler.check_overflow(grads)
            # fp32-accumulated global norm (grads may be bf16; a bf16 reduce
            # would overflow/round — the cast fuses into the reduction)
            grad_norm = tree_global_norm(grads)
            if clip and clip > 0:
                factor = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
                grads = jax.tree_util.tree_map(lambda g: g * factor.astype(g.dtype), grads)

            target = state.master if keep_master else state.params
            # "cpu" offload tier: states live in pinned host memory between
            # steps; stream them through HBM for the update (the reference
            # instead runs the step on the CPU — ZeRO-Offload's overlap is
            # XLA's to schedule here)
            opt_in = (jax.device_put(state.opt_state, opt_dev_shardings)
                      if offload_opt else state.opt_state)
            updates, new_opt = optimizer.update(grads, opt_in, target)
            new_target = optax.apply_updates(target, updates)

            # masked skip-step on overflow (reference: FP16_Optimizer.step overflow path)
            new_target = masked_update(new_target, target, finite)
            new_opt = masked_update(new_opt, opt_in, finite)
            if offload_opt:
                new_opt = jax.device_put(new_opt, opt_host_shardings)

            if keep_master:
                new_params = tree_cast(new_target, compute_dtype)
                new_master = new_target
            else:
                new_params = new_target
                new_master = None
            # re-materialize params in their (replicated or fsdp) layout → all-gather
            new_params = jax.lax.with_sharding_constraint(new_params, param_shardings)

            new_scaler = scaler.update(state.scaler, finite)
            new_step = state.step + jnp.where(finite, 1, 0).astype(jnp.int32)
            rng, _ = jax.random.split(state.rng)

            lr = (schedule_fn(state.step) if schedule_fn is not None
                  else jnp.asarray(0.0, jnp.float32))
            metrics = {
                "loss": loss.astype(jnp.float32),
                "grad_norm": grad_norm.astype(jnp.float32),
                "overflow": ~finite,
                "loss_scale": state.scaler.scale,
                "lr": jnp.asarray(lr, jnp.float32),
            }
            new_state = TrainState(params=new_params, master=new_master, opt_state=new_opt,
                                   scaler=new_scaler, step=new_step, rng=rng)
            return new_state, metrics

        return apply_grads

    def _quantized_micro_grad_fn(self):
        """ZeRO++ explicit-collective micro step (qwZ/qgZ).

        The standard step lets XLA insert bf16/f32 collectives from sharding
        constraints; quantized collectives must be explicit, so this variant runs
        the micro-grad inside `shard_map` over the data domain: params arrive as
        their ZeRO-3 shards and are (optionally) gathered over an int8 wire
        (qwZ, reference `partition_parameters.py:668`), grads leave through the
        2-hop int8 all-to-all reduce (qgZ, `coalesced_collectives.py:31`).
        Supported on pure data-parallel meshes (tensor/sequence/pipe/expert = 1),
        matching the reference's DP-only scope for these features.
        """
        from deepspeed_tpu.utils.jax_compat import shard_map
        from deepspeed_tpu.runtime import quantized_collectives as qc

        zcfg = self.config.zero_optimization
        qw = bool(zcfg.zero_quantized_weights) and self.zero_stage == 3
        qg = bool(zcfg.zero_quantized_gradients)
        sizes = self.spec.axis_sizes()
        for ax in (mesh_mod.TENSOR_AXIS, mesh_mod.SEQ_AXIS, mesh_mod.PIPE_AXIS,
                   mesh_mod.EXPERT_AXIS):
            assert sizes[ax] == 1, (
                "zero_quantized_weights/gradients need a pure data-parallel mesh "
                f"(axis {ax} has size {sizes[ax]})")
        axes = tuple(a for a in (mesh_mod.DATA_AXIS, mesh_mod.ZERO_INNER_AXIS)
                     if sizes[a] > 1) or (mesh_mod.DATA_AXIS,)
        micro_grad = self._micro_grad_fn()
        group_size = 256

        param_specs = jax.tree_util.tree_map(lambda s: s.spec, self.param_shardings)

        def gather_site(spec):
            return _gather_site(spec, axes)

        def body(params, micro_batch, rng, scale_state):
            if self.zero_stage == 3:
                # stage-3 shards must be gathered before use: int8 wire under
                # qwZ, plain bf16 all-gather otherwise (qgZ-only config)
                def gather(p, spec):
                    d, ax = gather_site(spec)
                    if d is None:
                        return p
                    if qw:
                        return qc.quantized_all_gather_dim(p, ax, d, group_size)
                    return jax.lax.all_gather(p, ax, axis=d, tiled=True)
                params = jax.tree_util.tree_map(gather, params, param_specs)
            with mesh_mod.constraints_disabled():
                grads, loss = micro_grad(params, micro_batch, rng, scale_state)
            n = 1
            for a in axes:
                n *= sizes[a]
            if qg:
                # qgZ sums over the domain; grad semantics here are mean
                grads = jax.tree_util.tree_map(
                    lambda g: qc.qgz_allreduce(g.astype(jnp.float32), axes,
                                               group_size) / n, grads)
            else:
                grads = jax.lax.pmean(grads, axes)
            loss = jax.lax.pmean(loss, axes)
            return grads, loss

        def qmicro(params, micro_batch, rng, scale_state):
            in_batch_specs = jax.tree_util.tree_map(
                lambda _: P(mesh_mod.BATCH_AXES), micro_batch)
            return shard_map(
                body, mesh=self.mesh,
                in_specs=(param_specs, in_batch_specs, P(),
                          jax.tree_util.tree_map(lambda _: P(), scale_state)),
                out_specs=(jax.tree_util.tree_map(lambda _: P(), params), P()),
                check_vma=False,
            )(params, micro_batch, rng, scale_state)

        return qmicro

    def _explicit_grads_fn(self, wire, fast, slow):
        """Explicit compressed grad-reduce through the comm facade.

        One `shard_map` spans the whole gas scan, so the step does ONE
        hierarchical reduce instead of one per micro-batch: a plain psum
        rides the fast (ICI) axes, then the declared slow axis runs the
        2-hop transform wire (`comm/collectives.compressed_all_reduce`) —
        fp32 (`wire="none"`, the measured baseline), int8 qgZ
        (`wire="int8"`), or the 1-bit Adam error-feedback reduce
        (`wire="onebit"`, which threads residuals through the step:
        signature grows a trailing `err` argument and return value).

        Stage-3 shards gather on entry (int8 under qwZ), same as the
        per-micro quantized path; like it, this needs a data-domain-only
        mesh.
        """
        from deepspeed_tpu.utils.jax_compat import shard_map
        from deepspeed_tpu.comm import collectives as coll
        from deepspeed_tpu.runtime import quantized_collectives as qc

        zcfg = self.config.zero_optimization
        qw = bool(zcfg.zero_quantized_weights) and self.zero_stage == 3
        sizes = self.spec.axis_sizes()
        for ax in (mesh_mod.TENSOR_AXIS, mesh_mod.SEQ_AXIS,
                   mesh_mod.PIPE_AXIS, mesh_mod.EXPERT_AXIS):
            if sizes[ax] != 1:
                raise ValueError(
                    "explicit_grad_reduce/onebit_gradients need a data-"
                    f"domain-only mesh (axis {ax} has size {sizes[ax]}); "
                    "pipeline models take the grad_reduce_transform knob "
                    "instead")
        axes = fast + (slow,)
        n_total = 1
        for a in axes:
            n_total *= sizes[a]
        onebit = wire == "onebit"
        gas = self.gradient_accumulation_steps_value
        micro_grad = self._micro_grad_fn()
        group_size = 256
        predivide = self.config.gradient_predivide_factor or 1.0
        param_specs = jax.tree_util.tree_map(lambda s: s.spec,
                                             self.param_shardings)

        def body(params, batch, rng, scale_state, err):
            if self.zero_stage == 3:
                def gather(p, spec):
                    d, ax = _gather_site(spec, axes)
                    if d is None:
                        return p
                    if qw:
                        return qc.quantized_all_gather_dim(p, ax, d,
                                                           group_size)
                    return coll.all_gather(p, ax, axis=d, tiled=True)
                params = jax.tree_util.tree_map(gather, params, param_specs)
            with mesh_mod.constraints_disabled():
                if gas > 1:
                    def scan_body(carry, mb):
                        g_acc, loss_acc, i = carry
                        g, l = micro_grad(params, mb,
                                          jax.random.fold_in(rng, i),
                                          scale_state)
                        g_acc = jax.tree_util.tree_map(
                            lambda a, b: a + (b.astype(jnp.float32)
                                              / jnp.asarray(predivide,
                                                            jnp.float32)),
                            g_acc, g)
                        return (g_acc, loss_acc + l.astype(jnp.float32),
                                i + 1), None

                    zeros = jax.tree_util.tree_map(
                        lambda p: jnp.zeros(p.shape, jnp.float32), params)
                    (grads, loss_sum, _), _ = jax.lax.scan(
                        scan_body, (zeros, jnp.asarray(0.0, jnp.float32), 0),
                        batch)
                    grads = jax.tree_util.tree_map(
                        lambda g: g * (predivide / gas), grads)
                    loss = loss_sum / gas
                else:
                    grads, loss = micro_grad(params, batch, rng, scale_state)
                    grads = jax.tree_util.tree_map(
                        lambda g: g.astype(jnp.float32), grads)
            # hierarchical reduce: fast axes in plain fp32, slow axis wired
            if fast:
                grads = jax.tree_util.tree_map(
                    lambda g: coll.psum(g, fast), grads)
            new_err = err
            if onebit:
                err_local = jax.tree_util.tree_map(lambda e: e[0], err)
                flat_g, treedef = jax.tree_util.tree_flatten(grads)
                flat_e = jax.tree_util.tree_leaves(err_local)
                outs = [coll.compressed_all_reduce(g, slow, "onebit", err=e)
                        for g, e in zip(flat_g, flat_e)]
                grads = jax.tree_util.tree_unflatten(
                    treedef, [o[0] for o in outs])
                new_err = jax.tree_util.tree_unflatten(
                    treedef, [o[1][None] for o in outs])
            else:
                # same 2-hop reduce-scatter + all-gather structure for the
                # fp32 and int8 wires — the facade byte stats then compare
                # the ENCODING alone (the bench lane's wire-ratio claim)
                grads = jax.tree_util.tree_map(
                    lambda g: coll.compressed_all_reduce(
                        g, slow, wire, group_size=group_size), grads)
            grads = jax.tree_util.tree_map(lambda g: g / n_total, grads)
            loss = jax.lax.pmean(loss, axes)
            return grads, loss, new_err

        batch_leaf_spec = P(None, mesh_mod.BATCH_AXES) if gas > 1 \
            else P(mesh_mod.BATCH_AXES)
        grads_out_specs = jax.tree_util.tree_map(lambda _: P(), param_specs)

        def grads_fn(params, batch, rng, scaler_state, err=None):
            in_batch_specs = jax.tree_util.tree_map(
                lambda _: batch_leaf_spec, batch)
            scaler_specs = jax.tree_util.tree_map(lambda _: P(), scaler_state)
            if onebit:
                err_specs = jax.tree_util.tree_map(lambda _: P(slow), err)
                return shard_map(
                    body, mesh=self.mesh,
                    in_specs=(param_specs, in_batch_specs, P(), scaler_specs,
                              err_specs),
                    out_specs=(grads_out_specs, P(), err_specs),
                    check_vma=False,
                )(params, batch, rng, scaler_state, err)
            grads, loss = shard_map(
                lambda p, b, r, s: body(p, b, r, s, None)[:2],
                mesh=self.mesh,
                in_specs=(param_specs, in_batch_specs, P(), scaler_specs),
                out_specs=(grads_out_specs, P()),
                check_vma=False,
            )(params, batch, rng, scaler_state)
            return grads, loss

        return grads_fn

    def _grad_accum_dtype(self):
        """Gas accumulator dtype (reference data_types.grad_accum_dtype,
        `runtime/config.py:876`): fp32 default; bf16/fp16 opt-in."""
        name = (self.config.data_types.grad_accum_dtype or "fp32").lower()
        table = {"fp32": jnp.float32, "float32": jnp.float32,
                 "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
                 "fp16": jnp.float16, "float16": jnp.float16}
        assert name in table, f"unknown grad_accum_dtype {name!r}"
        return table[name]

    def _make_grads_fn(self, with_extras=False):
        """(params, batch, rng, scaler) -> (grads, loss): the gas-scan grad
        accumulation exactly as the fused step computes it (accumulator dtype,
        predivide, quantized-collective micro path). Shared by the fused
        train step and the offload tier's split grads program. With
        `with_extras` the return grows a third element: slash-namespaced f32
        scalar metrics from the loss aux (mean over micro-batches at gas>1;
        `{}` on the quantized micro path, which spans a shard_map)."""
        gas = self.gradient_accumulation_steps_value
        zcfg = self.config.zero_optimization
        wire = getattr(self, "_explicit_wire", None)
        if wire is not None:
            if getattr(self.model_spec, "grad_fn", None) is not None:
                logger.warning(
                    "explicit_grad_reduce/onebit_gradients ignored: model "
                    "supplies a custom grad_fn (pipeline 1F1B) — use the "
                    "pipeline's grad_reduce_transform knob instead")
            elif wire == "onebit" and self._comm_err is None:
                logger.warning(
                    "onebit_gradients: single-device data domain — "
                    "error-feedback wire disabled")
            else:
                fast, slow = self.zero_policy.reduce_domain(
                    getattr(zcfg, "compressed_comm_axis", None))
                if slow is None:
                    logger.warning(
                        "explicit_grad_reduce: single-device data domain — "
                        "compressed wire disabled")
                else:
                    fn = self._explicit_grads_fn(wire, fast, slow)
                    if with_extras and wire != "onebit":
                        # explicit-collective path spans a shard_map: no
                        # aux-metrics channel; keep the 3-tuple contract
                        return lambda *a: fn(*a) + ({},)
                    return fn
        wants_quantized = zcfg.zero_quantized_gradients or (
            zcfg.zero_quantized_weights and self.zero_stage == 3)
        if wants_quantized and getattr(self.model_spec, "grad_fn", None) is None:
            qmicro = self._quantized_micro_grad_fn()

            def micro_grad(*a):
                return qmicro(*a) + ({},)
        else:
            if wants_quantized:
                logger.warning(
                    "zero_quantized_gradients/weights ignored: model supplies "
                    "a custom grad_fn (pipeline 1F1B) which computes its own "
                    "backward pass")
            micro_grad = self._micro_grad_fn(with_extras=True)
        grad_shardings = self._grad_shardings()
        predivide = self.config.gradient_predivide_factor or 1.0

        def grads_fn(params, batch, rng, scaler_state):
            if gas > 1:
                acc_dtype = self._grad_accum_dtype()

                def body(carry, micro_batch):
                    g_acc, loss_acc, i = carry
                    g, l, e = micro_grad(params, micro_batch,
                                         jax.random.fold_in(rng, i),
                                         scaler_state)
                    g_acc = jax.tree_util.tree_map(
                        lambda a, b: a + (b.astype(acc_dtype)
                                          / jnp.asarray(predivide, acc_dtype)),
                        g_acc, g)
                    return (g_acc, loss_acc + l.astype(jnp.float32), i + 1), e

                zeros = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, acc_dtype), params)
                zeros = jax.lax.with_sharding_constraint(zeros, grad_shardings)
                (grads, loss_sum, _), extras = jax.lax.scan(
                    body, (zeros, jnp.asarray(0.0, jnp.float32), 0), batch)
                grads = jax.tree_util.tree_map(lambda g: g * (predivide / gas), grads)
                loss = loss_sum / gas
                # scan stacks per-micro extras along the leading axis
                extras = {k: jnp.mean(v) for k, v in extras.items()}
            else:
                # grads stay in compute dtype: they were already rounded to it
                # by the backward pass, and bf16→f32 promotion inside the fused
                # optimizer update is exact — an eager upcast would only
                # materialize an extra fp32 grad tree (1.4G at 350M, 3G at
                # 760m; fp32 accumulation matters only ACROSS micro-batches,
                # the gas>1 branch above)
                grads, loss, extras = micro_grad(params, batch, rng, scaler_state)
            if with_extras:
                return grads, loss, extras
            return grads, loss

        return grads_fn

    def _build_train_step(self):
        # the EF wire path returns the explicit-collective grads_fn (5-arg,
        # no extras channel); the standard path threads slash-keyed loss-aux
        # metrics (moe/* counters) through to the metrics dict
        grads_fn = self._make_grads_fn(with_extras=self._comm_err is None)
        apply_grads = self._apply_grads_fn()

        if self._comm_err is not None:
            # onebit wire: the error-feedback residuals thread through the
            # fused step as a third donated argument/output
            def train_step_ef(state, batch, err):
                rng = jax.random.fold_in(state.rng, state.step)
                grads, loss, new_err = grads_fn(state.params, batch, rng,
                                                state.scaler, err)
                new_state, metrics = apply_grads(state, grads, loss)
                return new_state, metrics, new_err

            return jax.jit(train_step_ef,
                           donate_argnums=(0, 2),
                           out_shardings=(self.state_shardings, None,
                                          self._comm_err_shardings))

        def train_step(state, batch):
            rng = jax.random.fold_in(state.rng, state.step)
            grads, loss, extras = grads_fn(state.params, batch, rng, state.scaler)
            new_state, metrics = apply_grads(state, grads, loss)
            metrics.update(extras)
            return new_state, metrics

        return jax.jit(train_step,
                       donate_argnums=(0,),
                       out_shardings=(self.state_shardings, None))

    def _build_offload_split_step(self):
        """Split programs for the EAGER multi-device offload tier (VERDICT r4
        weak #3): the fused step would stall on the host->HBM transfer of the
        full fp32 optimizer tree before computing anything (an XLA executable
        waits for ALL its inputs). Splitting grads from the update lets the
        opt-state upload ride the async dispatch queue WHILE the (long)
        grads program computes — reference analog: the pipelined swapper
        (`runtime/swap_tensor/pipelined_optimizer_swapper.py:51`) overlaps
        swap-in with backward the same way."""
        grads_fn = self._make_grads_fn()
        apply_grads = self._apply_grads_fn()

        def grads_prog(params, batch, rng_key, step, scaler_state):
            rng = jax.random.fold_in(rng_key, step)
            return grads_fn(params, batch, rng, scaler_state)

        def apply_prog(state, grads, loss):
            return apply_grads(state, grads, loss)

        # pin the grads' output sharding to what _off_apply_step consumes:
        # on sharded gas==1 meshes (no in-fn sharding constraint on grads)
        # propagation could otherwise pick a layout that forces a cross-
        # boundary reshard between the two programs (ADVICE r5 #3)
        self._off_grads_step = jax.jit(
            grads_prog, out_shardings=(self._grad_shardings(), None))
        self._off_apply_step = jax.jit(apply_prog, donate_argnums=(0,),
                                       out_shardings=(self.state_shardings, None))

    def _build_grad_program(self):
        """Device program for the host-offload step: grads + loss only."""
        gas = self.gradient_accumulation_steps_value
        micro_grad = self._micro_grad_fn()
        grad_shardings = self.param_shardings

        acc_dtype = self._grad_accum_dtype()

        def grad_program(params, batch, rng, scaler_state):
            if gas > 1:
                def body(carry, mb):
                    g_acc, loss_acc, i = carry
                    g, l = micro_grad(params, mb, jax.random.fold_in(rng, i), scaler_state)
                    g_acc = jax.tree_util.tree_map(
                        lambda a, b: a + b.astype(acc_dtype), g_acc, g)
                    return (g_acc, loss_acc + l.astype(jnp.float32), i + 1), None

                zeros = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, acc_dtype), params)
                (grads, loss_sum, _), _ = jax.lax.scan(
                    body, (zeros, jnp.asarray(0.0, jnp.float32), 0), batch)
                grads = jax.tree_util.tree_map(lambda g: g / gas, grads)
                loss = loss_sum / gas
            else:
                grads, loss = micro_grad(params, batch, rng, scaler_state)
                grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
            grads = jax.lax.with_sharding_constraint(grads, grad_shardings)
            return grads, loss

        return jax.jit(grad_program)

    def _host_train_batch(self, batch):
        """ZeRO-Infinity step: device grads -> C++ host optimizer -> params push."""
        placed = self._maybe_split_gas(batch)
        rng = jax.random.fold_in(self.state.rng, self.state.step)
        grads, loss = self._grad_program(self.state.params, placed, rng,
                                         self.state.scaler)
        master = self.host_optimizer.step(grads)
        params = self._push_params(master)
        self.state = self.state._replace(params=params, step=self.state.step + 1)
        metrics = {"loss": loss.astype(jnp.float32),
                   "grad_norm": jnp.asarray(0.0),
                   "overflow": jnp.asarray(False),
                   "loss_scale": self.state.scaler.scale,
                   "lr": jnp.asarray(self.host_optimizer._current_lr(), jnp.float32)}
        return metrics

    def _build_eval_step(self):
        loss_fn = self._loss_fn

        def eval_step(params, batch, rng):
            loss, aux = loss_fn(params, batch, rng)
            return loss

        return jax.jit(eval_step)

    def _build_grad_and_apply(self):
        """Separate grad / apply programs for the forward/backward/step parity API."""
        micro_grad = self._micro_grad_fn()
        apply_grads = self._apply_grads_fn()

        def grad_step(state, batch, micro_idx):
            rng = jax.random.fold_in(state.rng, state.step * 131071 + micro_idx)
            grads, loss = micro_grad(state.params, batch, rng, state.scaler)
            grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
            return grads, loss

        def accumulate(acc, grads):
            return jax.tree_util.tree_map(lambda a, g: a + g, acc, grads)

        self._grad_step = jax.jit(grad_step)
        self._acc_step = jax.jit(accumulate, donate_argnums=(0,))

        def apply(state, grads, loss, n):
            grads = jax.tree_util.tree_map(lambda g: g / n, grads)
            return apply_grads(state, grads, loss / n)

        # donate the state only: per leaf the program has params+mu+nu+grads
        # donated in but only params+mu+nu out, so one buffer per leaf can
        # never alias — donating grads too just trips XLA's "donated buffers
        # were not usable" warning without freeing anything extra (the grads
        # buffer dies at the end of the program either way)
        self._apply_step = jax.jit(apply, donate_argnums=(0,),
                                   out_shardings=(self.state_shardings, None))

    # ------------------------------------------------------------------
    # batch placement
    # ------------------------------------------------------------------

    def _batch_sharding(self, for_scan):
        lead = (None, mesh_mod.BATCH_AXES) if for_scan else (mesh_mod.BATCH_AXES,)
        return NamedSharding(self.mesh, P(*lead))

    def _shard_batch(self, batch, for_scan):
        sharding = self._batch_sharding(for_scan)

        def place(x):
            x = np.asarray(x) if not isinstance(x, (jnp.ndarray, jax.Array)) else x
            return jax.device_put(x, sharding)

        return jax.tree_util.tree_map(place, batch)

    def _maybe_split_gas(self, batch):
        """[gas*micro*dp, ...] -> [gas, micro*dp, ...] for the scan."""
        gas = self.gradient_accumulation_steps_value
        if gas == 1:
            return self._shard_batch(batch, for_scan=False)

        def split(x):
            x = np.asarray(x)
            assert x.shape[0] % gas == 0, (
                f"batch dim {x.shape[0]} not divisible by gradient_accumulation_steps={gas}")
            return x.reshape(gas, x.shape[0] // gas, *x.shape[1:])

        return self._shard_batch(jax.tree_util.tree_map(split, batch), for_scan=True)

    # ------------------------------------------------------------------
    # public API (reference parity)
    # ------------------------------------------------------------------

    def train_batch(self, batch=None, data_iter=None):
        """One full optimizer step: GAS micro-batches fused into one XLA program.

        Analog of `PipelineEngine.train_batch` / the forward-backward-step loop of
        the reference engine. `batch` leading dim must be gas × micro × dp_data.
        """
        if batch is None:
            it = data_iter
            if it is None and self.training_dataloader is not None:
                # persistent repeating iterator (reference RepeatingLoader semantics)
                if getattr(self, "_data_iterator", None) is None:
                    self._data_iterator = iter(RepeatingLoader(self.training_dataloader))
                it = self._data_iterator
            assert it is not None, "train_batch needs a batch or data_iter/training_data"
            batch = next(it)
        if self.curriculum_scheduler is not None and isinstance(batch, dict) \
                and ("tokens" in batch or "input_ids" in batch):
            # label-mask formulation keeps shapes static under jit (no
            # per-difficulty recompiles, unlike the reference's truncation);
            # applies both to bare-token batches (labels derived) and to
            # batches that already carry labels (masked in place)
            from deepspeed_tpu.runtime.data_pipeline.curriculum import \
                apply_seqlen_curriculum
            difficulty = self.curriculum_scheduler.update_difficulty(self.global_steps)
            batch = apply_seqlen_curriculum(batch, difficulty)
        if (self.progressive_layer_drop is not None
                or self.random_ltd_scheduler is not None) and isinstance(batch, dict):
            batch = self._inject_routing_directives(batch)
        self.tput_timer.start()
        self.timers(TRAIN_BATCH_TIMER).start()
        t_step0 = time.perf_counter()   # timer.start() already fenced the device
        placed = None
        try:
            if self.host_optimizer is not None:
                metrics = self._host_train_batch(batch)
            elif self._comm_err is not None:
                placed = self._maybe_split_gas(batch)
                self.state, metrics, self._comm_err = self._run_stateful_step(
                    self._train_step, placed, self._comm_err)
            else:
                placed = self._maybe_split_gas(batch)
                self.state, metrics = self._run_stateful_step(
                    self._train_step, placed)
        except Exception as e:
            # OOM-forensics dispatch boundary: RESOURCE_EXHAUSTED dumps the
            # memory ledger + planner delta + flight ring, then re-raises
            if self.memscope is not None:
                self.memscope.on_step_error(e)
            raise
        self.timers(TRAIN_BATCH_TIMER).stop()
        step_seconds = time.perf_counter() - t_step0   # incl. stop()'s fence
        self.tput_timer.stop(global_step=True)
        # auto-profile at profile_step (reference engine.forward:1782 /
        # step:2162 flops_profiler_profile_step hook); outside the timer
        # window — cost analysis recompiles the step from scratch
        fp_cfg = self.config.flops_profiler
        if fp_cfg.enabled and self._flops_profiler is None \
                and self.global_steps + 1 >= fp_cfg.profile_step:
            if placed is not None:
                self._run_flops_profile(placed)
            else:
                logger.warning("flops_profiler: not supported with the host "
                               "(CPU-offload) optimizer step; skipping")
                from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler
                self._flops_profiler = FlopsProfiler(ds_engine=self)
        self._after_step(metrics, count_micro=True)
        if self.telemetry.enabled:
            self._record_step_telemetry(batch, placed, step_seconds)
        self._maybe_step_moq(batch)
        self._maybe_step_compression()
        return metrics["loss"]

    def _maybe_step_compression(self):
        """Advance stateful compression (snip_momentum masks, activation-
        quant schedule gates); a True step() means trace-time state changed
        and the compiled programs must be rebuilt (same contract as MoQ).
        Stepper errors propagate — a swallowed failure would silently train
        uncompressed (fail-loud policy)."""
        retrace = False
        for s in self.compression_steppers:
            retrace = bool(s.step(self)) or retrace
        if retrace:
            self._rebuild_compiled_steps()

    def _rebuild_compiled_steps(self):
        """Invalidate every program that bakes trace-time compression state
        (fake-quant bits, pruning masks, act-quant gates) in as constants —
        including the host-optimizer path's grad program."""
        if self._train_step is not None:
            self._train_step = self._build_train_step()
        if getattr(self, "_grad_program", None) is not None:
            self._grad_program = self._build_grad_program()
        self._eval_step = self._build_eval_step()
        self._grad_step = None
        self._apply_step = None
        self._off_grads_step = None
        self._off_apply_step = None

    def _inject_routing_directives(self, batch):
        """Host-side per-step sampling for PLD / random-LTD, delivered as
        EXTRA batch leaves broadcast over the batch dim — they split, shard
        and scan exactly like the data, and their SHAPES carry the static
        kept counts (one compiled program per count bucket; see __init__).

        Leaves (consumed by models/gpt.gpt_loss; other models ignore them):
          pld_keep_idx [B, n_keep] int32 — kept layer ids (same for all rows)
          pld_theta    [B] float32       — current keep-prob for the rescale
          ltd_keep_idx [B, n_ltd_layers, K] int32 — per-SAMPLE sorted kept
              token positions for each routed layer
          ltd_start    [B, start_layer] int8 zeros — the static start layer,
              carried in the shape (values are tracers under jit)
        """
        tokens = batch.get("tokens", batch.get("input_ids"))
        if tokens is None:
            return batch
        tokens = np.asarray(tokens)
        B0 = tokens.shape[0]
        out = dict(batch)
        pld = self.progressive_layer_drop
        if pld is not None:
            pld.update_state(self.global_steps)
            theta = pld.get_theta()
            n_layer = getattr(getattr(self.model_spec, "arch_cfg", None),
                              "n_layer", None)
            assert n_layer, ("progressive_layer_drop needs the model's layer "
                            "count (ModelSpec.arch_cfg.n_layer)")
            keep = self._pld_rng.random(n_layer) < theta
            if not keep.any():
                keep[self._pld_rng.integers(n_layer)] = True
            idx = np.flatnonzero(keep).astype(np.int32)
            out["pld_keep_idx"] = np.broadcast_to(idx[None], (B0, idx.size)).copy()
            out["pld_theta"] = np.full((B0,), theta, np.float32)
        sched = self.random_ltd_scheduler
        if sched is not None:
            T_in = tokens.shape[1] - (0 if batch.get("labels") is not None else 1)
            K = sched.keep_count(self.global_steps, T_in)
            lo, hi = sched.start_layer, sched.end_layer
            n_ltd = hi - lo + 1
            if K < T_in and n_ltd > 0:
                # vectorized sample-without-replacement: top-K of uniform keys
                r = self._ltd_rng.random((B0, n_ltd, T_in))
                idx = np.sort(np.argpartition(r, K - 1, axis=-1)[..., :K],
                              axis=-1).astype(np.int32)
                out["ltd_keep_idx"] = idx
                # the start layer must be STATIC for the three-way layer-scan
                # split; values are tracers under jit, so it rides in a dummy
                # leaf's SHAPE like the counts do ([B, lo] int8 zeros)
                out["ltd_start"] = np.zeros((B0, lo), np.int8)
        return out

    def _maybe_step_moq(self, batch):
        """Advance the MoQ bit-reduction schedule once per optimizer step; at
        gas-boundary resolution, refresh per-layer curvature estimates that
        stretch high-curvature layers' periods (reference engine.py:2116-2127
        + quantize.py:51). When bits change, retrace the compiled programs
        that bake the fake-quant constants in."""
        sched = self.quantize_scheduler
        if sched is None or not sched.any_precision_switch():
            return
        ecfg = self.config.eigenvalue
        ev = self.block_eigenvalue
        if ecfg.enabled and self.global_steps % max(ecfg.gas_boundary_resolution, 1) == 0:
            from deepspeed_tpu.runtime.quantize import (block_eigenvalues,
                                                        post_process_eigenvalues)
            try:
                mb = jax.tree_util.tree_map(
                    lambda a: a[:self.micro_batch_size], batch)
                rng = jax.random.PRNGKey(self.config.seed)
                raw = block_eigenvalues(
                    lambda p, b: self._loss_fn(p, b, rng)[0],
                    self.state.params, mb,
                    max_iter=ecfg.max_iter, tol=ecfg.tol,
                    stability=ecfg.stability)
                ev = self.block_eigenvalue = post_process_eigenvalues(raw)
                if ecfg.verbose:
                    log_dist(f"block eigenvalues: raw={raw} scaled={ev}", ranks=[0])
            except (KeyError, TypeError) as e:
                logger.warning(f"eigenvalue estimation unavailable for this "
                               f"model layout ({e}); MoQ advances uncurved")
        if sched.step(ev):
            self._rebuild_compiled_steps()

    def eval_batch(self, batch, rng=None):
        placed = self._shard_batch(batch, for_scan=False)
        rng = rng if rng is not None else jax.random.fold_in(self.state.rng, 0x7FFFFFFF)
        return self._eval_step(self.state.params, placed, rng)

    # --- forward/backward/step parity triplet -------------------------------
    # In functional JAX the loss is produced inside grad; `forward` therefore
    # computes loss AND per-microbatch grads in one compiled call, `backward`
    # accumulates them, `step` applies at the GAS boundary — semantically identical
    # to the reference's autograd flow (engine.py:1753,1894,2092).

    def forward(self, batch):
        if self._grad_step is None:
            self._build_grad_and_apply()
        placed = self._shard_batch(batch, for_scan=False)
        grads, loss = self._grad_step(self.state, placed,
                                      jnp.asarray(len(self._pending), jnp.int32))
        self._forward_cache = (grads, loss)
        return loss

    def backward(self, loss=None, allreduce_gradients=True):
        assert getattr(self, "_forward_cache", None) is not None, \
            "backward() must follow forward()"
        grads, loss_v = self._forward_cache
        self._forward_cache = None
        if not self._pending:
            self._grad_acc, self._loss_acc = grads, loss_v
        else:
            self._grad_acc = self._acc_step(self._grad_acc, grads)
            self._loss_acc = self._loss_acc + loss_v
        self._pending.append(1)
        self.micro_steps += 1
        return loss_v

    def step(self):
        assert self._pending, "step() must follow backward()"
        n = float(len(self._pending))
        self.state, metrics = self._run_stateful_step(
            self._apply_step, self._grad_acc, self._loss_acc, n)
        self._pending = []
        self._grad_acc = None
        self._after_step(metrics)
        return metrics

    def _after_step(self, metrics, count_micro=False):
        self.global_steps += 1
        if count_micro:
            self.micro_steps += self.gradient_accumulation_steps_value
        self._last_metrics = metrics
        if self.telemetry.enabled:
            # slash-namespaced metrics (moe/aux_loss, moe/overflow_tokens, …)
            # are model-emitted gauges; the fixed train/* set is handled by
            # _record_step_telemetry
            reg = self.telemetry.registry
            for k, v in metrics.items():
                if "/" in k:
                    reg.gauge(k).set(float(v))
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        # overflow can only occur under fp16; avoid a host sync otherwise
        if self.fp16_enabled and bool(metrics.get("overflow", False)):
            self.skipped_steps += 1
            log_dist(f"step {self.global_steps}: grad overflow — step skipped "
                     f"(loss scale -> {float(self.state.scaler.scale):.1f})", ranks=[0])
        if self.monitor is not None and self.monitor.enabled:
            if self.global_steps % self.config.steps_per_print == 0:
                events = [
                    ("Train/loss", float(metrics["loss"]), self.global_steps),
                    ("Train/lr", float(metrics["lr"]), self.global_steps),
                    ("Train/loss_scale", float(metrics["loss_scale"]), self.global_steps),
                    ("Train/grad_norm", float(metrics["grad_norm"]), self.global_steps),
                ]
                if self.block_eigenvalue is not None:
                    # reference engine.py:2150-2158 Train/Eigenvalues events
                    events += [(f"Train/Eigenvalues/ModelBlockParam_{i}",
                                float(v), self.global_steps)
                               for i, v in enumerate(self.block_eigenvalue)]
                self.monitor.write_events(events)
        if self.config.wall_clock_breakdown and \
                self.global_steps % self.config.steps_per_print == 0:
            self.timers.log([TRAIN_BATCH_TIMER])
        if self.config.memory_breakdown and \
                self.global_steps % self.config.steps_per_print == 0:
            # the reference's memory_breakdown knob: periodic
            # see_memory_usage, routed through the registry too so the
            # mem/bytes_in_use gauge tracks the same reading
            from deepspeed_tpu.utils.memory import see_memory_usage
            see_memory_usage(f"step {self.global_steps}", force=True,
                             telemetry=self.telemetry)
        if self._sentinel.enabled:
            overflow = self.fp16_enabled and bool(metrics.get("overflow", False))
            cause = self._sentinel.observe(float(metrics["loss"]), overflow)
            if cause is not None:
                self._recover_bad_state(cause)

    # ------------------------------------------------------------------
    # telemetry (deepspeed_tpu/telemetry/; opt-in `telemetry` config block)
    # ------------------------------------------------------------------

    def _record_step_telemetry(self, batch, placed, step_seconds):
        """Per-step observability: step-time histogram, tokens/s gauge, and
        achieved MFU = program flops / (step wall time x per-chip peak).
        Program flops are measured ONCE (see _measure_program_flops); the
        peak comes from the device-generation table with a
        `telemetry.peak_tflops` override knob."""
        reg = self.telemetry.registry
        reg.histogram("train/step_time_ms").observe(step_seconds * 1e3)
        tokens = None
        if isinstance(batch, dict):
            t = batch.get("tokens", batch.get("input_ids"))
            if t is not None:
                tokens = int(np.asarray(t).size)
        if tokens:
            reg.gauge("train/tokens_per_sec").set(tokens / step_seconds)
        if self._program_flops is None:
            self._program_flops = self._measure_program_flops(placed, tokens)
        if self._program_flops > 0:
            achieved = self._program_flops / step_seconds   # per-chip FLOPs/s
            reg.gauge("train/tflops_per_chip").set(achieved / 1e12)
            reg.gauge("train/mfu").set(achieved / self.telemetry.peak_flops())
        # device-memory watermarks (best-effort: the CPU harness and some
        # runtimes expose no allocator stats)
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
            for src, dst in (("bytes_in_use", "train/hbm_bytes_in_use"),
                             ("peak_bytes_in_use", "train/hbm_peak_bytes")):
                if src in stats:
                    reg.gauge(dst).set(float(stats[src]))
        except Exception:
            pass
        if self.memscope is not None:
            # mem/* ledger gauges (params/master/opt attribution + program
            # temp once the first batch's shapes are known)
            self.memscope.publish(placed)
        self.telemetry.maybe_export(self.global_steps)

    def _measure_program_flops(self, placed, tokens):
        """The PER-CHIP MFU numerator, decided once at the first instrumented
        step: XLA's cost analysis of the compiled train step (the flops the
        partitioned per-device program actually schedules — one extra AOT
        lowering+compile, same machinery as the flops profiler) when
        `telemetry.measure_program_flops` is on, else the analytic
        6N-model-flops PaLM convention (total-mesh flops, so divided over
        the chips here — both paths return the same unit). Returns 0.0 when
        neither is available so the measurement is never retried per step."""
        flops = 0.0
        if getattr(self.config.telemetry, "measure_program_flops", True) \
                and self._train_step is not None and placed is not None:
            try:
                from deepspeed_tpu.profiling.flops_profiler import cost_analysis
                flops = float(cost_analysis(self._train_step, self.state,
                                            placed).get("flops", 0.0) or 0.0)
            except Exception as e:
                logger.warning(f"telemetry: program cost analysis failed "
                               f"({e}); falling back to 6N model flops")
        if flops <= 0.0 and tokens:
            flops = 6.0 * tree_num_params(self.state.params) * tokens \
                / max(self.mesh.devices.size, 1)
        return flops

    def _recover_bad_state(self, cause):
        """Persistent bad state past the masked skip-step: roll back to the
        last good checkpoint in-process when configured (and possible), else
        raise BadStateError for the supervisor (elasticity/elastic_agent.py)
        to classify and restart on."""
        ft = self.config.fault_tolerance
        detail = self._sentinel.describe(cause)
        target = self._last_ckpt_dir
        # black box FIRST, while the bad state is still in place: the ring
        # (sentinel trips, recent recompiles) + a training-state snapshot
        self.telemetry.flightrec.dump(
            f"bad-state sentinel: {cause}",
            state={"step": self.global_steps, "cause": cause,
                   "detail": detail, "rollbacks": self.rollbacks,
                   "rollback_target": str(target),
                   "watchdog": self.telemetry.watchdog.summary()})
        if ft.auto_rollback and target is not None \
                and self.rollbacks < ft.max_rollbacks:
            logger.warning(f"bad state at step {self.global_steps} ({detail}); "
                           f"rolling back to the last good checkpoint in "
                           f"{target}")
            path, _client = self.load_checkpoint(target)
            if path is not None:
                self.rollbacks += 1
                self._sentinel.reset()
                self._fast_forward_data()
                events = [
                    ("Recovery/rollbacks_total", float(self.rollbacks),
                     self.global_steps),
                    ("Recovery/last_good_step", float(self.global_steps),
                     self.global_steps),
                ]
                self.telemetry.record_events(events)
                if self.monitor is not None and self.monitor.enabled:
                    from deepspeed_tpu.monitor.monitor import write_recovery_events
                    write_recovery_events(self.monitor, events)
                log_dist(f"rollback #{self.rollbacks} complete: resumed at "
                         f"step {self.global_steps} (cause: {cause})", ranks=[0])
                return
            logger.error(f"rollback target {target} had no loadable checkpoint")
        raise BadStateError(cause, f"unrecoverable training state: {detail} "
                                   f"(rollbacks used: {self.rollbacks})")

    def _fast_forward_data(self):
        """Re-align the data pipeline with the restored step after an
        in-process rollback. Stateful loaders (curriculum sampler) restore
        exactly via client_state; the plain loader shuffles per-epoch from
        (seed + epoch), so rewinding its epoch counter to the restored
        step's epoch and skipping `restored_step % len` batches replays the
        exact permutation position the restored state last saw."""
        if self.training_dataloader is None:
            return
        if hasattr(self.training_dataloader, "load_state_dict"):
            return  # position restored from client_state by load_checkpoint
        n = len(self.training_dataloader)
        if n > 0 and hasattr(self.training_dataloader, "epoch"):
            # must be set BEFORE iter(): __iter__ consumes-then-increments it
            self.training_dataloader.epoch = self.global_steps // n
        self._data_iterator = iter(RepeatingLoader(self.training_dataloader))
        if n > 0:
            for _ in range(self.global_steps % n):
                next(self._data_iterator)

    # ------------------------------------------------------------------
    # properties / getters (reference engine surface)
    # ------------------------------------------------------------------

    @property
    def module(self):
        return self.model_spec

    @property
    def params(self):
        return self.state.params

    def get_lr(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler.get_lr()
        lr = self.config.optimizer.params.get("lr", 0.0) if self.config.optimizer else 0.0
        return [lr]

    @property
    def cur_scale(self):
        return float(self.state.scaler.scale)

    def loss_scale(self):
        return self.cur_scale

    @property
    def global_step(self):
        return int(self.state.step)

    def gradient_accumulation_steps(self):
        return self.gradient_accumulation_steps_value

    def train_micro_batch_size_per_gpu(self):
        return self.micro_batch_size

    def train_batch_size(self):
        return self.train_batch_size_value

    def zero_optimization_stage(self):
        return self.zero_stage

    def get_global_grad_norm(self):
        m = self._last_metrics
        return float(m["grad_norm"]) if "grad_norm" in m else None

    def sparse_gradients_enabled(self):
        return bool(self.config.sparse_gradients)

    def sparse_allreduce(self, sparse_tensor, axis=None):
        """Sum a row-sparse (embedding) gradient over the DP axes by exchanging
        (indices, values) instead of the dense buffer (reference
        `sparse_allreduce_no_retain`, engine.py:2427). Accepts a
        `runtime.sparse_tensor.SparseTensor`; see `sparse_embedding_grad` for
        producing one from a loss."""
        from deepspeed_tpu.runtime.sparse_tensor import sparse_all_reduce
        return sparse_all_reduce(sparse_tensor, axis=axis)

    def deepspeed_io(self, dataset, batch_size=None, collate_fn=None, shuffle=True):
        """Build the training dataloader (reference `engine.deepspeed_io`,
        engine.py:1661): global batch = micro_bs × dp × gas per train_batch call.

        When `data_efficiency.data_sampling.curriculum_learning` carries
        `curriculum_metrics` (the v2 metric-driven pipeline), the loader is a
        `CurriculumDataLoader` over a `DeepSpeedDataSampler` that consumes the
        offline DataAnalyzer indexes — each batch draws from the pool of
        samples whose metrics are within the scheduled difficulty (reference
        `data_sampling/data_sampler.py:36`)."""
        bs = batch_size or (self.micro_batch_size * self.spec.data *
                            self.gradient_accumulation_steps_value)
        de = self.config.data_efficiency
        cl = (de.data_sampling or {}).get("curriculum_learning", {}) \
            if de and de.enabled else {}
        # curriculum replaces the SHUFFLED training pass only; shuffle=False
        # (sequential eval/validation) keeps the plain loader — eval must not
        # be difficulty-gated and a differently-sized set would not match the
        # analyzer index anyway
        if shuffle and cl.get("enabled") and cl.get("curriculum_metrics"):
            from deepspeed_tpu.runtime.data_pipeline.data_sampler import \
                DeepSpeedDataSampler
            from deepspeed_tpu.runtime.dataloader import CurriculumDataLoader
            sampler = DeepSpeedDataSampler.from_config(
                len(dataset), bs, cl, seed=self.config.seed)
            return CurriculumDataLoader(dataset, bs, sampler,
                                        collate_fn=collate_fn)
        return TpuDataLoader(dataset, bs, collate_fn=collate_fn, shuffle=shuffle,
                             seed=self.config.seed)

    def _run_flops_profile(self, placed_batch):
        """Cost-analyze the compiled train step and log the profile report."""
        from deepspeed_tpu.profiling.flops_profiler import (FlopsProfiler,
                                                            cost_analysis)
        prof = FlopsProfiler(ds_engine=self)
        try:
            # mirror _run_stateful_step: the eager-streaming offload tier
            # calls the step with device-placed optimizer states
            state = (self._stream_opt_to_device(self.state)
                     if self.offload_optimizer_states and not self._offload_in_jit
                     else self.state)
            prof.analysis = cost_analysis(self._train_step, state, placed_batch)
            fp = self.config.flops_profiler
            arch = getattr(self.model_spec, "arch_cfg", None)
            if arch is not None and hasattr(arch, "n_layer"):
                from deepspeed_tpu.profiling.flops_profiler import \
                    gpt_module_profile
                try:
                    # the tree must describe the step being profiled: use the
                    # actual token length of the placed batch
                    toks = placed_batch.get("tokens",
                                            placed_batch.get("input_ids"))
                    seq = int(toks.shape[-1]) if toks is not None else None
                    prof.set_module_tree(gpt_module_profile(
                        arch, batch_size=self.micro_batch_size, seq_len=seq))
                except Exception as e:
                    logger.warning(f"per-module profile unavailable: {e}")
            prof.print_model_profile(profile_step=self.global_steps + 1,
                                     module_depth=fp.module_depth,
                                     top_modules=fp.top_modules,
                                     detailed=fp.detailed,
                                     output_file=fp.output_file)
        except Exception as e:
            logger.warning(f"flops profiler failed: {e}")
        self._flops_profiler = prof

    def _build_monitor(self):
        try:
            from deepspeed_tpu.monitor.monitor import MonitorMaster
            return MonitorMaster(self.config)
        except Exception as e:
            logger.warning(f"monitor unavailable: {e}")
            return None

    # ------------------------------------------------------------------
    # checkpointing (delegates to deepspeed_tpu.checkpoint)
    # ------------------------------------------------------------------

    def save_checkpoint(self, save_dir, tag=None, client_state=None, save_latest=True,
                        exclude_frozen_parameters=False):
        from deepspeed_tpu.checkpoint.saver import save_checkpoint as _save
        client_state = dict(client_state or {})
        client_state.update({
            "global_steps": self.global_steps,
            "skipped_steps": self.skipped_steps,
            "lr_scheduler": self.lr_scheduler.state_dict() if self.lr_scheduler else None,
        })
        if hasattr(self.training_dataloader, "state_dict"):
            # curriculum sampler position (reference data sampler
            # state_dict/load_state_dict): resume continues the exact
            # difficulty ramp + stateless draw sequence
            client_state["data_sampler"] = self.training_dataloader.state_dict()
        return _save(self, save_dir, tag=tag, client_state=client_state, save_latest=save_latest)

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                        load_optimizer_states=True, load_lr_scheduler_states=True,
                        load_module_only=False):
        from deepspeed_tpu.checkpoint.saver import load_checkpoint as _load
        path, client_state = _load(self, load_dir, tag=tag,
                                   load_optimizer_states=load_optimizer_states,
                                   load_module_only=load_module_only)
        if client_state:
            self.global_steps = client_state.get("global_steps", self.global_steps)
            self.skipped_steps = client_state.get("skipped_steps", self.skipped_steps)
            sd = client_state.get("lr_scheduler")
            if sd and self.lr_scheduler is not None and load_lr_scheduler_states:
                self.lr_scheduler.load_state_dict(sd)
            dsd = client_state.get("data_sampler")
            if dsd and hasattr(self.training_dataloader, "load_state_dict"):
                self.training_dataloader.load_state_dict(dsd)
        if self.compression_steppers:
            # stepper state is DERIVED (masks from params+opt_state, gates
            # from the restored step counter) — recompute instead of
            # serializing device arrays into the checkpoint
            changed = False
            for s in self.compression_steppers:
                if hasattr(s, "on_resume"):
                    changed = bool(s.on_resume(self)) or changed
            if changed:
                self._rebuild_compiled_steps()
        if path is not None:
            self._sentinel.reset()  # restored state gets fresh budgets
        return path, client_state

    def get_fp32_state_dict(self):
        """Gathered fp32 params (analog of `_zero3_consolidated_16bit_state_dict` +
        zero_to_fp32, reference engine.py:3395)."""
        source = self.state.master if self.keep_master else self.state.params
        rep = jax.tree_util.tree_map(lambda _: NamedSharding(self.mesh, P()), source)
        # dstpu: ignore[DT004]: cold consolidation API — a one-shot gather program per call is the point, not a hazard
        gathered = jax.jit(lambda p: tree_cast(p, jnp.float32), out_shardings=rep)(source)
        # dstpu: ignore[DT001]: checkpoint/export boundary — the consolidated fp32 tree is a host artifact
        return jax.device_get(gathered)


# ----------------------------------------------------------------------
# top-level initialize (reference deepspeed/__init__.py:64)
# ----------------------------------------------------------------------


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mesh=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None):
    """Returns (engine, optimizer, training_dataloader, lr_scheduler) — same tuple as
    the reference.

    `model`: a ModelSpec, or a loss callable (then `model_parameters` is the params
    pytree). `config`: dict / JSON path / TpuTrainConfig (falls back to
    `args.deepspeed_config`).
    """
    assert model is not None, "deepspeed_tpu.initialize: model is required"
    if config is None and config_params is not None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None) or getattr(args, "deepscale_config", None)
    cfg = TpuTrainConfig.load(config)

    if hasattr(model, "to_model_spec"):   # e.g. pipe.PipelineModule
        model = model.to_model_spec()
    # ZeRO-Infinity parameter spill in TRAINING (reference: stage 3 +
    # offload_param device cpu/nvme, `zero/stage3.py` + swap_tensor): a
    # LayeredModelSpec routes to the layer-streaming InfinityEngine
    from deepspeed_tpu.inference.zero_inference import LayeredModelSpec
    if isinstance(model, LayeredModelSpec):
        off = cfg.zero_optimization.offload_param
        assert off is not None and off.device in ("cpu", "nvme"), \
            "a LayeredModelSpec trains via the Infinity tier: set " \
            "zero_optimization.offload_param.device to 'cpu' or 'nvme'"
        assert optimizer is None and lr_scheduler is None, \
            "the Infinity tier builds its host optimizers from the config " \
            "(optimizer/scheduler blocks); passing objects is not supported"
        # refuse config the streaming trainer does not honor rather than
        # silently diverging from the reference semantics
        assert model_parameters is None, \
            "Infinity tier: the LayeredModelSpec carries its own params " \
            "(resident + blocks); model_parameters is not honored"
        _, inf_mbs, gas = cfg.resolve_batch_sizes(1)
        from deepspeed_tpu.runtime.infinity import InfinityEngine
        opt_off = cfg.zero_optimization.offload_optimizer
        opt_type = (cfg.optimizer.type.lower() if cfg.optimizer else "adamw")
        host_opt = {"adam": "adam", "adamw": "adam",
                    "deepspeedcpuadam": "adam", "lion": "lion",
                    "deepspeedcpulion": "lion", "adagrad": "adagrad",
                    "deepspeedcpuadagrad": "adagrad"}.get(opt_type)
        assert host_opt is not None, \
            f"Infinity host tier supports adam/adamw/lion/adagrad, not {opt_type}"
        opt_cfg = cfg.optimizer.params if cfg.optimizer else {}
        schedule_fn = lr_schedules.build_schedule(cfg.scheduler)
        inf = InfinityEngine(
            model,
            lr=opt_cfg.get("lr", 1e-3),
            betas=tuple(opt_cfg.get("betas", (0.9, 0.999))),
            eps=opt_cfg.get("eps", 1e-8),
            weight_decay=opt_cfg.get("weight_decay", 0.0),
            dtype=cfg.compute_dtype(),
            offload_device=off.device,
            nvme_path=off.nvme_path,
            optimizer_nvme_path=(opt_off.nvme_path
                                 if opt_off is not None and
                                 opt_off.device == "nvme" else None),
            optimizer=host_opt,
            adamw_mode=(opt_type != "adam"),  # Adam = coupled L2 decay
            lr_schedule=schedule_fn,
            micro_batch_size=inf_mbs,
            gradient_accumulation_steps=gas,
            gradient_clipping=cfg.gradient_clipping,
            training_data=training_data,
            collate_fn=collate_fn,
            seed=cfg.seed,
            # fp16 dynamic loss scaling (reference stage-3 + offload supports
            # it, `zero/stage3.py:1999`): overflow check on the host grad
            # flats, masked skip-step, halve/grow schedule
            fp16=cfg.fp16_enabled,
            static_loss_scale=(None if cfg.fp16.dynamic else
                               cfg.fp16.loss_scale) if cfg.fp16_enabled else None,
            initial_scale_power=cfg.fp16.initial_scale_power,
            loss_scale_window=cfg.fp16.loss_scale_window,
            min_loss_scale=cfg.fp16.min_loss_scale,
            hysteresis=cfg.fp16.hysteresis,
            consecutive_hysteresis=cfg.fp16.consecutive_hysteresis,
            # async staging pool: lookahead (device-ward depth) rides the
            # offload_param block — 0 is the DOCUMENTED blocking baseline,
            # so only None falls back to the default; telemetry enables the
            # offload/* staging metrics; the checkpoint block drives
            # save_checkpoint
            lookahead=int(1 if getattr(off, "lookahead", 1) is None
                          else getattr(off, "lookahead", 1)),
            telemetry=getattr(cfg, "telemetry", None),
            checkpoint=getattr(cfg, "checkpoint", None))
        return inf, None, inf.training_dataloader, None
    if not isinstance(model, ModelSpec):
        assert callable(model), "model must be a ModelSpec or a loss callable"
        assert model_parameters is not None, \
            "when model is a callable, pass model_parameters (a params pytree, " \
            "or an init_fn for construction-time partitioning)"
        if callable(model_parameters):
            # zero.Init ergonomics: params materialize directly into their
            # shards, never whole on the host
            model = ModelSpec(loss_fn=model, init_fn=model_parameters)
        else:
            model = ModelSpec(loss_fn=model, params=model_parameters)

    engine = Engine(model=model,
                    config=cfg,
                    optimizer=optimizer,
                    lr_scheduler=lr_scheduler,
                    training_data=training_data,
                    collate_fn=collate_fn,
                    mesh=mesh)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler
