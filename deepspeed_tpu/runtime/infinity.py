"""ZeRO-Infinity training: train models whose parameters exceed HBM.

Reference: `runtime/swap_tensor/partitioned_param_swapper.py:36` +
`zero/stage3.py` NVMe integration — in training, ZeRO-Infinity keeps the
fp16 parameters AND the fp32 optimizer state on host RAM / NVMe; each layer's
weights stream into device memory right before use (forward and again in
backward), gradients stream out, and the optimizer step runs on host CPU
while the accelerator computes.

TPU-native shape:
  * bit16 working weights live in a `LayerParamStore` (host or NVMe tier);
    `LayerStreamer` double-buffers layer uploads through the forward loop
    and again (reversed) through the backward loop;
  * HBM holds: resident leaves (embed/norms/head), `lookahead+1` layer
    blocks, and the layer-boundary activations [L, B, T, D] — NOT the model;
  * backward is layer-at-a-time `jax.vjp` with in-layer recomputation (the
    boundary activation is the only saved tensor per layer — same memory
    shape as `jax.checkpoint` full remat);
  * each layer's gradient is fetched to host and fed to a per-layer
    `HostOffloadOptimizer` (the C++ OpenMP Adam, `csrc/cpu_optim`) whose
    fp32 master + moments never touch the device; the updated bit16 layer
    is written straight back to the store (the reference's swap-out);
  * one jitted block fn + one jitted block-vjp serve every layer.

This is the capability the reference's "train/serve models 10-100x beyond
device memory" claims rest on; the inference half lives in
`inference/zero_inference.py`.
"""

import types

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.cpu_optimizer import HostOffloadOptimizer
from deepspeed_tpu.runtime.offload_staging import HostwardPipe
from deepspeed_tpu.runtime.param_swap import LayerParamStore, LayerStreamer
from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.utils.tree import tree_cast


class InfinityEngine:
    """Layer-streaming trainer over a LayeredModelSpec (train fns required).

    `offload_device`: "cpu" | "nvme" for the bit16 weights;
    `optimizer_nvme_path`: optionally push the per-layer Adam moments to
    NVMe too (the full ZeRO-Infinity tier);
    `lookahead`: staging depth of the async double-buffered upload pool
    (0 = the blocking baseline — every layer acquisition stalls);
    `landing_depth`: how many layers' grad flats may be in device->host
    flight at once (the backward-direction half of the overlap);
    `telemetry`: a TelemetryConfig — enables the `offload/*` staging
    metrics (stage-wait, occupancy, in-flight bytes) and per-step export;
    `checkpoint`: a CheckpointConfig for `save_checkpoint` (engine,
    keep_last_n, checksum verification — checkpoint/saver.py)."""

    def __init__(self, spec, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, dtype=jnp.bfloat16, offload_device="cpu",
                 nvme_path=None, optimizer_nvme_path=None, lookahead=1,
                 optimizer="adam", adamw_mode=True, lr_schedule=None,
                 micro_batch_size=None, gradient_accumulation_steps=1,
                 gradient_clipping=0.0, training_data=None, collate_fn=None,
                 seed=1234, fp16=False, static_loss_scale=None,
                 initial_scale_power=16, loss_scale_window=1000,
                 min_loss_scale=1.0, hysteresis=2,
                 consecutive_hysteresis=False, landing_depth=None,
                 max_write_bytes=None, telemetry=None, checkpoint=None):
        assert spec.layer_train_fn is not None and spec.train_loss_fn is not None, \
            "InfinityEngine needs a LayeredModelSpec with train fns " \
            "(models.gpt.make_gpt_layered_model provides them)"
        self.spec = spec
        self.micro_batch_size = micro_batch_size
        self.gas = max(1, int(gradient_accumulation_steps))
        self.dtype = jnp.dtype(dtype)
        from deepspeed_tpu.telemetry import Telemetry
        self.telemetry = Telemetry(telemetry, subsystem="infinity")
        # minimal config surface for checkpoint/saver.py's free functions
        # (engine.config.checkpoint drives the checkpoint-engine choice;
        # this tier's state is a host-side numpy pytree, so default to the
        # npz engine rather than orbax)
        self.config = types.SimpleNamespace(
            checkpoint=(checkpoint if checkpoint is not None else
                        types.SimpleNamespace(engine="numpy",
                                              async_save=False)),
            telemetry=telemetry)
        self.monitor = None
        self.resident = jax.device_put(tree_cast(spec.resident, self.dtype))
        self.store = LayerParamStore(tree_cast(spec.blocks, self.dtype),
                                     device=offload_device,
                                     swap_folder=nvme_path,
                                     max_write_bytes=max_write_bytes)
        self.store.telemetry = self.telemetry
        self.streamer = LayerStreamer(self.store, lookahead=lookahead,
                                      telemetry=self.telemetry)
        self.landing_depth = max(1, int(landing_depth
                                        if landing_depth is not None
                                        else max(1, lookahead)))
        # hostward (grad-landing) stall accounting across the per-pass
        # pipes — the bench lane's stall fraction includes BOTH directions
        self.hostward_wait_ms_total = 0.0
        self.hostward_bytes_total = 0
        self.L = self.store.num_layers

        # fp32 masters + moments on host, one optimizer per layer + resident.
        # Masters come straight from spec.blocks (full init precision, no
        # store round-trip — on the nvme tier that would be a whole-model
        # write-then-read before step 0, and fp32(bit16(w)) would lose the
        # init's low bits).
        opt_kw = dict(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
                      optimizer=optimizer, adamw_mode=adamw_mode,
                      lr_schedule=lr_schedule)
        # per-layer slicing INSIDE the loop: at most one extra layer of fp32
        # exists transiently (the tier exists because the model exceeds
        # memory; a list of all slices would peak at ~2x whole-model fp32
        # on top of the optimizers' own master copies)
        block_leaves = jax.tree_util.tree_leaves(spec.blocks)
        self.layer_opts = []
        for i in range(self.L):
            layer_i = jax.tree_util.tree_unflatten(
                self.store.treedef,
                [np.asarray(l[i], np.float32) for l in block_leaves])
            self.layer_opts.append(HostOffloadOptimizer(
                layer_i,
                nvme_folder=(f"{optimizer_nvme_path}/layer{i}"
                             if optimizer_nvme_path else None), **opt_kw))
            del layer_i
        self.resident_opt = HostOffloadOptimizer(
            # dstpu: ignore[DT001]: tier build, runs once — the resident host master starts from a device pull
            jax.device_get(tree_cast(spec.resident, jnp.float32)),
            nvme_folder=(f"{optimizer_nvme_path}/resident"
                         if optimizer_nvme_path else None), **opt_kw)

        # fp16 dynamic loss scaling (VERDICT r4 item 6 — reference supports
        # stage-3 + offload with dynamic scaling, `zero/stage3.py:1999`).
        # The scale rides the head-VJP seed (grads leave the device
        # pre-multiplied; the returned loss stays unscaled), the host divides
        # it back out of the grad flats, and the all-finite check runs on the
        # host flats BEFORE any layer's optimizer steps — fp16 therefore
        # forces the two-phase (accumulate-then-step) schedule, trading the
        # backward/step overlap for skip-step correctness, exactly like
        # gradient clipping does. The schedule itself is the shared
        # `precision.LossScaler` (hysteresis, window, min scale — one
        # implementation for both tiers), driven eagerly here.
        from deepspeed_tpu.runtime.precision import LossScaler
        self.fp16 = bool(fp16)
        self._scaler = LossScaler(static_scale=static_loss_scale,
                                  initial_scale_power=initial_scale_power,
                                  loss_scale_window=loss_scale_window,
                                  hysteresis=hysteresis,
                                  consecutive_hysteresis=consecutive_hysteresis,
                                  min_loss_scale=min_loss_scale,
                                  enabled=self.fp16)
        self._scale_state = self._scaler.init()  # scale == 1.0 when disabled

        layer_fn = spec.layer_train_fn
        loss_fn = spec.train_loss_fn

        self._block = jax.jit(layer_fn)

        def block_vjp(p, x_in, positions, g_out):
            _, pull = jax.vjp(lambda p_, x_: layer_fn(p_, x_, positions),
                              p, x_in)
            g_p, g_x = pull(g_out)
            return g_p, g_x

        self._block_vjp = jax.jit(block_vjp)

        def head(res, x, labels, seed):
            loss, pull = jax.vjp(lambda r, x_: loss_fn(r, x_, labels), res, x)
            # the loss-scale rides the VJP seed: grads leave pre-multiplied,
            # the RETURNED loss stays unscaled
            g_res, g_x = pull(jnp.asarray(seed, loss.dtype))
            return loss, g_res, g_x

        self._head = jax.jit(head)

        def embed_vjp(res, toks, positions, g_x0):
            _, pull = jax.vjp(lambda r: spec.embed_fn(r, toks, positions), res)
            (g_res,) = pull(g_x0)
            return g_res

        self._embed = jax.jit(spec.embed_fn)
        self._embed_vjp = jax.jit(embed_vjp)
        self._add = jax.jit(lambda a, b: jax.tree_util.tree_map(
            lambda x, y: x + y, a, b))
        # grads leave the device as ONE fused fp32 vector per tree: a single
        # large transfer is both faster through a tunneled runtime and avoids
        # the flaky many-small-buffer fetch observed there (one layer's grads
        # arriving garbled -> NaN masters a few steps in)
        self._flatten = jax.jit(lambda tree: jnp.concatenate(
            [jnp.ravel(l).astype(jnp.float32)
             for l in jax.tree_util.tree_leaves(tree)]))
        self.gradient_clipping = float(gradient_clipping or 0.0)
        self.last_grad_norm = None
        # dataloader (reference engine training_data contract): batches of
        # micro_batch x gas rows per train_batch() call
        self.training_dataloader = None
        self._data_iterator = None
        if training_data is not None:
            from deepspeed_tpu.runtime.dataloader import TpuDataLoader
            bs = (micro_batch_size or 1) * self.gas
            self.training_dataloader = TpuDataLoader(
                training_data, bs, collate_fn=collate_fn, shuffle=True,
                seed=seed)
        self.step_count = 0
        log_dist(f"infinity engine: {spec.name} L={self.L} "
                 f"layer_mb={self.store.layer_bytes/1e6:.1f} "
                 f"weights={offload_device} "
                 f"opt={'nvme' if optimizer_nvme_path else 'host'}", ranks=[0])

    @property
    def cur_scale(self):
        """Current loss scale (reference `engine.cur_scale` spelling)."""
        return float(self._scale_state.scale)

    @cur_scale.setter
    def cur_scale(self, value):
        self._scale_state = self._scale_state._replace(
            scale=jnp.asarray(float(value), jnp.float32))

    @property
    def skipped_steps(self):
        return int(self._scale_state.overflows)

    @staticmethod
    def _unflatten_host(flat, shapes):
        out, off = [], 0
        for shape in shapes:
            n = int(np.prod(shape)) if shape else 1
            out.append(np.asarray(flat[off:off + n]).reshape(shape))
            off += n
        return out

    def _layer_step_host(self, i, flat):
        """Host optimizer step for layer i from a host fp32 grad flat; bit16
        write-back to the store (async under the store's write budget — the
        disk write of layer i overlaps layer i-1's backward)."""
        g_host = self._unflatten_host(flat, [s for s, _ in self.store.leaf_meta])
        g_tree = jax.tree_util.tree_unflatten(self.store.treedef, g_host)
        new_master = self.layer_opts[i].step(g_tree)
        self.store.put(i, [np.asarray(l).astype(self.store.leaf_meta[j][1])
                           for j, l in enumerate(
                               jax.tree_util.tree_leaves(new_master))])

    def _micro_pass(self, inputs, labels, acc, res_acc, mode):
        """One micro-batch forward+backward. `mode`:
        "apply"      — gas==1: each layer's host Adam runs overlapped inside
                       the backward loop;
        "accumulate" — non-final gas micro: host grad flats accumulate into
                       `acc`/`res_acc` (weights stay constant, as
                       accumulation semantics require);
        "finalize"   — FINAL gas micro: each layer's mean grad
                       (acc[i]+flat)/gas steps the host Adam inside the same
                       overlapped pipeline, and acc[i] is freed as consumed —
                       overlap is preserved and accumulator memory falls
                       layer by layer through the last backward."""
        B, T = inputs.shape
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None],
                                     (B, T))
        x = self._embed(self.resident, inputs, positions)
        boundaries = []
        for i in range(self.L):
            boundaries.append(x)
            x = self._block(self.streamer.layer(i), x, positions)

        loss, g_res, g_x = self._head(self.resident, x, labels,
                                      jnp.asarray(self.cur_scale, jnp.float32))

        # backward: stream layers in reverse. No reset first: layer L-1's
        # device copy from the forward is exactly what the backward needs;
        # the direction-aware eviction window handles the turn-around.
        # Layer i's grad flat is submitted to the hostward pipe the moment
        # its vjp is enqueued — copy_to_host_async dispatches the D2H copy
        # behind it — and lands `landing_depth` layers later, so the host
        # optimizer (and the write-back) overlaps the device backward while
        # the transfer itself overlaps the NEXT layer's vjp (the tier's
        # raison d'etre; a late transfer's stall is measured in
        # offload/hostward_wait_ms, not hidden).
        pipe = HostwardPipe(depth=self.landing_depth,
                            telemetry=self.telemetry)
        for i in reversed(range(self.L)):
            p = self.streamer.layer(i, direction=-1)
            g_p, g_x = self._block_vjp(p, boundaries[i], positions, g_x)
            for k, flat in pipe.submit(i, self._flatten(g_p)):
                self._consume(acc, mode, k, flat)
        for k, flat in pipe.drain():
            self._consume(acc, mode, k, flat)
        self.hostward_wait_ms_total += pipe.wait_ms_total
        self.hostward_bytes_total += pipe.bytes_total

        g_res = self._add(g_res, self._embed_vjp(self.resident, inputs,
                                                 positions, g_x))
        # dstpu: ignore[DT001]: ZeRO-Infinity tier — the resident grad flat accumulates in host RAM by design
        res_flat = np.asarray(jax.device_get(self._flatten(g_res)))
        if res_acc is None:
            res_acc = res_flat.copy()  # device_get arrays are read-only
        else:
            res_acc += res_flat
        return float(loss), res_acc

    def _consume(self, acc, mode, i, flat):
        """Consume layer i's LANDED host grad flat (the hostward pipe did
        the device->host transfer asynchronously)."""
        if mode == "apply":
            self._layer_step_host(i, flat)
            return
        if mode == "finalize":
            mean = (acc[i] + flat) / self.gas
            acc[i] = None  # accumulator memory falls as the backward proceeds
            self._layer_step_host(i, mean)
        elif acc[i] is None:
            acc[i] = flat.copy()  # landed arrays are read-only views
        else:
            acc[i] += flat

    def train_batch(self, batch=None, data_iter=None):
        """One full step over the GLOBAL batch (micro_batch x gas rows, like
        the main engine): streamed forward/backward per micro-batch, host
        optimizer steps on the mean gradient at the gas boundary, bit16
        write-back, resident update last. Returns the mean loss.

        With `gradient_clipping` set, the step runs in two phases: grads
        accumulate on host through every micro-pass; once the backward
        completes, the per-layer norms² are summed into the GLOBAL norm and
        the host Adam steps apply the clip scale layer by layer. The cost: the
        optimizer work no longer overlaps the device backward (the scale
        depends on every layer's grad) — correctness over overlap when
        clipping is requested (reference stage-3 + offload clips the same
        global norm)."""
        if batch is None:
            it = data_iter
            if it is None and self.training_dataloader is not None:
                if self._data_iterator is None:
                    from deepspeed_tpu.runtime.dataloader import RepeatingLoader
                    self._data_iterator = iter(
                        RepeatingLoader(self.training_dataloader))
                it = self._data_iterator
            assert it is not None, \
                "train_batch needs a batch or data_iter/training_data"
            batch = next(it)
        tokens = np.asarray(batch.get("tokens", batch.get("input_ids")))
        labels = batch.get("labels")
        if labels is None:
            inputs, labels = tokens[:, :-1], tokens[:, 1:]
        else:
            inputs = tokens
        inputs = jnp.asarray(inputs, jnp.int32)
        labels = jnp.asarray(labels, jnp.int32)
        B, T = inputs.shape
        assert B % self.gas == 0, (
            f"global batch {B} not divisible by "
            f"gradient_accumulation_steps={self.gas}")
        mbs = B // self.gas
        if self.micro_batch_size is not None:
            assert mbs == self.micro_batch_size, (
                f"global batch of {B} with gas={self.gas} implies micro "
                f"batch {mbs}, engine configured for {self.micro_batch_size}")

        clip = self.gradient_clipping
        # two-phase (accumulate, then step): needed whenever NO update may
        # run before a whole-model property of the grads is known — the
        # global norm for clipping, all-finiteness for the fp16 skip-step
        two_phase = clip > 0 or self.fp16
        acc = [None] * self.L
        res_acc = None
        losses = []
        for m in range(self.gas):
            sl = slice(m * mbs, (m + 1) * mbs)
            if two_phase:
                mode = "accumulate"
            elif self.gas == 1:
                mode = "apply"
            else:
                mode = "finalize" if m == self.gas - 1 else "accumulate"
            loss, res_acc = self._micro_pass(inputs[sl], labels[sl], acc,
                                             res_acc, mode)
            losses.append(loss)
        loss = float(np.mean(losses))

        # the scale the micro-passes SEEDED their VJPs with — snapshot before
        # the scaler update mutates it (unscaling with a grown scale would
        # silently shrink one update per window)
        used_scale = self.cur_scale
        if self.fp16:
            # host-side all-finite check on the (still scale-multiplied) grad
            # flats BEFORE any optimizer state or stored weight changes —
            # reference FP16_Optimizer.step overflow semantics; the halve /
            # hysteresis / window-grow schedule is the shared LossScaler
            finite = bool(np.isfinite(res_acc).all()) and all(
                bool(np.isfinite(a).all()) for a in acc)
            self._scale_state = self._scaler.update(
                self._scale_state, jnp.asarray(finite))
            if not finite:
                log_dist(f"fp16 overflow: step skipped, "
                         f"loss scale -> {self.cur_scale:.1f}", ranks=[0])
                self.streamer.reset()
                return float(loss)

        # mean grads carry gas micro-passes AND the fp16 loss scale
        denom = self.gas * used_scale
        g_res_flat = res_acc / denom

        scale = 1.0
        if two_phase:
            if clip > 0:
                sq = float(np.dot(g_res_flat, g_res_flat))
                for i in range(self.L):
                    mean_i = acc[i] / denom
                    sq += float(np.dot(mean_i, mean_i))
                total_norm = float(np.sqrt(sq))
                self.last_grad_norm = total_norm
                scale = min(1.0, clip / max(total_norm, 1e-12))
            for i in range(self.L):
                self._layer_step_host(i, acc[i] * (scale / denom))
                acc[i] = None
            g_res_flat = g_res_flat * scale

        self.streamer.reset()  # device copies are stale after write-back
        self.store.flush_writes()  # one barrier per step, not per layer

        res_leaves = jax.tree_util.tree_leaves(self.resident)
        g_res_host = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(self.resident),
            self._unflatten_host(g_res_flat,
                                 [l.shape for l in res_leaves]))
        new_res_master = self.resident_opt.step(g_res_host)
        self.resident = jax.device_put(tree_cast(new_res_master, self.dtype))
        self.step_count += 1
        self.telemetry.maybe_export(self.step_count)
        return float(loss)

    @property
    def peak_param_hbm_bytes(self):
        return self.streamer.peak_live_layers * self.store.layer_bytes

    def offload_stats(self):
        """Host-side overlap counters for the bench offload lane,
        available with telemetry off. The two directions are reported
        SEPARATELY on purpose: `staging.stall_ms_total` (device-ward) is
        a pure transfer-lateness signal — acquiring a layer never waits
        on compute — while `hostward_wait_ms_total` is measured at the
        host's one sync point with the device stream per layer, so it
        includes the producing vjp's in-flight compute by construction;
        summing them into one "stall" would double-count compute as
        transfer."""
        return {"staging": self.streamer.stats(),
                "hostward_wait_ms_total": round(self.hostward_wait_ms_total,
                                                3),
                "hostward_bytes_total": self.hostward_bytes_total,
                "write_flushes": self.store.write_flushes,
                "landing_depth": self.landing_depth,
                "lookahead": self.streamer.lookahead}

    def memory_plan(self, capacity_bytes=0):
        """The memscope training plan priced from THE LIVE TIER: the host
        params column is byte-identical to the `LayerParamStore`, the
        device staging column to the streamer's `lookahead+1` window
        (telemetry/memscope.py `plan_training_from_infinity`)."""
        from deepspeed_tpu.telemetry.memscope import plan_training_from_infinity
        return plan_training_from_infinity(self, capacity_bytes=capacity_bytes)

    # ---- checkpointing (checkpoint/saver.py free functions; the commit
    # protocol, validated rollback-walking loads, retention and the fault
    # hooks all come from there — this tier only defines what "state" is) --

    @property
    def global_steps(self):
        return self.step_count

    @property
    def state(self):
        """Host snapshot pytree: fp32 masters + moments + loss-scale
        bookkeeping. The bit16 store is DERIVED state (bit16(master)) —
        rebuilt by the setter on load, so a checkpoint holds one copy of
        the truth and never needs to read the (possibly disk-resident)
        store."""
        return {"layer_opts": [o.state_dict() for o in self.layer_opts],
                "resident_opt": self.resident_opt.state_dict(),
                "step": int(self.step_count),
                "scale": float(self.cur_scale),
                "good_steps": int(self._scale_state.good_steps),
                "overflows": int(self._scale_state.overflows),
                "hysteresis_left": int(self._scale_state.hysteresis_left)}

    @state.setter
    def state(self, s):
        for i, sd in enumerate(s["layer_opts"]):
            opt = self.layer_opts[i]
            opt.load_state_dict(sd)
            # bit16 write-back: the store content is derived from the master
            self.store.put(i, [np.asarray(l).astype(self.store.leaf_meta[j][1])
                               for j, l in enumerate(opt.master)])
        self.store.flush_writes()
        self.resident_opt.load_state_dict(s["resident_opt"])
        res_master = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(self.resident),
            self.resident_opt.master)
        self.resident = jax.device_put(tree_cast(res_master, self.dtype))
        self.streamer.reset()           # device copies are stale
        self.step_count = int(np.asarray(s["step"]))
        from deepspeed_tpu.runtime.precision import LossScaleState
        self._scale_state = LossScaleState(
            scale=jnp.asarray(float(np.asarray(s["scale"])), jnp.float32),
            good_steps=jnp.asarray(int(np.asarray(s["good_steps"])), jnp.int32),
            overflows=jnp.asarray(int(np.asarray(s["overflows"])), jnp.int32),
            hysteresis_left=jnp.asarray(
                int(np.asarray(s["hysteresis_left"])), jnp.int32))

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True):
        """Atomic-commit checkpoint of the tier's host state (PR 2
        protocol: stage -> manifest -> rename-commit -> latest). The async
        write-back queue is flushed FIRST: a snapshot must never race its
        own in-flight disk writes — that ordering is what keeps a mid-step
        crash during async write-back recoverable (the manifest only ever
        describes a quiesced store)."""
        self.store.flush_writes()
        from deepspeed_tpu.checkpoint import saver
        client = dict(client_state or {})
        client.setdefault("global_steps", int(self.step_count))
        return saver.save_checkpoint(self, save_dir, tag=tag,
                                     client_state=client,
                                     save_latest=save_latest)

    def load_checkpoint(self, load_dir, tag=None):
        """Validated restore with the corruption rollback walk
        (checkpoint/saver.py): checksum-verified manifest, newest good tag
        wins. Full-state loads only — this tier's masters/moments ARE the
        model, partial loads have nothing to stand on."""
        from deepspeed_tpu.checkpoint import saver
        return saver.load_checkpoint(self, load_dir, tag=tag)

    def release(self):
        self.telemetry.close()
        self.store.release()
