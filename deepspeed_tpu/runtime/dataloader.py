"""Data loading — analog of `runtime/dataloader.py` (`DeepSpeedDataLoader`,
`RepeatingLoader`).

The engine consumes batches of numpy/jax arrays (pytrees). `TpuDataLoader` slices
an indexable dataset into global batches of `micro_batch_size × data_parallel_size`
samples; in multi-host runs each process loads the full global batch and
`jax.device_put` with a data-axis sharding keeps only the local shard resident
(`jax.make_array_from_process_local_data` territory — single-host covers this
round's scope).
"""

import numpy as np

from deepspeed_tpu.utils.logging import logger


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference same name)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __len__(self):
        return len(self.loader)

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([s[i] for s in samples]) for i in range(len(first)))
    return np.stack(samples)


class CurriculumDataLoader:
    """Difficulty-driven loader: each batch's sample indices come from a
    `DeepSpeedDataSampler` (metric-index curriculum) instead of a shuffle —
    the loader-level analog of the reference's sampler-in-DataLoader wiring
    (`data_pipeline/data_sampling/data_sampler.py:36` consumed via
    `engine.deepspeed_io`). One "epoch" yields dataset_len // batch_size
    batches; the sampler's step advances monotonically across epochs so the
    difficulty ramp never resets."""

    def __init__(self, dataset, batch_size, sampler, collate_fn=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = sampler
        self.collate_fn = collate_fn or _default_collate

    def __len__(self):
        return max(len(self.dataset) // self.batch_size, 1)

    def __iter__(self):
        for _ in range(len(self)):
            idx = self.sampler.next_indices()
            yield self.collate_fn([self.dataset[int(i)] for i in idx])

    def state_dict(self):
        return self.sampler.state_dict()

    def load_state_dict(self, sd):
        self.sampler.load_state_dict(sd)


class TpuDataLoader:
    """Batches an indexable dataset; drops the ragged tail (matching drop_last)."""

    def __init__(self, dataset, batch_size, collate_fn=None, shuffle=False, seed=0, drop_last=True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0

    def __len__(self):
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        self.epoch += 1
        for start in range(0, n - (self.batch_size - 1 if self.drop_last else 0), self.batch_size):
            idx = order[start:start + self.batch_size]
            yield self.collate_fn([self.dataset[int(i)] for i in idx])
