"""Quantized collectives — ZeRO++ qwZ / qgZ, TPU-native.

Reference:
  * qwZ — quantized-weight all-gather: `CUDAQuantizer` int8 blockwise quant
    before the stage-3 param all-gather (`zero/partition_parameters.py:668`,
    enabled by `zero_quantized_weights`, `zero/config.py:260`).
  * qgZ — quantized-gradient reduce: `all_to_all_quant_reduce`
    (`runtime/comm/coalesced_collectives.py:31`) — int8 swizzle-quant →
    intra-node all-to-all → dequant-reduce → inter-node hop.

TPU realization: the collectives become explicit `shard_map` bodies over the
ZeRO mesh axes. The int8 payload + f32 group scales travel over ICI (4x less
bandwidth than bf16 for the payload); dequantization happens on the receiver and
reduction is always in f32 (error stays bounded by one quantization step per
hop, same as the reference's scheme).

The quantization rule itself (groupwise symmetric int8, scale = max|x|/127)
has ONE definition: `comm/collectives.py`'s `group_quant_int8` — the same
semantics `ops/pallas/quant.py` implements on-chip — and every wire hop here
goes through the comm facade's instrumented primitives, so per-op byte stats
(`comm/*` telemetry) accrue under the engine's quantized step for free.

These primitives are used by the engine's quantized step variant
(`zero_quantized_weights` / `zero_quantized_gradients` config knobs) and are
directly usable inside any shard_map body.
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.comm import collectives as coll
from deepspeed_tpu.comm.collectives import (group_dequant_int8 as _group_dequant,
                                            group_quant_int8 as _group_quant)


def quantized_all_gather(x, axis_name, group_size=256):
    """qwZ all-gather: int8 payload over the wire (inside shard_map).

    x: local shard [...]. Returns the concatenated global array along axis 0,
    dequantized to x.dtype.
    """
    deq = coll.transform_all_gather(x.reshape(-1), axis_name, "int8",
                                    group_size)        # [n, numel]
    n = deq.shape[0]
    return deq.reshape((n * x.shape[0],) + x.shape[1:])


def quantized_reduce_scatter(x, axis_name, group_size=256):
    """qgZ reduce-scatter: each rank ends with its reduced shard of axis 0.

    x: full-size local contribution [N, ...] with N divisible by the axis size.
    int8 all-to-all moves every rank's chunk-for-rank-j to rank j; receivers
    dequantize and reduce in f32 (the reference's dequant-reduce,
    `coalesced_collectives.py:39-71`). Returns [N/n, ...] in f32.
    """
    n = jax.lax.psum(1, axis_name)
    N = x.shape[0]
    if N % n != 0:
        raise ValueError(f"leading dim {N} not divisible by axis size {n}")
    flat = x.reshape(N, -1).reshape(-1)
    total = coll.transform_reduce_scatter(flat, axis_name, "int8", group_size)
    return total.reshape((N // n,) + x.shape[1:])


def quantized_psum_scatter_mean(x, axis_name, group_size=256):
    """quantized_reduce_scatter / axis size (mean semantics for grad averaging)."""
    n = jax.lax.psum(1, axis_name)
    return quantized_reduce_scatter(x, axis_name, group_size) / n


def qgz_allreduce(x, axis_name, group_size=256):
    """qgZ all-reduce: int8 reduce-scatter + int8 all-gather (two quantized hops,
    the reference's 2-hop scheme, `coalesced_collectives.py:31-71`).

    x: any-shape local contribution; returns the sum over the axis, replicated,
    in f32. Pads the flat payload to a multiple of the axis size.
    """
    return coll.compressed_all_reduce(x, axis_name, transform="int8",
                                      group_size=group_size)


def quantized_all_gather_dim(x, axis_name, dim, group_size=256):
    """qwZ all-gather of a leaf sharded on dimension `dim` (inside shard_map):
    int8 payload over the wire, reconstructs the full array in x.dtype."""
    shard_shape = x.shape
    deq = coll.transform_all_gather(x.reshape(-1), axis_name, "int8",
                                    group_size)        # [n, numel]
    n = deq.shape[0]
    arr = deq.reshape((n,) + shard_shape)
    arr = jnp.moveaxis(arr, 0, dim)                   # [..., n, k, ...]
    new_shape = shard_shape[:dim] + (n * shard_shape[dim],) + shard_shape[dim + 1:]
    return arr.reshape(new_shape)
