"""Quantized collectives — ZeRO++ qwZ / qgZ, TPU-native.

Reference:
  * qwZ — quantized-weight all-gather: `CUDAQuantizer` int8 blockwise quant
    before the stage-3 param all-gather (`zero/partition_parameters.py:668`,
    enabled by `zero_quantized_weights`, `zero/config.py:260`).
  * qgZ — quantized-gradient reduce: `all_to_all_quant_reduce`
    (`runtime/comm/coalesced_collectives.py:31`) — int8 swizzle-quant →
    intra-node all-to-all → dequant-reduce → inter-node hop.

TPU realization: the collectives become explicit `shard_map` bodies over the
ZeRO mesh axes. The int8 payload + f32 group scales travel over ICI (4x less
bandwidth than bf16 for the payload); dequantization happens on the receiver and
reduction is always in f32 (error stays bounded by one quantization step per
hop, same as the reference's scheme).

These primitives are used by the engine's quantized step variant
(`zero_quantized_weights` / `zero_quantized_gradients` config knobs) and are
directly usable inside any shard_map body.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.comm import mesh as mesh_mod


def _group_quant(x, group_size):
    """x: [..., D] → (int8 [..., D], f32 scales [..., D//group_size])."""
    D = x.shape[-1]
    g = max(1, D // group_size) if D % group_size == 0 else 1
    gs = D // g
    xg = x.astype(jnp.float32).reshape(x.shape[:-1] + (g, gs))
    amax = jnp.max(jnp.abs(xg), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xg / scale[..., None]), -127, 127).astype(jnp.int8)
    return q.reshape(x.shape), scale


def _group_dequant(q, scale, dtype):
    D = q.shape[-1]
    g = scale.shape[-1]
    gs = D // g
    x = q.astype(jnp.float32).reshape(q.shape[:-1] + (g, gs)) * scale[..., None]
    return x.reshape(q.shape).astype(dtype)


def quantized_all_gather(x, axis_name, group_size=256):
    """qwZ all-gather: int8 payload over the wire (inside shard_map).

    x: local shard [...]. Returns the concatenated global array along axis 0,
    dequantized to x.dtype.
    """
    flat = x.reshape(-1)
    q, scale = _group_quant(flat, group_size)
    q_all = jax.lax.all_gather(q, axis_name)          # [n, numel] int8
    s_all = jax.lax.all_gather(scale, axis_name)      # [n, groups] f32
    deq = _group_dequant(q_all, s_all, x.dtype)       # [n, numel]
    n = deq.shape[0]
    return deq.reshape((n * x.shape[0],) + x.shape[1:])


def quantized_reduce_scatter(x, axis_name, group_size=256):
    """qgZ reduce-scatter: each rank ends with its reduced shard of axis 0.

    x: full-size local contribution [N, ...] with N divisible by the axis size.
    int8 all-to-all moves every rank's chunk-for-rank-j to rank j; receivers
    dequantize and reduce in f32 (the reference's dequant-reduce,
    `coalesced_collectives.py:39-71`). Returns [N/n, ...] in f32.
    """
    n = jax.lax.psum(1, axis_name)
    N = x.shape[0]
    assert N % n == 0, f"leading dim {N} not divisible by axis size {n}"
    chunks = x.reshape((n, N // n) + x.shape[1:])
    flat = chunks.reshape(n, -1)
    q, scale = _group_quant(flat, group_size)
    # all_to_all: split axis 0 (the chunk-owner dim), concat received on new axis
    q_recv = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                                tiled=False)
    s_recv = jax.lax.all_to_all(scale, axis_name, split_axis=0, concat_axis=0,
                                tiled=False)
    deq = _group_dequant(q_recv, s_recv, jnp.float32)   # [n, chunk_numel]
    total = jnp.sum(deq, axis=0)                        # reduce contributions
    return total.reshape((N // n,) + x.shape[1:])


def quantized_psum_scatter_mean(x, axis_name, group_size=256):
    """quantized_reduce_scatter / axis size (mean semantics for grad averaging)."""
    n = jax.lax.psum(1, axis_name)
    return quantized_reduce_scatter(x, axis_name, group_size) / n


def qgz_allreduce(x, axis_name, group_size=256):
    """qgZ all-reduce: int8 reduce-scatter + int8 all-gather (two quantized hops,
    the reference's 2-hop scheme, `coalesced_collectives.py:31-71`).

    x: any-shape local contribution; returns the sum over the axis, replicated,
    in f32. Pads the flat payload to a multiple of the axis size.
    """
    n = jax.lax.psum(1, axis_name)
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    numel = flat.shape[0]
    pad = (-numel) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    mine = quantized_reduce_scatter(flat, axis_name, group_size)
    # second hop: gather the reduced shards back (int8 wire again)
    full = quantized_all_gather(mine, axis_name, group_size)
    return full[:numel].reshape(shape)


def quantized_all_gather_dim(x, axis_name, dim, group_size=256):
    """qwZ all-gather of a leaf sharded on dimension `dim` (inside shard_map):
    int8 payload over the wire, reconstructs the full array in x.dtype."""
    n = jax.lax.psum(1, axis_name)
    shard_shape = x.shape
    flat = x.reshape(-1)
    q, scale = _group_quant(flat, group_size)
    q_all = jax.lax.all_gather(q, axis_name)
    s_all = jax.lax.all_gather(scale, axis_name)
    deq = _group_dequant(q_all, s_all, x.dtype)       # [n, numel]
    arr = deq.reshape((n,) + shard_shape)
    arr = jnp.moveaxis(arr, 0, dim)                   # [..., n, k, ...]
    new_shape = shard_shape[:dim] + (n * shard_shape[dim],) + shard_shape[dim + 1:]
    return arr.reshape(new_shape)
