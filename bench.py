"""Benchmark: GPT-2 bf16 training step throughput on the local chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

`vs_baseline` compares "how well each framework drives its own silicon" —
our model-flops utilization (MFU) over the reference's best published GPT
MFU on A100 — computed on the SAME flops convention for both sides.

The reference's 204.49 TFLOPs/GPU (`docs/_posts/2022-07-26-deepspeed-azure.md:97`)
is computed with the Megatron-paper formula stated in that same post
(`:91-93`): 96*B*s*l*h^2*(1 + s/6h + V/16lh) — the factor-8 "hardware flops"
convention that counts the full activation-checkpointing forward recompute
as throughput (8 = 2 fwd + 4 bwd + 2 recompute passes per matmul; the
model-flops version of the identical formula is 72*... = factor 6). Our
bench reports strict 6N model flops (no recompute credit — we use selective
remat precisely so most of the recompute never happens). Comparing our 6N
MFU against their factor-8 number would hand the reference a free 33%:
  reference, model-flops convention: 204.49 * 6/8 = 153.4 TF / 312 peak = 0.4916
  (at 175B the formula's attention/vocab correction terms are <1%, so the
  6/8 rescale is exact to 3 digits)
So vs_baseline = our_6N_mfu / 0.4916. Both conventions are reported in
`extra`: `mfu` (6N, the honest one — excludes our remat recompute AND the
attention einsums) and `mfu_megatron` (their factor-8 formula applied to our
run verbatim, for a like-for-like read against 204.49/312 = 0.655).

Default shape mirrors the reference's headline benchmark (seq 512, micro-bs
near capacity — their 204.49 TFLOPs number is GPT-175B at mbs 32/seq 512 on
80G A100s, i.e. the largest model the memory takes): gpt2-760m / seq 512 /
mbs 12 / gas 16 / pure-bf16 optimizer state (bf16.master_weights=false) /
selective remat ("dots_with_no_batch_dims_saveable") is the highest-MFU
configuration that fits a single v5e (16G HBM). Override with BENCH_MODEL /
BENCH_SEQ / BENCH_BATCH / BENCH_GAS / BENCH_ZERO / BENCH_REMAT /
BENCH_REMAT_POLICY / BENCH_FLASH / BENCH_SOFTMAX / BENCH_MASTER.

Perf decomposition (r3 xprof, per micro-step of the 760m config):
  forward block scan   ~61 ms  (~153 TF/s on its matmul flops = 78% MXU)
  backward block scan ~153 ms  (2.5x fwd: 2x ideal bwd + saved-dot reload +
                                attention/elementwise recompute)
  head+CE+update       ~39 ms  (head fwd+bwd ~19, Adam update ~13 @ HBM BW,
                                CE the rest)  -> amortized by gas
Measured lever ladder on this chip (760m/mbs12/seq512, best of runs):
  fp32 master + full remat (r2 default)            MFU 0.509
  bf16-only state + full remat                      MFU 0.513
  bf16-only state + dots_with_no_batch_dims, gas=1  MFU 0.551
  same, gas=8 / gas=16 (update amortized)           MFU 0.568 / 0.572
Rejected empirically: flash kernel at seq 512 (0.44 — XLA attention wins
below ~2k), saving attention probs (0.499 — HBM reload beats recompute),
dots_saveable (0.514), mbs 16/24 (~0.54), gpt2-1.3b at any fitting config
(<=0.50: fp32-anything OOMs, and bf16 full-remat loses the remat tax).
fp32-master ceiling on 16G HBM: 0.492 (dots policy, gas=1; gas>=2 OOMs on
fp32 grad accumulators) — the pure-bf16 state IS the TPU-native config at
this HBM:flops ratio; both numbers are honest, the headline uses bf16 state.
Remaining gap to the ~120 TF practical matmul ceiling (61% of nominal) is
backward-scan slice/stash traffic + attention recompute — memory-bound at
197TF:819GB/s, not schedulable away at seq 512.
"""

import json
import os
import sys
import time

import numpy as np


def peak_bf16_tflops():
    """Peak bf16 TFLOPs of the local accelerator generation."""
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    table = {"v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0}
    for key, val in table.items():
        if key in gen:
            return val
    import jax
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    for key, val in table.items():
        if key in kind:
            return val
    return 197.0  # assume v5e


def main():
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt import GPT2_CONFIGS, make_gpt_model

    model_name = os.environ.get("BENCH_MODEL", "gpt2-760m")
    batch = int(os.environ.get("BENCH_BATCH", "12"))
    seq = int(os.environ.get("BENCH_SEQ", "512"))
    gas = int(os.environ.get("BENCH_GAS", "16"))
    # keep measured micro-steps ~constant as gas grows (a gas=16 step is 16
    # micro-steps; 8 outer steps already average 128 of them)
    steps = int(os.environ.get("BENCH_STEPS", str(max(8, 30 // gas))))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))

    import dataclasses
    cfg = GPT2_CONFIGS[model_name]
    use_flash = os.environ.get("BENCH_FLASH", "0") == "1" and seq % 128 == 0
    remat = os.environ.get("BENCH_REMAT", "1") == "1"
    policy = os.environ.get("BENCH_REMAT_POLICY", "dots_with_no_batch_dims_saveable")
    import jax.numpy as _jnp
    sm_dtype = {"fp32": _jnp.float32, "bf16": _jnp.bfloat16}[
        os.environ.get("BENCH_SOFTMAX", "bf16")]
    cfg = dataclasses.replace(cfg, use_flash_attention=use_flash, remat=remat,
                              remat_policy=policy, softmax_dtype=sm_dtype)
    # abstract init: params materialize on-device (engine init_fn path) — the
    # tunneled host->device link (~27 MB/s) makes host-side init impractical
    model = make_gpt_model(cfg=cfg, name=model_name, abstract=True)
    n_chips = jax.device_count()
    master = os.environ.get("BENCH_MASTER", "0") == "1"
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.1}},
        "bf16": {"enabled": True, "master_weights": master},
        "gradient_clipping": 1.0,
        "zero_optimization": {"stage": int(os.environ.get("BENCH_ZERO", "1"))},
        "steps_per_print": 10**9,
    })

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (engine.train_batch_size(), seq + 1)).astype(np.int32)
    # explicit labels keep the model's T == seq (128-multiple → flash kernel path)
    b = {"tokens": tokens[:, :-1], "labels": tokens[:, 1:]}

    loss = None
    for _ in range(warmup):
        loss = engine.train_batch(b)
    # NOTE: on tunneled backends block_until_ready can be a no-op; a scalar
    # device_get is the only reliable completion fence.
    if loss is not None:
        float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(b)
    float(loss)  # sequential state dependency → fences all steps
    dt = time.perf_counter() - t0

    step_time = dt / steps
    samples_per_sec = engine.train_batch_size() / step_time
    samples_per_sec_chip = samples_per_sec / n_chips

    # 6 * N * tokens model flops (no recompute credit); the reference baseline
    # number uses the Megatron factor-8 formula — see module docstring for the
    # convention reconciliation behind vs_baseline.
    n_params = cfg.num_params()
    tokens_per_step = engine.train_batch_size() * seq
    flops_per_step = 6.0 * n_params * tokens_per_step
    tflops_per_chip = flops_per_step / step_time / n_chips / 1e12
    peak = peak_bf16_tflops()
    mfu = tflops_per_chip / peak
    # reference's own formula applied to our run verbatim (azure post :91-93)
    h, l, V = cfg.d_model, cfg.n_layer, cfg.vocab_size
    megatron_flops = (96.0 * engine.train_batch_size() * seq * l * h * h
                      * (1 + seq / (6.0 * h) + V / (16.0 * l * h)))
    mfu_megatron = megatron_flops / step_time / n_chips / 1e12 / peak
    REF_MODEL_FLOPS_MFU = 204.49 * (6.0 / 8.0) / 312.0  # = 0.4916
    vs_baseline = mfu / REF_MODEL_FLOPS_MFU

    print(json.dumps({
        "metric": f"{model_name}_bf16_zero{engine.zero_stage}_train_samples_per_sec_per_chip",
        "value": round(samples_per_sec_chip, 3),
        "unit": "samples/s/chip",
        "vs_baseline": round(vs_baseline, 4),
        "extra": {
            "step_time_ms": round(step_time * 1e3, 2),
            "tflops_per_chip": round(tflops_per_chip, 2),
            "mfu": round(mfu, 4),
            "mfu_megatron": round(mfu_megatron, 4),
            "ref_mfu_model_flops": round(REF_MODEL_FLOPS_MFU, 4),
            "seq_len": seq,
            "global_batch": engine.train_batch_size(),
            "n_chips": n_chips,
            "loss": float(loss),
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
